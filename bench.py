"""Headline benchmark: ALS training throughput on MovieLens-20M-scale data.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Metric: rating-updates/sec/chip during ALS training — n_ratings *
iterations / wall-time of the timed iterations. Warm-up (excluded from
the timed region) covers host binning, device placement, XLA compile,
and one full throwaway training run that forces the compilation; the
timed region is pure device training synced by a scalar readback, with
model materialization (host transfer) after the clock stops. This is the
rebuild's side of BASELINE.md's north star ("ALS on MovieLens-20M at
>=5x Spark-CPU events/sec/chip"): the reference publishes no numbers
(BASELINE.json "published": {}), so vs_baseline is computed against a
1e6 ratings/sec Spark-MLlib-CPU-node proxy — the >=5x target is
therefore vs_baseline >= 5.

Scale knobs via env: PIO_BENCH_USERS/ITEMS/RATINGS/RANK/ITERS.
"""

import json
import os
import time

import numpy as np


def main() -> None:
    n_users = int(os.environ.get("PIO_BENCH_USERS", 138_000))
    n_items = int(os.environ.get("PIO_BENCH_ITEMS", 27_000))
    n_ratings = int(os.environ.get("PIO_BENCH_RATINGS", 20_000_000))
    rank = int(os.environ.get("PIO_BENCH_RANK", 64))
    iterations = int(os.environ.get("PIO_BENCH_ITERS", 5))

    from predictionio_tpu.ops.als import ALSConfig, ALSTrainer

    rng = np.random.default_rng(0)
    # Zipf-ish popularity for items, uniform users — MovieLens-shaped
    uu = rng.integers(0, n_users, size=n_ratings, dtype=np.int64)
    item_pop = rng.zipf(1.2, size=n_ratings) % n_items
    ii = item_pop.astype(np.int64)
    vals = rng.integers(1, 11, size=n_ratings).astype(np.float32) / 2.0

    cfg = ALSConfig(rank=rank, iterations=iterations, reg=0.1, block_size=4096)

    # one-time costs: host binning + device placement + XLA compile
    t0 = time.perf_counter()
    trainer = ALSTrainer((uu, ii, vals), n_users, n_items, cfg)
    trainer.compile()
    warm = time.perf_counter() - t0

    t0 = time.perf_counter()
    trainer.step_n(iterations)     # scalar-pull sync: all device work done
    elapsed = time.perf_counter() - t0
    trainer.factors()              # model materialization, outside the
                                   # timed region (host transfer, one-time)

    # the segmented layout processes every rating on both half-steps
    # (no per-group caps); kept_* stay in the detail block as the
    # honest-accounting invariant (must equal n_ratings)
    effective = (trainer.kept_user_entries + trainer.kept_item_entries) / 2
    value = effective * iterations / elapsed
    baseline_proxy = 1e6  # Spark MLlib ALS CPU-node ratings/sec (see module doc)
    print(json.dumps({
        "metric": "als_ml20m_rating_updates_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "ratings*iters/sec",
        "vs_baseline": round(value / baseline_proxy, 2),
        "detail": {
            "n_users": n_users, "n_items": n_items, "n_ratings": n_ratings,
            "effective_ratings": int(effective),
            "kept_user_frac": round(trainer.kept_user_entries / n_ratings, 3),
            "kept_item_frac": round(trainer.kept_item_entries / n_ratings, 3),
            "rank": rank, "iterations": iterations,
            "elapsed_sec": round(elapsed, 2), "warmup_sec": round(warm, 2),
        },
    }))


if __name__ == "__main__":
    main()
