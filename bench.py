"""Headline benchmark: the full events->model pipeline at MovieLens-20M
scale, ending in ALS training throughput on-chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": {...}}

Unlike a kernel microbench, this drives the framework's own data path —
the `pio train` call stack (SURVEY.md §3.1):

  synth   - structured ratings (latent-factor signal + noise, so the
            RMSE gate below measures real generalization, not luck)
  ingest  - 20M events into the native eventlog via the storage write
            API (columnar bulk path = PEvents.write role; the row path
            insert_batch is sampled separately)
  read    - RecoDataSource.read_training: native columnar scan with
            dict-encoded string ids (HBPEvents.scala:48 role)
  prepare - RecoPreparator: BiMap id indexing over the vocabularies
  bin     - ragged->segmented static blocks + device placement + XLA
            compile + one throwaway run (ALSTrainer.compile)
  train   - the timed region: pure device ALS alternations, synced by a
            scalar readback
  rmse    - model-quality gate on a 5% held-out split: the model must
            beat the global-mean predictor's RMSE by >=15%, so a
            numerically-degraded fast path cannot "win" the benchmark

Headline metric: rating-updates/sec/chip = n_train_ratings * iterations
/ train_sec. ``vs_baseline`` divides by an ASSUMED PROXY of 1e6
ratings*iters/sec for a Spark-MLlib-ALS CPU node — the reference
publishes no benchmark numbers at all (BASELINE.json "published": {});
the proxy is our own stated assumption, recorded in the detail block,
and the >=5x north-star (BASELINE.md) reads as vs_baseline >= 5.
If the RMSE gate fails, value is reported as 0.0.

Scale knobs via env: PIO_BENCH_USERS/ITEMS/RATINGS/RANK/ITERS.
"""

import json
import os
import shutil
import tempfile
import time

import numpy as np


def synthesize(n_users, n_items, n_ratings, rng):
    """Ratings with planted rank-8 structure: clip(3 + 1.2z + noise)."""
    uu = rng.integers(0, n_users, size=n_ratings, dtype=np.int64)
    item_pop = rng.zipf(1.2, size=n_ratings) % n_items  # Zipf popularity
    ii = item_pop.astype(np.int64)
    U = rng.normal(size=(n_users, 8)).astype(np.float32)
    V = rng.normal(size=(n_items, 8)).astype(np.float32)
    z = np.einsum("nk,nk->n", U[uu], V[ii]) / np.sqrt(8.0)
    raw = 3.0 + 1.2 * z + rng.normal(0, 0.35, size=n_ratings).astype(np.float32)
    vals = np.clip(np.round(raw * 2.0) / 2.0, 0.5, 5.0).astype(np.float64)
    return uu, ii, vals


def main() -> None:
    n_users = int(os.environ.get("PIO_BENCH_USERS", 138_493))   # ML-20M
    n_items = int(os.environ.get("PIO_BENCH_ITEMS", 26_744))    # cardinalities
    n_ratings = int(os.environ.get("PIO_BENCH_RATINGS", 20_000_000))
    rank = int(os.environ.get("PIO_BENCH_RANK", 64))
    iterations = int(os.environ.get("PIO_BENCH_ITERS", 5))

    from predictionio_tpu.data.storage import EventColumns, Storage, set_storage
    from predictionio_tpu.ops.als import ALSConfig, ALSTrainer, predict_rmse
    from predictionio_tpu.parallel.mesh import MeshContext
    from predictionio_tpu.templates.recommendation import (
        RecoDataSource,
        RecoDataSourceParams,
        RecoPreparator,
    )

    detail = {"n_users": n_users, "n_items": n_items, "n_ratings": n_ratings,
              "rank": rank, "iterations": iterations}
    rng = np.random.default_rng(0)
    base_dir = tempfile.mkdtemp(prefix="pio_bench_")
    try:
        # -- synth ----------------------------------------------------------
        t0 = time.perf_counter()
        uu, ii, vals = synthesize(n_users, n_items, n_ratings, rng)
        cols = EventColumns(
            entity_codes=uu.astype(np.int32),
            target_codes=ii.astype(np.int32),
            name_codes=np.zeros(n_ratings, np.int32),
            values=vals,
            times_us=np.arange(n_ratings, dtype=np.int64) * 1_000_000,
            entity_vocab=[f"u{i}" for i in range(n_users)],
            target_vocab=[f"i{i}" for i in range(n_items)],
            names=["rate"],
        )
        detail["synth_sec"] = round(time.perf_counter() - t0, 2)

        # -- ingest (storage write path, native eventlog) -------------------
        storage = Storage.from_env({
            "PIO_STORAGE_SOURCES_EL_TYPE": "eventlog",
            "PIO_STORAGE_SOURCES_EL_PATH": base_dir,
            **{f"PIO_STORAGE_REPOSITORIES_{r}_{k}": v
               for r in ("METADATA", "EVENTDATA", "MODELDATA")
               for k, v in (("NAME", r.lower()), ("SOURCE", "EL"))},
        })
        set_storage(storage)
        app = storage.apps().insert("bench")
        storage.events().init(app.id)

        t0 = time.perf_counter()
        storage.events().insert_columnar(
            cols, app.id, entity_type="user", target_entity_type="item",
            value_property="rating",
        )
        ingest_sec = time.perf_counter() - t0
        detail["ingest_sec"] = round(ingest_sec, 2)
        detail["ingest_events_per_sec"] = round(n_ratings / ingest_sec, 1)

        # row-path write rate, sampled (the per-request API the event
        # server uses; full 20M through Python Event objects would add
        # ~10 min of pure object churn to every bench run)
        sample = min(100_000, n_ratings)
        from predictionio_tpu.data.event import Event
        import datetime as dt

        t0 = time.perf_counter()
        epoch = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
        events = [
            Event(event="rate", entity_type="user", entity_id=f"u{uu[k]}",
                  target_entity_type="item", target_entity_id=f"i{ii[k]}",
                  properties={"rating": float(vals[k])},
                  event_time=epoch + dt.timedelta(seconds=int(k)))
            for k in range(sample)
        ]
        storage.events().insert_batch(events, app.id)
        detail["insert_batch_events_per_sec"] = round(
            sample / (time.perf_counter() - t0), 1
        )
        extra_rows = sample  # the sampled rows are real events in the log

        # -- read (the DataSource the recommendation template ships) --------
        ctx = MeshContext()
        ds = RecoDataSource(RecoDataSourceParams(app_name="bench"))
        t0 = time.perf_counter()
        td = ds.read_training(ctx)
        read_sec = time.perf_counter() - t0
        detail["read_sec"] = round(read_sec, 2)
        n_read = len(td.columns.ratings)
        assert n_read == n_ratings + extra_rows, (n_read, n_ratings, extra_rows)

        # -- prepare (BiMap string-id indexing) ------------------------------
        t0 = time.perf_counter()
        pd = RecoPreparator(None).prepare(ctx, td)
        detail["prepare_sec"] = round(time.perf_counter() - t0, 2)

        # -- held-out split for the quality gate -----------------------------
        hold = np.arange(n_read) % 20 == 0   # 5%
        tr_u, tr_i, tr_r = pd.user_idx[~hold], pd.item_idx[~hold], pd.ratings[~hold]
        ho = (pd.user_idx[hold], pd.item_idx[hold], pd.ratings[hold])
        n_train = len(tr_r)

        # -- bin + place + compile (one-time costs) --------------------------
        cfg = ALSConfig(rank=rank, iterations=iterations, reg=0.05,
                        block_size=4096)
        t0 = time.perf_counter()
        trainer = ALSTrainer((tr_u, tr_i, tr_r), len(pd.user_ids),
                             len(pd.item_ids), cfg)
        trainer.compile()
        detail["bin_compile_sec"] = round(time.perf_counter() - t0, 2)

        # -- train (timed region: pure device work) --------------------------
        t0 = time.perf_counter()
        trainer.step_n(iterations)
        train_sec = time.perf_counter() - t0
        factors = trainer.factors()
        detail["train_sec"] = round(train_sec, 2)

        # -- quality gate -----------------------------------------------------
        rmse = predict_rmse(factors, ho)
        base_rmse = float(np.sqrt(np.mean((ho[2] - tr_r.mean()) ** 2)))
        gate = rmse <= 0.85 * base_rmse
        detail["rmse_heldout"] = round(rmse, 4)
        detail["rmse_global_mean_baseline"] = round(base_rmse, 4)
        detail["rmse_gate_passed"] = bool(gate)

        # -- headline + honest accounting ------------------------------------
        effective = (trainer.kept_user_entries + trainer.kept_item_entries) / 2
        assert int(effective) == n_train, (effective, n_train)
        value = effective * iterations / train_sec if gate else 0.0
        e2e_sec = read_sec + detail["prepare_sec"] + detail["bin_compile_sec"] + train_sec
        detail["events_to_model_sec"] = round(e2e_sec, 2)
        detail["events_to_model_events_per_sec"] = round(n_read / e2e_sec, 1)
        detail["baseline_proxy"] = {
            "value": 1e6,
            "unit": "ratings*iters/sec",
            "basis": ("ASSUMED Spark-MLlib-ALS CPU-node throughput; the "
                      "reference publishes no numbers (BASELINE.json "
                      "published={}) — this proxy is our own stated "
                      "assumption, not a citation"),
        }
        print(json.dumps({
            "metric": "als_ml20m_rating_updates_per_sec_per_chip",
            "value": round(value, 1),
            "unit": "ratings*iters/sec",
            "vs_baseline": round(value / 1e6, 2),
            "detail": detail,
        }))
    finally:
        set_storage(None)
        shutil.rmtree(base_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
