"""Headline benchmark: the full events->model->serving pipeline at
MovieLens-20M scale on one chip.

Prints ONE COMPACT JSON line (< MAX_HEADLINE_BYTES — the driver only
captures a ~2KB stdout tail, BENCH_r04 lesson):
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
   "gates": {...}, "key": {...}, "detail_file": "BENCH_DETAIL.json"}
The full detail blob (histograms, per-run arrays, roofline trace) is
written to BENCH_DETAIL.json beside this file and committed.

Unlike a kernel microbench, this drives the framework's own data path —
the `pio train` call stack (SURVEY.md §3.1) — TWICE, in two fresh
processes sharing one on-disk store and one persistent compilation
cache, so both halves of the compile story are measured:

  cold stage (fresh cache):
    synth   - structured ratings (latent-factor signal + noise, so the
              RMSE gates below measure real generalization, not luck)
    ingest  - 20M events into the native eventlog via the storage write
              API (columnar bulk path = PEvents.write role). The live
              row lane — raw API-format JSON array bytes through the
              native encoder (insert_json_batch, the POST
              /batch/events.json path) — is sampled separately with a
              hard gate: row_lane_events_per_sec >= 50k or the
              headline is zeroed. The FSYNC=1 (SYNC_WAL durability)
              lane and the legacy Event-object fallback are reported
              alongside.
    read    - RecoDataSource.read_training: native columnar scan
    prepare - RecoPreparator: BiMap id indexing
    bin     - ragged->segmented static blocks + device placement
    compile - XLA compile + one throwaway run (cache MISS: the full
              compile tax, persisted to the cache for the warm stage)
    train   - the timed region: pure device ALS alternations, synced by
              a scalar readback
    rmse    - quality gates on a 5% held-out split: beat the
              global-mean predictor by >=15% AND (at default knobs)
              land inside the absolute band for this fixed generator —
              a silent half-regression in solve quality zeroes the
              headline, not just total breakage
    serve   - the trained model is persisted through the models repo,
              deployed via the REAL EngineServer (prepare_deploy +
              warm-up), and driven over HTTP POST /queries.json:
              sequential p50/p99 + concurrent throughput, then a
              SATURATING stage: 32 keep-alive connections, p50/p99/qps
              with zero errors tolerated and the MicroBatcher's
              dispatch-size histogram recorded (batches > 1 must form).
              Gates: sequential p50 < 10 ms (BASELINE.json north-star)
              AND 32-conn p99 < 25 ms with real batching, or the
              headline is zeroed.

  warm stage (fresh process, same cache): read -> prepare -> bin ->
    compile -> train again. Compile becomes a disk-cache HIT; this is
    what every repeat train / deploy warm-up / /reload pays in
    production.

  twotower stage (fresh process): the stretch neural model at catalog
    scale (1M users/items, dim 128, batch 8192) — steady-state step
    time, a loss-learning gate, and a MEASURED MFU: analytic matmul
    FLOPs over xplane-traced device time vs the public bf16 peak
    (VERDICT r4 item 5). A failed loss gate zeroes the headline.

  retrieval stage (fresh process): candidate generation over the
    trained item factors (predictionio_tpu/index) — brute force vs the
    exact index vs an IVF nprobe sweep, queries/s at MEASURED recall
    vs brute force; ``key.retrieval_qps_recall95`` is the fastest arm
    clearing recall >= 0.95 and ``key.index_build_sec`` its build
    cost (detail.retrieval carries the full sweep).

  prof stage (host-only, runs early): the continuous profiler's cost
    and the first serve-path interpreter breakdown — an in-process
    event server under threaded HTTP load with the always-on sampler
    retained; ``key.prof_overhead_pct`` is the gated number
    (lower-better) and detail.prof_serve_breakdown the
    parse/json/socket/dispatch shares.

  stream stage: see stage_stream (runs LAST — it appends events).

Roofline: analytic FLOP/byte counts from the trainer's actual padded
device shapes (ALSTrainer.work_model — documented under-estimate of
bytes) against TPU v5e public peaks, recorded so the headline number is
grounded in what the chip can do: the train region is expected near the
HBM roof (gather-bound), which is also why the fused Pallas gather
kernel lost to XLA and was removed (ops/als.py measurement note).

Headline metric: rating-updates/sec/chip = n_train_ratings * iterations
/ train_sec (cold stage). ``vs_baseline`` divides by an ASSUMED PROXY
of 1e6 ratings*iters/sec for a Spark-MLlib-ALS CPU node — the reference
publishes no benchmark numbers at all (BASELINE.json "published": {});
the proxy is our own stated assumption, recorded in the detail file,
and the >=5x north-star (BASELINE.md) reads as vs_baseline >= 5.
If ANY gate fails (relative RMSE, absolute RMSE band, serving p50,
32-conn p99 + batching, row-lane >= 50k ev/s), value is reported as
0.0 with the gate flags telling which.

Scale knobs via env: PIO_BENCH_USERS/ITEMS/RATINGS/RANK/ITERS (the
absolute RMSE band only applies at the default knobs).

Telemetry (obs/): the measurements this script reports map onto the
framework's metric names, so a dashboard and a bench run agree on
vocabulary — serving latency is `pio_serving_request_seconds{engine=}`
(the engine server records it for every driven query), ingest and
device-transfer byte counts are `pio_transfer_bytes_total{direction=}`,
train-stage wall times are `pio_train_seconds{engine=}` /
`pio_train_step_seconds`, and the cold-vs-warm compile story is
`pio_jax_compile_cache_total{result=}` + `pio_jax_compile_seconds{phase=}`.
All are live in-process during a run (`bin/pio metrics` dumps them; the
serve stage's server also exposes `GET /metrics` over HTTP).
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

import numpy as np

# the chip peaks live in ONE place (obs/perfacct.py — the live
# pio_train_mfu gauge divides by the same numbers, so a bench capture
# and a production dashboard can never disagree on the denominator);
# perfacct imports no jax at module level, so the orchestrating parent
# stays chip-free
from predictionio_tpu.obs.perfacct import (  # noqa: E402
    PEAK_BF16_FLOPS as V5E_PEAK_BF16_FLOPS,
    PEAK_HBM_BYTES as V5E_PEAK_HBM_BYTES,
)

DEFAULT_KNOBS = (138_493, 26_744, 20_000_000, 64, 5)  # ML-20M + rank/iters
# absolute held-out RMSE band for the DEFAULT synthetic generator at the
# default knobs (measured 0.427 across rounds; the band catches silent
# solve-quality regressions that still beat the trivial 15% gate)
RMSE_BAND = (0.38, 0.48)


def knobs():
    return (
        int(os.environ.get("PIO_BENCH_USERS", DEFAULT_KNOBS[0])),
        int(os.environ.get("PIO_BENCH_ITEMS", DEFAULT_KNOBS[1])),
        int(os.environ.get("PIO_BENCH_RATINGS", DEFAULT_KNOBS[2])),
        int(os.environ.get("PIO_BENCH_RANK", DEFAULT_KNOBS[3])),
        int(os.environ.get("PIO_BENCH_ITERS", DEFAULT_KNOBS[4])),
    )


def synthesize(n_users, n_items, n_ratings, rng):
    """Ratings with planted rank-8 structure: clip(3 + 1.2z + noise)."""
    uu = rng.integers(0, n_users, size=n_ratings, dtype=np.int64)
    item_pop = rng.zipf(1.2, size=n_ratings) % n_items  # Zipf popularity
    ii = item_pop.astype(np.int64)
    U = rng.normal(size=(n_users, 8)).astype(np.float32)
    V = rng.normal(size=(n_items, 8)).astype(np.float32)
    z = np.einsum("nk,nk->n", U[uu], V[ii]) / np.sqrt(8.0)
    raw = 3.0 + 1.2 * z + rng.normal(0, 0.35, size=n_ratings).astype(np.float32)
    vals = np.clip(np.round(raw * 2.0) / 2.0, 0.5, 5.0).astype(np.float64)
    return uu, ii, vals


def _storage(base_dir):
    from predictionio_tpu.data.storage import Storage, set_storage

    st = Storage.from_env({
        "PIO_STORAGE_SOURCES_EL_TYPE": "eventlog",
        "PIO_STORAGE_SOURCES_EL_PATH": base_dir,
        **{f"PIO_STORAGE_REPOSITORIES_{r}_{k}": v
           for r in ("METADATA", "EVENTDATA", "MODELDATA")
           for k, v in (("NAME", r.lower()), ("SOURCE", "EL"))},
    })
    set_storage(st)
    return st


def _bench_cfg():
    from predictionio_tpu.ops.als import ALSConfig

    _, _, _, rank, iterations = knobs()
    return ALSConfig(rank=rank, iterations=iterations, reg=0.05,
                     block_size=4096)


#: bench derivation tag for the binned-layout cache: the 5% holdout
#: split below reshapes the COO, so the key must differ from the
#: template's full-data key
_HOLD_TAG = "|hold5pct"

#: generous bound on the transfer-watcher join: the worst observed
#: driver weather moved ~220 MB at ~2 MB/s (~110 s); 1800 s only fires
#: on a genuine wire hang, which must become a diagnosable error rather
#: than a silently wedged bench process
TRANSFER_JOIN_TIMEOUT_SEC = 1800.0


def _transfer_and_compile(detail, trainer, iterations, n_read):
    """Shared tail of both stages: transfer and compile OVERLAPPED
    (VERDICT r4 item 3 — warm cost should be ~max(transfer, bin+
    compile), not their sum). Device puts are async and started back in
    the constructor; here the host's XLA trace+compile runs WHILE the
    bytes are still crossing the tunnel (compilation needs only
    shapes), a watcher thread timestamps wire completion, and the
    warm-up run then blocks on whichever finishes last. Honest
    attribution survives the overlap: transfer_sec is measured from
    the FIRST put dispatch (trainer.put_start) to wire completion, so
    bytes/MB-s still read as bandwidth and tunnel VARIANCE never
    masquerades as a pipeline regression (VERDICT r3 weak #2)."""
    import threading

    t_enter = time.perf_counter()
    wire = {}
    comp = {}

    def watch():
        try:
            wire["dones"] = trainer.wait_device_timed()
        except Exception as e:  # noqa: BLE001 — surfaced after join
            wire["error"] = e

    def compile_run():
        # on its own thread: compile()'s warm-up ends in a blocking
        # scalar pull on the SAME arrays still crossing the wire, so a
        # genuine tunnel hang would wedge the main thread before any
        # join-with-timeout ran — the deadline below must cover BOTH
        # sides of the overlap to ever fire (r6 advisor finding)
        try:
            trainer.compile()
        except Exception as e:  # noqa: BLE001 — surfaced after join
            comp["error"] = e

    th = threading.Thread(target=watch, daemon=True)
    tc = threading.Thread(target=compile_run, daemon=True)
    th.start()
    tc.start()   # host compile overlaps the transfer
    deadline = t_enter + TRANSFER_JOIN_TIMEOUT_SEC
    for t in (th, tc):
        t.join(timeout=max(0.0, deadline - time.perf_counter()))
    if th.is_alive() or tc.is_alive():
        pending = [side for side, t in (("wire (async puts never "
                                         "completed)", th),
                                        ("compile+warmup (blocks on the "
                                         "transferred data)", tc))
                   if t.is_alive()]
        # a side that DIED with an error is often the root cause of the
        # other side's hang (a dropped tunnel fails the watcher fast,
        # then the warm-up waits forever on data that will never land):
        # surface it in the same message
        died = "; ".join(
            f"{side} already failed: {d['error']!r}"
            for side, d in (("wire", wire), ("compile", comp))
            if "error" in d)
        raise RuntimeError(
            "transfer/compile overlap still pending after "
            f"{TRANSFER_JOIN_TIMEOUT_SEC:.0f}s — side(s): "
            + "; ".join(pending) + (f" [{died}]" if died else ""))
    if "error" in comp:
        raise RuntimeError("host compile failed") from comp["error"]
    if "error" in wire:
        raise RuntimeError("device transfer failed") from wire["error"]
    overlap_wall = time.perf_counter() - t_enter
    transfer_sec = wire["dones"][-1] - trainer.put_start
    detail["transfer_sec"] = round(transfer_sec, 2)
    detail["transfer_bytes"] = int(trainer.transfer_bytes)
    detail["transfer_mb_per_sec"] = round(
        trainer.transfer_bytes / max(transfer_sec, 1e-9) / 1e6, 1)
    # pure-wire bandwidth: the LAST side's dispatch-done -> completion
    # span contains no host work (binning/compile done dispatching), so
    # a binning regression can never masquerade as a bandwidth drop
    tail_t0, tail_bytes = trainer._put_log[-1]
    tail_sec = max(wire["dones"][-1] - tail_t0, 1e-9)
    detail["transfer_tail_mb_per_sec"] = round(tail_bytes / tail_sec / 1e6, 1)
    detail["compile_host_sec"] = round(trainer.compile_host_sec, 2)
    detail["compile_warmup_sec"] = round(trainer.compile_run_sec, 2)
    detail["compile_sec"] = round(
        trainer.compile_host_sec + trainer.compile_run_sec, 2)
    detail["overlap_note"] = (
        "transfer/compile run CONCURRENTLY (r5): transfer_sec is the "
        "wall window from first put dispatch (overlaps binning + host "
        "compile) — transfer_tail_mb_per_sec is the pure-wire "
        "bandwidth signal; compile_warmup_sec includes any residual "
        "data wait; the stage's wall cost is bin_compile_sec")
    # continuity with BENCH_r01/r02 (one one-time-costs number): now
    # bin + the OVERLAPPED wall, which is the point of the pipeline
    detail["bin_compile_sec"] = round(detail["bin_sec"] + overlap_wall, 2)
    t0 = time.perf_counter()
    trainer.step_n(iterations)
    train_sec = time.perf_counter() - t0
    detail["train_sec"] = round(train_sec, 2)
    detail["events_to_model_sec"] = round(
        detail["read_sec"] + detail["prepare_sec"]
        + detail["bin_compile_sec"] + train_sec, 2
    )
    detail["events_to_model_events_per_sec"] = round(
        n_read / detail["events_to_model_sec"], 1
    )
    return train_sec


def _read_prepare_bin_train(detail, n_expected):
    """The shared events->model path (both stages): returns everything
    the caller needs for quality gates / serving — (trainer, pd, ho,
    train_stats, cfg, train_sec) where train_stats = {"n_train",
    "train_mean"} (the COO itself no longer materializes on the
    zero-copy lane).

    Cold lane (PIO_BENCH_BINNED=0 restores the legacy path): the
    fused native scan+bin call (store.bin_columnar) replaces
    read_training -> prepare -> ALSTrainer binning — one pass off the
    mmap'd log straight into the device-ready compressed layout, with
    the 5%% holdout split applied natively. read_sec is the native
    scan share, bin_sec the resolve+plan+fill share plus the (async)
    put dispatch."""
    from predictionio_tpu.data.bimap import BiMap
    from predictionio_tpu.ops import bincache
    from predictionio_tpu.ops.als import (ALSTrainer, als_row_cost_slots,
                                          layout_cache_key,
                                          side_layout_from_binned)
    from predictionio_tpu.parallel.mesh import MeshContext
    from predictionio_tpu.templates.recommendation import (
        RecoDataSource,
        RecoDataSourceParams,
        RecoPreparator,
    )

    _, _, _, rank, iterations = knobs()
    ctx = MeshContext()
    # binned=False: the bench drives the two lanes EXPLICITLY (the
    # engine-path plumbing is exercised by tier-1; here each stage is
    # timed by hand), so the fallback read must stay columnar
    ds = RecoDataSource(RecoDataSourceParams(app_name="bench",
                                             binned=False))
    cfg = _bench_cfg()
    binned_lane = (os.environ.get("PIO_BENCH_BINNED", "1") != "0"
                   and ds._binned_supported())
    detail["zero_copy_lane"] = bool(binned_lane)
    if binned_lane:
        from predictionio_tpu.data import store as dstore
        from predictionio_tpu.models.als import PreparedRatings

        fp = ds.data_fingerprint()
        t0 = time.perf_counter()
        binned = dstore.bin_columnar(
            "bench", value_property="rating", overrides={"buy": 4.0},
            entity_type="user", event_names=["rate", "buy"],
            target_entity_type="item",
            skip_mod=20, skip_rem=0,            # the 5% holdout split
            seg_len=cfg.seg_len, block_size=cfg.block_size,
            row_cost_slots=als_row_cost_slots(cfg.rank))
        t1 = time.perf_counter()
        n_hold = 0 if binned.holdout is None else len(binned.holdout[0])
        assert binned.n_rows + n_hold == n_expected, (
            binned.n_rows, n_hold, n_expected)
        detail["read_sec"] = round(binned.scan_sec, 2)
        detail["prepare_sec"] = 0.0   # dict-encode fused into the scan
        user_side = side_layout_from_binned(binned.user_side)
        item_side = side_layout_from_binned(binned.item_side)
        trainer = ALSTrainer.from_sides(
            user_side, item_side, len(binned.entity_vocab),
            len(binned.target_vocab), binned.n_rows, cfg)
        # everything that is not the scan is the bin stage (native
        # resolve+plan+fill + vocab decode + async put dispatch)
        detail["bin_sec"] = round(
            (t1 - t0 - binned.scan_sec)
            + (time.perf_counter() - t1), 2)
        detail["bin_cache_hit"] = False
        if fp is not None:
            # persist under the SAME key the warm stage loads
            arrays = {**user_side.to_arrays("u_"),
                      **item_side.to_arrays("i_")}
            bincache.save(
                layout_cache_key(fp + _HOLD_TAG, cfg, 1), arrays, {
                    "n_users": len(binned.entity_vocab),
                    "n_items": len(binned.target_vocab),
                    "n_shards": 1, "total_entries": binned.n_rows,
                    **user_side.meta("u_"), **item_side.meta("i_"),
                })
        pd = PreparedRatings(
            user_ids=BiMap.from_vocab(binned.entity_vocab),
            item_ids=BiMap.from_vocab(binned.target_vocab),
            fingerprint=fp)
        ho = binned.holdout
        train_stats = {
            "n_train": binned.n_rows,
            "train_mean": (binned.user_side.kept_value_sum
                           / max(1, binned.user_side.kept_entries)),
        }
        train_sec = _transfer_and_compile(detail, trainer, iterations,
                                          n_expected)
        return trainer, pd, ho, train_stats, cfg, train_sec

    t0 = time.perf_counter()
    td = ds.read_training(ctx)
    read_sec = time.perf_counter() - t0
    detail["read_sec"] = round(read_sec, 2)
    n_read = len(td.columns.ratings)
    assert n_read == n_expected, (n_read, n_expected)

    t0 = time.perf_counter()
    pd = RecoPreparator(None).prepare(ctx, td)
    detail["prepare_sec"] = round(time.perf_counter() - t0, 2)

    hold = np.arange(n_read) % 20 == 0   # 5% held out
    tr_u, tr_i, tr_r = pd.user_idx[~hold], pd.item_idx[~hold], pd.ratings[~hold]
    ho = (pd.user_idx[hold], pd.item_idx[hold], pd.ratings[hold])

    cache_key = (pd.fingerprint + _HOLD_TAG) if pd.fingerprint else None
    t0 = time.perf_counter()
    trainer = ALSTrainer((tr_u, tr_i, tr_r), len(pd.user_ids),
                         len(pd.item_ids), cfg, cache_key=cache_key)
    detail["bin_sec"] = round(time.perf_counter() - t0, 2)
    detail["bin_cache_hit"] = bool(trainer.cache_hit)
    train_stats = {"n_train": len(tr_r), "train_mean": float(tr_r.mean())}
    train_sec = _transfer_and_compile(detail, trainer, iterations, n_read)
    return trainer, pd, ho, train_stats, cfg, train_sec


def _parse_train_profile(profile_dir):
    """Parse a profiled run's xplane trace into MEASURED occupancy
    numbers (VERDICT r3 item 4): per-HLO-category device time, XLA
    cost-model flops, and bytes split by memory space. The decoding now
    lives in the framework itself (obs/profiler.py — shared with
    workflow/train.py's post-train breakdown and `pio profile`
    artifacts); this stage keeps the subprocess boundary (tensorflow's
    proto stack must not share the bench process) and prints ONE JSON
    line."""
    from predictionio_tpu.obs.profiler import parse_xplane

    print(json.dumps(parse_xplane(profile_dir)))


def _step_device_breakdown(trace, steps):
    """detail.* per-step device-time breakdown from a parsed trace that
    covered ``steps`` steps — so future BENCH_r*.json carry where each
    step's device time went, not just its total. Delegates to the one
    shared implementation (obs/profiler.per_step), so bench captures
    and workflow/train.py logs can never disagree on the same trace."""
    from predictionio_tpu.obs.profiler import per_step

    return per_step(trace, steps)


def _roofline(trainer, train_sec, iterations):
    wm = trainer.work_model()
    achieved_flops = wm["flops_per_iter"] * iterations / train_sec
    achieved_bytes = wm["hbm_bytes_per_iter"] * iterations / train_sec
    return {
        "model": ("analytic counts from actual padded device shapes "
                  "(ALSTrainer.work_model); bytes are a documented "
                  "UNDER-estimate, so hbm fraction is a lower bound"),
        "flops_per_iter": wm["flops_per_iter"],
        "hbm_bytes_per_iter": wm["hbm_bytes_per_iter"],
        "achieved_tflops": round(achieved_flops / 1e12, 2),
        "achieved_hbm_gb_per_sec": round(achieved_bytes / 1e9, 1),
        "peak_bf16_tflops": V5E_PEAK_BF16_FLOPS / 1e12,
        "peak_hbm_gb_per_sec": V5E_PEAK_HBM_BYTES / 1e9,
        "mxu_fraction": round(achieved_flops / V5E_PEAK_BF16_FLOPS, 3),
        "hbm_fraction": round(achieved_bytes / V5E_PEAK_HBM_BYTES, 3),
    }


def _pct(sorted_vals, q):
    """Percentile by index over an already-sorted sample (shared by the
    serve and fleet stages — their quantile arithmetic must agree)."""
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(len(sorted_vals) * q))]


def _run_loadgen(port, users_file, threads, per_thread, on_warmup=None):
    """One out-of-process loadgen run against ``port`` (the separate
    process keeps the clients' CPU off the server's GIL/tail): returns
    the parsed result dict, asserting a clean exit and zero errors.
    Shared by the serve and fleet sweeps — the invocation protocol and
    output parsing must not drift between them.

    ``on_warmup`` runs in THIS process at the loadgen's WARMUP_DONE
    marker — the instant every connection's warm-up requests have
    finished and the timed region begins. The fleet sweep snapshots
    per-replica request counters there to exclude warm-up traffic from
    server-side percentiles exactly (warm-ups strictly precede the
    marker; any timed request racing the snapshot only shrinks the
    measured window, it can never let a warm-up in)."""
    argv = [sys.executable, os.path.abspath(__file__),
            "--stage", "loadgen",
            "--base", json.dumps({
                "port": port, "users_file": users_file,
                "threads": threads, "per_thread": per_thread})]
    if on_warmup is None:
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=600)
        returncode, stdout, stderr = (proc.returncode, proc.stdout,
                                      proc.stderr)
    else:
        proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True)
        head = []
        # bounded by the loadgen's own internal deadlines (barrier
        # abort at 120s, worker joins at 540s): it always reaches EOF
        for line in proc.stdout:
            head.append(line)
            if line.strip() == "WARMUP_DONE":
                on_warmup()
                break
        rest, stderr = proc.communicate(timeout=600)
        returncode, stdout = proc.returncode, "".join(head) + rest
    lines = [l for l in stdout.splitlines() if l.startswith("{")]
    assert returncode == 0 and lines, (
        returncode, stdout[-500:], stderr[-500:])
    load = json.loads(lines[-1])
    assert load["errors"] == 0, load
    return load


def _serve_stage(storage, factors, pd, cfg, detail):
    """Persist the trained model through the models repo, deploy it via
    the REAL EngineServer (prepare_deploy + warm-up), and measure the
    live HTTP route (ref: CreateServer.scala:552-559 serving path)."""
    import datetime as dt
    import http.client
    import pickle
    import threading
    import uuid

    from predictionio_tpu.core.params import EngineParams
    from predictionio_tpu.data.metadata import EngineInstance, Model
    from predictionio_tpu.models.als import ALSModel, ALSParams
    from predictionio_tpu.serving.engine_server import EngineServer
    from predictionio_tpu.templates.recommendation import (
        RecoDataSourceParams,
        recommendation_engine,
    )

    engine = recommendation_engine()
    ep = EngineParams(
        data_source_params=("", RecoDataSourceParams(app_name="bench")),
        preparator_params=("", None),
        algorithm_params_list=[("als", ALSParams(
            rank=cfg.rank, num_iterations=cfg.iterations, lambda_=cfg.reg))],
        serving_params=("", None),
    )
    ep_json = ep.to_json_dict()
    now = dt.datetime.now(tz=dt.timezone.utc)
    instance = EngineInstance(
        id=uuid.uuid4().hex, status="COMPLETED", start_time=now, end_time=now,
        engine_id="bench_reco", engine_version="0", engine_variant="default",
        engine_factory="bench", batch="bench",
        data_source_params=json.dumps(ep_json["dataSourceParams"]),
        preparator_params=json.dumps(ep_json["preparatorParams"]),
        algorithms_params=json.dumps(ep_json["algorithmParamsList"]),
        serving_params=json.dumps(ep_json["servingParams"]),
    )
    storage.engine_instances().insert(instance)
    model = ALSModel(factors, pd.user_ids, pd.item_ids)
    storage.models().insert(Model(id=instance.id, models=pickle.dumps([model])))

    server = EngineServer(
        engine, "bench_reco", host="127.0.0.1", port=0, storage=storage,
    ).start()
    try:
        rng = np.random.default_rng(7)
        inv = pd.user_ids.inverse()
        users = [inv[int(u)]
                 for u in rng.integers(0, len(pd.user_ids), size=512)]

        import socket

        def connect():
            c = http.client.HTTPConnection("127.0.0.1", server.port,
                                           timeout=60)
            c.connect()
            # what every production HTTP client (curl, urllib3) does;
            # stdlib http.client leaves Nagle on
            c.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return c

        def one(conn, user):
            body = json.dumps({"user": user, "num": 10})
            conn.request("POST", "/queries.json", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
            assert resp.status == 200 and b"itemScores" in data, data[:200]

        conn = connect()
        for u in users[:16]:            # settle connection + code paths
            one(conn, u)
        laps = []
        for u in users[16:376]:         # 360 timed sequential requests
            t0 = time.perf_counter()
            one(conn, u)
            laps.append(time.perf_counter() - t0)
        conn.close()
        laps.sort()
        p50 = laps[len(laps) // 2]
        p99 = laps[int(len(laps) * 0.99)]

        # concurrent throughput: 4 keep-alive connections
        n_threads, per_thread = 4, 120
        errs = []

        def worker(tid):
            try:
                c = connect()
                for j in range(per_thread):  # graftlint: disable=JT09 — except below hands the error to errs[]; the stage fails loudly on it
                    one(c, users[(tid * per_thread + j) % len(users)])
                c.close()
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        # daemon: a wedged worker must not block interpreter shutdown
        # after the bounded join already failed the stage loudly
        threads = [threading.Thread(target=worker, args=(t,), daemon=True)
                   for t in range(n_threads)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            # bounded join (JT12): a wedged worker must fail the stage
            # loudly, not hang the whole bench run
            t.join(timeout=600)
            assert not t.is_alive(), "serve worker wedged past 600s"
        wall = time.perf_counter() - t0
        assert not errs, errs[0]

        detail["serve_p50_ms"] = round(p50 * 1e3, 2)
        detail["serve_p99_ms"] = round(p99 * 1e3, 2)
        detail["serve_qps"] = round(n_threads * per_thread / wall, 1)
        detail["serve_gate_passed"] = bool(p50 * 1e3 < 10.0)  # BASELINE north-star

        # device-memory ledger snapshot WHILE the deployment is live:
        # the served model (+ its retrieval index) registers weakly, so
        # sampling after server.stop()/GC would read an empty ledger
        # and key.model_hbm_bytes would gate nothing (review finding)
        from predictionio_tpu.obs import memacct

        mem = memacct.report()
        detail["memacct"] = {"models": mem["models"],
                             "basis": mem["basis"]}
        detail["model_hbm_bytes"] = int(mem["total_model_bytes"])

        # saturating CONCURRENCY SWEEP (VERDICT r3 item 6 + r4 item 5):
        # 1/8/32/128 keep-alive connections hammering /queries.json —
        # per-request client latencies, the server-side serving time,
        # and its queue-wait vs model-dispatch SPLIT per point (where
        # does p50 cross 10 ms, and is it queueing or device work?).
        # The load generator runs in a SEPARATE process: in-process
        # client threads would share the server's GIL and bill the
        # clients' own CPU to the server's tail. The 32-conn point
        # keeps the r3/r4 gate (server-side p99 < 25 ms with real
        # batches forming) and runs min-of-2 — the single-vCPU bench
        # host has CPU-steal weather; other points run once.
        import tempfile as _tf

        with _tf.NamedTemporaryFile("w", suffix=".json", delete=False) as uf:
            json.dump(users, uf)
            users_file = uf.name

        def load_point(conns, per_thread):
            count_before = server.stats.request_count
            load = _run_loadgen(server.port, users_file, conns,
                                per_thread)
            n_timed = conns * per_thread
            assert server.stats.request_count - count_before >= n_timed
            srv_lat = sorted(server.stats.recent(n_timed))
            load["srv_p50_ms"] = round(_pct(srv_lat, 0.5) * 1e3, 2)
            load["srv_p99_ms"] = round(_pct(srv_lat, 0.99) * 1e3, 2)
            if server._batcher is not None:
                splits = server._batcher.recent_splits(n_timed)
                waits = sorted(s[0] for s in splits)
                disp = sorted(s[1] for s in splits)
                load["srv_queue_p50_ms"] = round(_pct(waits, 0.5) * 1e3, 2)
                load["srv_queue_p99_ms"] = round(_pct(waits, 0.99) * 1e3, 2)
                load["srv_dispatch_p50_ms"] = round(_pct(disp, 0.5) * 1e3, 2)
                load["srv_dispatch_p99_ms"] = round(_pct(disp, 0.99) * 1e3, 2)
            return load

        sweep = []
        runs = []
        stage_hist = {}
        try:
            for conns in (1, 8, 32, 128):
                per_thread = max(40, 4800 // conns)
                if conns == 32:
                    # gate point: snapshot the histogram around it so
                    # the batching evidence is this point's own
                    hist_before = (
                        server._batcher.histogram()["batchSizeHistogram"]
                        if server._batcher else {})
                    for _ in range(2):           # min-of-2 (gate)
                        runs.append(load_point(conns, per_thread))
                    hist_after = (
                        server._batcher.histogram()["batchSizeHistogram"]
                        if server._batcher else {})
                    stage_hist = {
                        k: hist_after.get(k, 0) - hist_before.get(k, 0)
                        for k in hist_after
                        if hist_after.get(k, 0) - hist_before.get(k, 0) > 0
                    }
                    point = min(runs, key=lambda r: r["srv_p99_ms"])
                else:
                    point = load_point(conns, per_thread)
                sweep.append({"conns": conns, **point})
        finally:
            os.unlink(users_file)
        detail["serve_sweep"] = sweep
        batched = sum(v for k, v in stage_hist.items() if int(k) > 1)
        best = min(runs, key=lambda r: r["srv_p99_ms"])
        # two latency views, both honest: the CLIENT-observed numbers
        # (include the load generator's own CPU on this single-core
        # bench host — client and server share the core, so client
        # parse/format time bills into the observed tail), and the
        # SERVER-side serving time (queue wait + dispatch, measured
        # inside the server) — the server's actual contribution, which
        # is what the gate holds to 25 ms. A multi-core serving host
        # would pull the client view toward the server view.
        detail["serve_qps_32conn"] = best["qps"]
        detail["serve_p50_ms_32conn"] = best["p50_ms"]
        detail["serve_p99_ms_32conn"] = best["p99_ms"]
        detail["serve_p50_ms_32conn_serverside"] = best["srv_p50_ms"]
        detail["serve_p99_ms_32conn_serverside"] = best["srv_p99_ms"]
        detail["serve_32conn_runs"] = runs
        detail["serve_32conn_note"] = (
            "min-of-2 runs (both reported in serve_32conn_runs): the "
            "single-vCPU bench host has CPU-steal weather; "
            "client-observed numbers include the loadgen's own CPU on "
            "the shared core; the gate holds the SERVER-side p99 "
            "(queue wait + dispatch) to 25 ms")
        detail["serve_batch_histogram"] = stage_hist
        detail["serve_32_gate_passed"] = bool(
            best["srv_p99_ms"] < 25.0 and batched > 0)
    finally:
        server.stop()


def stage_stream(base_dir, out_path):
    """Streaming freshness stage (ROADMAP item C / PR 9), run LAST in
    its own process: the stream bench APPENDS events, which advances
    the event-log fingerprint — run before the warm stage, those
    appends would evict the unchanged-data layout-cache fast path the
    warm stage exists to price. Reopening the store here also exercises
    the delta cursor's restart contract on the real bench log."""
    from predictionio_tpu.data.storage import set_storage
    from predictionio_tpu.serving.engine_server import EngineServer
    from predictionio_tpu.templates.recommendation import recommendation_engine

    storage = _storage(base_dir)
    detail = {}
    engine = recommendation_engine()
    server = EngineServer(
        engine, "bench_reco", host="127.0.0.1", port=0, storage=storage,
    ).start()
    try:
        item_ids = server.deployment.models[0].item_ids
        _stream_stage(storage, engine, server, item_ids, detail)
    finally:
        server.stop()
    storage.events().close()
    set_storage(None)
    with open(out_path, "w") as f:
        json.dump(detail, f)


def _stream_stage(storage, engine, server, item_ids, detail):
    """event_to_servable: append→changed-prediction latency through the
    streaming fold-in path (ROADMAP item C / PR 9) against a LIVE
    engine server serving the bench instance — plus fold-in throughput.

    The timed region is the full freshness loop a production stream
    daemon runs per cycle: raw event append into the native log, delta
    tail read (find_columnar_since), ALS fold-in solves, model patch
    over HTTP to the serving process, and a confirming /queries.json
    answer carrying the folded user's predictions. Jit buckets are
    warmed by the preceding folds (steady-state freshness is the
    metric, same stance as the serve warm-up)."""
    import datetime as dt
    import urllib.request

    from predictionio_tpu.data.event import Event
    from predictionio_tpu.workflow.stream import StreamUpdater

    updater = StreamUpdater(engine, "bench_reco", storage=storage,
                            patch_servers=[server])
    app = storage.apps().get_by_name("bench")
    events = storage.events()
    inv_items = item_ids.inverse()
    rng = np.random.default_rng(11)

    def rate(user, item, r):
        return Event(
            event="rate", entity_type="user", entity_id=user,
            target_entity_type="item", target_entity_id=item,
            properties={"rating": float(r)},
            event_time=dt.datetime.now(tz=dt.timezone.utc))

    def query(user):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/queries.json",
            data=json.dumps({"user": user, "num": 5}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read())

    # warm the fold path's compiled buckets with one tiny fold
    events.insert_batch([rate("stream_warm_u", inv_items[0], 4.0)], app.id)
    updater.poll_once()

    # fold-in throughput: 1000 events from 100 new users over 8 distinct
    # existing items (bounds the per-item history scans)
    hot_items = [inv_items[int(i)]
                 for i in rng.integers(0, len(item_ids), size=8)]
    batch = [rate(f"stream_tp_u{k % 100}", hot_items[k % 8],
                  float(rng.integers(1, 11)) / 2.0)
             for k in range(1000)]
    events.insert_batch(batch, app.id)
    stats = updater.poll_once()
    assert stats["events"] == 1000 and stats["published"], stats
    detail["foldin_events_per_sec"] = round(
        stats["events"] / max(stats["seconds"], 1e-9), 1)

    # append -> servable changed prediction, measured end to end: the
    # fresh user answers empty before the fold and with scores after
    user = "stream_fresh_u"
    assert query(user)["itemScores"] == []
    t0 = time.perf_counter()
    events.insert_batch([rate(user, inv_items[1], 5.0)], app.id)
    stats = updater.poll_once()
    answer = query(user)
    elapsed_ms = (time.perf_counter() - t0) * 1e3
    assert stats["published"] and answer["itemScores"], (stats, answer)
    detail["event_to_servable_ms"] = round(elapsed_ms, 1)
    detail["stream_fold_stats"] = {
        k: stats[k] for k in ("events", "touched_users", "touched_items",
                              "seconds")}
    detail["event_to_servable_note"] = (
        "append -> delta tail -> ALS fold-in -> HTTP /model/patch -> "
        "confirmed changed /queries.json answer, steady-state (fold jit "
        "warmed); the batch warm path re-ships the world in "
        "warm_events_to_model_sec instead")


def stage_quality(base_dir, out_path):
    """Model-quality observability stage (ROADMAP item D): prices the
    continuous-evaluation plane on the bench's trained instance —

      quality_recall_vs_retrain  the shadow-drift probe's recall after
                                 a real fold cycle (the gate value the
                                 stream daemon exports continuously;
                                 benchcmp: "recall" = higher-better)
      quality_probe_ms           wall cost of one drift probe (the
                                 per-cycle tax of continuous eval)
      replay_mean_overlap        the replay harness end-to-end on
                                 captured live payloads (self-replay:
                                 must stay 1.0)
      replay_ms_per_query        replay throughput tax per query
      canary_verdict_ms          wall cost of rendering one canary
                                 promote/rollback verdict from paired
                                 stats + lane histograms (benchcmp:
                                 "_ms" = lower-better)
    """
    import urllib.request

    from predictionio_tpu.data.storage import set_storage
    from predictionio_tpu.obs import quality
    from predictionio_tpu.serving.engine_server import EngineServer
    from predictionio_tpu.templates.recommendation import recommendation_engine
    from predictionio_tpu.workflow import replay as replay_mod
    from predictionio_tpu.workflow.stream import StreamUpdater

    os.environ["PIO_FLIGHT_PAYLOADS"] = "128"
    storage = _storage(base_dir)
    detail = {}
    engine = recommendation_engine()
    server = EngineServer(
        engine, "bench_reco", host="127.0.0.1", port=0, storage=storage,
    ).start()
    try:
        import datetime as dt

        from predictionio_tpu.data.event import Event

        app = storage.apps().get_by_name("bench")
        item_ids = server.deployment.models[0].item_ids
        inv_items = item_ids.inverse()
        updater = StreamUpdater(engine, "bench_reco", storage=storage,
                                patch_servers=[server])
        # one real fold so the drift probe prices the live lane, not a
        # trivially-identical snapshot
        events = [Event(event="rate", entity_type="user",
                        entity_id=f"q_u{k % 16}",
                        target_entity_type="item",
                        target_entity_id=inv_items[k % 8],
                        properties={"rating": 4.0},
                        event_time=dt.datetime.now(tz=dt.timezone.utc))
                  for k in range(64)]
        storage.events().insert_batch(events, app.id)
        stats = updater.poll_once()
        assert stats["published"], stats
        t0 = time.perf_counter()
        report = updater.probe_quality()
        detail["quality_probe_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 2)
        detail["quality_recall_vs_retrain"] = report["recall_vs_retrain"]
        detail["quality_rmse_drift"] = report["rmse_drift"]

        # replay: capture real payloads through the live HTTP lane,
        # then replay them (self-replay — overlap gates at 1.0)
        rng = np.random.default_rng(17)
        users = [f"q_u{int(u)}" for u in rng.integers(0, 16, size=32)]
        for user in users:
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/queries.json",
                data=json.dumps({"user": user, "num": 5}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                resp.read()
        from predictionio_tpu.obs import flight

        payloads = flight.RECORDER.payloads()
        assert payloads, "payload capture recorded nothing"
        target = replay_mod.server_target(server)
        t0 = time.perf_counter()
        rep = replay_mod.replay(payloads, target, target)
        replay_sec = time.perf_counter() - t0
        detail["replay_mean_overlap"] = rep["mean_overlap"]
        detail["replay_ms_per_query"] = round(
            replay_sec / max(1, rep["n"]) * 1e3, 3)

        # canary verdict: realistic paired stats + lane histograms,
        # then the verdict math end to end
        quality.STATE.canary_begin("bench_r1", "base_inst", "cand_inst")
        lat = rng.lognormal(-5.0, 0.4, size=512)
        for v in lat:
            quality.CANARY_SECONDS.labels("baseline").observe(float(v))
            quality.CANARY_SECONDS.labels("canary").observe(float(v * 1.1))
        for _ in range(256):
            quality.STATE.add_paired({"overlap": 0.9, "score_delta": 0.01})
        t0 = time.perf_counter()
        for _ in range(10):
            verdict = quality.STATE.canary_verdict()
        detail["canary_verdict_ms"] = round(
            (time.perf_counter() - t0) / 10 * 1e3, 3)
        detail["canary_verdict_note"] = (
            "verdict render over 256 paired samples + 2x512-observation "
            "lane histograms; verdict=" + verdict["verdict"])
        quality.STATE.canary_end("bench_done", None)
    finally:
        server.stop()
    storage.events().close()
    set_storage(None)
    with open(out_path, "w") as f:
        json.dump(detail, f)


def stage_retrieval(base_dir, out_path):
    """Candidate-generation stage (index subsystem): build the ANN
    indexes over the trained bench model's item factors, then sweep
    brute force vs the exact index (Pallas kernel where the backend
    supports it, XLA fallback otherwise) vs IVF at increasing nprobe —
    queries/s AT measured recall. The headline keys are
    ``retrieval_qps_recall95`` (best backend that clears recall >=
    0.95 vs brute force) and ``index_build_sec`` (that backend's build
    time): an index that answers fast but can't find the right items
    earns nothing."""
    from predictionio_tpu.data.storage import set_storage
    # backends constructed DIRECTLY: the stage sweeps both by design,
    # so the operator's PIO_INDEX_BACKEND (which overrides make_index's
    # argument) must not collapse the sweep onto one arm
    from predictionio_tpu.index.exact import ExactIndex
    from predictionio_tpu.index.ivf import IVFIndex
    from predictionio_tpu.index.recall import brute_force_topk, recall_at_k
    from predictionio_tpu.parallel.mesh import MeshContext
    from predictionio_tpu.templates.recommendation import recommendation_engine
    from predictionio_tpu.workflow.deploy import prepare_deploy

    storage = _storage(base_dir)
    detail = {}
    instance = storage.engine_instances().get_latest_completed(
        "bench_reco", "0", "default")
    deployment = prepare_deploy(recommendation_engine(), instance,
                                MeshContext(), storage)
    model = deployment.models[0]
    vectors = np.asarray(model.item_factors, np.float32)
    n_items = vectors.shape[0]
    rng = np.random.default_rng(23)
    n_q = int(os.environ.get("PIO_BENCH_RETRIEVAL_QUERIES", "256"))
    user_rows = rng.integers(0, len(model.user_ids), size=n_q)
    queries = np.asarray(model.user_factors, np.float32)[user_rows]
    k = 10
    batch = 32
    sweep = {}

    def timed_qps(search):
        """Steady-state queries/s at batch=32 (one warm call first —
        compile/build costs are priced separately)."""
        search(queries[:batch], k)
        t0 = time.perf_counter()
        n_done = 0
        while n_done < n_q or time.perf_counter() - t0 < 0.2:
            b = queries[n_done % n_q:(n_done % n_q) + batch]
            if len(b) == 0:
                b = queries[:batch]
            search(b, k)
            n_done += len(b)
        wall = time.perf_counter() - t0
        return round(n_done / wall, 1)

    # brute force is both the recall truth and the baseline arm
    sweep["brute"] = {
        "qps": timed_qps(lambda q, kk: brute_force_topk(vectors, q, kk)),
        "recall": 1.0,
    }

    t0 = time.perf_counter()
    exact = ExactIndex()
    exact.build(vectors)
    exact_build = time.perf_counter() - t0
    sweep["exact"] = {
        "qps": timed_qps(exact.search),
        "recall": round(recall_at_k(exact, queries[:64], k,
                                    vectors=vectors), 4),
        "build_sec": round(exact_build, 3),
        "kernel": exact.kernel_plan,
    }

    ivf_best = None
    t0 = time.perf_counter()
    ivf = IVFIndex()
    ivf.build(vectors)
    ivf_build = time.perf_counter() - t0
    for nprobe in sorted({1, 4, ivf.nprobe or 1,
                          min(2 * (ivf.nprobe or 1), ivf.stats()["nlist"])}):
        ivf.nprobe = nprobe
        arm = {
            "qps": timed_qps(ivf.search),
            "recall": round(recall_at_k(ivf, queries[:64], k,
                                        vectors=vectors), 4),
            "nprobe": nprobe,
        }
        sweep[f"ivf_nprobe{nprobe}"] = arm
        if arm["recall"] >= 0.95 and (
                ivf_best is None or arm["qps"] > ivf_best["qps"]):
            ivf_best = arm
    sweep["ivf_build_sec"] = round(ivf_build, 3)
    sweep["ivf_config"] = {kk: ivf.stats()[kk]
                           for kk in ("nlist", "quantize", "recall_floor")}

    # the gated headline pair: fastest arm at recall >= 0.95 (brute is
    # always eligible, so the key always lands) + its build cost
    arms = [("brute", sweep["brute"], 0.0),
            ("exact", sweep["exact"], exact_build)]
    if ivf_best is not None:
        arms.append((f"ivf_nprobe{ivf_best['nprobe']}", ivf_best, ivf_build))
    name, best, build = max(
        (a for a in arms if a[1]["recall"] >= 0.95), key=lambda a: a[1]["qps"])
    detail["retrieval"] = {**sweep, "n_items": n_items, "k": k,
                           "batch": batch, "best_backend": name}
    detail["retrieval_qps_recall95"] = best["qps"]
    detail["index_build_sec"] = round(build, 3)
    storage.events().close()
    set_storage(None)
    with open(out_path, "w") as f:
        json.dump(detail, f)


def _fleet_stage(storage, cfg, detail):
    """serve_128conn fleet sweep: the SAME trained instance behind
    1/2/4 threaded engine-server replicas and the health-routed query
    router (serving/fleet.py + serving/router.py), hammered by the
    out-of-process load generator at 128 keep-alive connections —
    qps + client p99 + the merged SERVER-side p99 per replica count.

    Honesty note: on a single-vCPU bench host threaded replicas share
    one core, so scaling here measures the router's overhead + the
    redundancy story, not multi-core speedup — per-process replicas on
    a serving host are where the qps curve moves. The gate metric is
    the 128-conn router-path server-side p99 at the best replica
    count (key.fleet_srv_p99_ms_128conn, lower-better in
    `pio bench-compare`)."""
    import tempfile as _tf

    from predictionio_tpu.serving.engine_server import EngineServer
    from predictionio_tpu.serving.fleet import (FleetSupervisor,
                                                threaded_fleet)
    from predictionio_tpu.serving.router import QueryRouter
    from predictionio_tpu.templates.recommendation import (
        recommendation_engine,
    )

    rng = np.random.default_rng(11)

    # the instance/model _serve_stage published; user ids re-derived
    # from the stored model blob so this stage stands alone
    import pickle as _pickle

    instance = storage.engine_instances().get_latest_completed(
        "bench_reco", "0", "default")
    assert instance is not None, "fleet stage needs the serve stage's instance"
    blob = storage.models().get(instance.id)
    model = _pickle.loads(blob.models)[0]
    inv = model.user_ids.inverse()
    users = [inv[int(u)]
             for u in rng.integers(0, len(model.user_ids), size=512)]
    with _tf.NamedTemporaryFile("w", suffix=".json", delete=False) as uf:
        json.dump(users, uf)
        users_file = uf.name

    # env-tunable for constrained hosts (defaults are the real sweep)
    replica_counts = [int(x) for x in os.environ.get(
        "PIO_BENCH_FLEET_REPLICAS", "1,2,4").split(",") if x.strip()]
    conns = int(os.environ.get("PIO_BENCH_FLEET_CONNS", "128"))
    sweep = []
    try:
        for n_replicas in replica_counts:
            engine = recommendation_engine()

            def factory(name, _engine=engine):
                return EngineServer(_engine, "bench_reco",
                                    host="127.0.0.1", port=0,
                                    storage=storage, chaos_tag=name)

            fleet = FleetSupervisor(
                threaded_fleet(n_replicas, factory),
                probe_interval=0.2).start()
            router = None
            try:
                assert fleet.wait_ready(timeout=120), "fleet not ready"
                router = QueryRouter(fleet, host="127.0.0.1",
                                     port=0).start()
                per_thread = max(20, 4800 // conns)
                warm_counts = {}

                def _snap_warmup(_fleet=fleet, _counts=warm_counts):
                    for r in _fleet.replicas:
                        _counts[r.name] = r.server.stats.request_count

                load = _run_loadgen(router.port, users_file, conns,
                                    per_thread, on_warmup=_snap_warmup)
                # merged server-side serving times across replicas,
                # warm-ups excluded exactly: the per-replica counter
                # snapshot at the loadgen's warm-up barrier bounds each
                # replica's timed-sample window (a warm-up burst of
                # conns simultaneous fresh connections would otherwise
                # outnumber the p99 cohort of the merged samples)
                srv = []
                for r in fleet.replicas:
                    timed = (r.server.stats.request_count
                             - warm_counts.get(r.name, 0))
                    if timed > 0:
                        srv.extend(r.server.stats.recent(timed))
                assert srv, "no post-warm-up server-side samples"
                srv.sort()
                point = {
                    "replicas": n_replicas,
                    "conns": conns,
                    "qps": load["qps"],
                    "p50_ms": load["p50_ms"],
                    "p99_ms": load["p99_ms"],
                    "srv_p50_ms": round(_pct(srv, 0.5) * 1e3, 2),
                    "srv_p99_ms": round(_pct(srv, 0.99) * 1e3, 2),
                }
                sweep.append(point)
                if n_replicas == max(replica_counts):
                    _federation_bench(router,
                                      {"user": users[0], "num": 10},
                                      detail)
            finally:
                if router is not None:
                    router.stop()
                fleet.stop()
    finally:
        os.unlink(users_file)
    detail["fleet_sweep"] = sweep
    if not sweep:  # PIO_BENCH_FLEET_REPLICAS= disables the sweep
        detail["fleet_note"] = "fleet sweep disabled via env"
        return
    best = min(sweep, key=lambda p: p["srv_p99_ms"])
    detail["fleet_best_replicas"] = best["replicas"]
    detail["fleet_qps_128conn"] = best["qps"]
    detail["fleet_p99_ms_128conn"] = best["p99_ms"]
    detail["fleet_srv_p99_ms_128conn"] = best["srv_p99_ms"]
    detail["fleet_note"] = (
        "threaded replicas share the bench host's core(s): the sweep "
        "prices the router hop + redundancy, not multi-core scaling; "
        "server-side percentiles merge all replicas' serving times")


def _federation_bench(router, payload, detail):
    """Price the observability federation plane (obs/collect.py) over
    the live bench fleet: one full member /metrics merge
    (``fleet_scrape_ms`` — the cost of a fleet-wide scrape pass) and
    one cross-process trace stitch (``trace_stitch_ms`` — query the
    router, then assemble the spans into the annotated tree). Both are
    benchcmp-gated lower-better (`_ms` suffix). Best-effort: a failed
    probe query leaves a note, never fails the fleet stage."""
    import urllib.request as _ur

    from predictionio_tpu.obs import collect, trace as trace_mod

    members = collect.default_members(router)
    t0 = time.perf_counter()
    fed = collect.federate_metrics(members)
    detail["fleet_scrape_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
    detail["fleet_members_scraped"] = len(fed["merged_from"])
    req = _ur.Request(
        f"http://127.0.0.1:{router.port}/queries.json",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with _ur.urlopen(req, timeout=30) as resp:
            resp.read()
            trace_id = resp.headers.get(trace_mod.TRACE_HEADER)
    except Exception as e:  # noqa: BLE001 — the stitch number is
        # telemetry about telemetry; never fail the sweep over it
        detail["trace_stitch_note"] = f"stitch probe query failed: {e}"
        return
    if not trace_id:
        return
    # the edge spans seal as the handler threads unwind, AFTER the
    # response bytes: wait for the ring to carry the trace so the
    # stitch timing prices assembly, not an empty fan-out
    deadline = time.perf_counter() + 2.0
    while (not trace_mod.recent_spans(trace_id=trace_id)
           and time.perf_counter() < deadline):
        time.sleep(0.01)
    t0 = time.perf_counter()
    doc = collect.stitch_trace(trace_id, members)
    detail["trace_stitch_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
    detail["trace_stitch_spans"] = doc["span_count"]


def stage_loadgen(config_json):
    """Out-of-process load generator for the saturation stage (its own
    GIL — client CPU must not masquerade as server latency). Drives
    ``threads`` keep-alive connections ``per_thread`` requests each
    against POST /queries.json; prints ONE JSON line with latencies.

    The client is a minimal raw-socket HTTP/1.1 driver, not
    http.client: on a single-core bench host the load generator shares
    the core with the server under test, so every cycle it burns in
    stdlib header parsing is a cycle STOLEN from the server — a light
    client is the closest stand-in for a second machine."""
    import socket
    import threading

    cfg = json.loads(config_json)
    with open(cfg["users_file"]) as f:
        users = json.load(f)
    port = int(cfg["port"])
    n_threads = int(cfg["threads"])
    per_thread = int(cfg["per_thread"])
    errs = []
    lat = [[] for _ in range(n_threads)]
    spans = [None] * n_threads
    barrier = threading.Barrier(n_threads)

    # pre-built request bytes per user: the timed loop only does
    # sendall + header-scan + body read
    def request_bytes(user):
        body = json.dumps({"user": user, "num": 10}).encode()
        return (b"POST /queries.json HTTP/1.1\r\n"
                b"Host: 127.0.0.1\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: " + str(len(body)).encode() +
                b"\r\n\r\n" + body)
    reqs = [request_bytes(u) for u in users]

    def one(sock, rfile, req):
        sock.sendall(req)
        # status line + headers
        status = rfile.readline()
        if not status.startswith(b"HTTP/1.1 200"):
            raise AssertionError(f"bad status {status[:80]!r}")
        length = None
        while True:
            line = rfile.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            if line.lower().startswith(b"content-length:"):
                length = int(line.split(b":", 1)[1])
        if length is None:
            raise AssertionError("no Content-Length (route changed?)")
        data = rfile.read(length)
        if b"itemScores" not in data:
            raise AssertionError(data[:120])

    def worker(tid):
        try:
            sock = socket.create_connection(("127.0.0.1", port), 60)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            rfile = sock.makefile("rb")
            # per-connection warm-up OUTSIDE the timed region (TCP
            # setup + server thread spawn are connection costs)
            for j in range(3):  # graftlint: disable=JT09 — except below records to errs[] and aborts the barrier; never silent
                one(sock, rfile, reqs[(tid + j) % len(reqs)])
            barrier.wait(timeout=120)  # a stuck peer aborts the barrier
            if tid == 0:
                # warm-up boundary marker: every connection's warm-ups
                # are done once the barrier releases, so the parent can
                # snapshot server-side counters HERE to exclude them
                print("WARMUP_DONE", flush=True)
            t_start = time.perf_counter()
        except Exception as e:  # noqa: BLE001
            errs.append(repr(e))
            barrier.abort()  # fail fast, never hang the stage
            return
        try:
            for j in range(per_thread):  # graftlint: disable=JT09 — except below records to errs[]; the stage reports them in its output
                t0 = time.perf_counter()
                one(sock, rfile, reqs[(tid * per_thread + j) % len(reqs)])
                lat[tid].append(time.perf_counter() - t0)
            spans[tid] = (t_start, time.perf_counter())
            rfile.close()
            sock.close()
        except Exception as e:  # noqa: BLE001
            errs.append(repr(e))

    # daemon: after a timed-out join prints the error JSON, the process
    # must still be able to exit (interpreter shutdown joins non-daemon
    # threads, which would hang until the parent's subprocess timeout
    # killed us and discarded the diagnostics)
    threads = [threading.Thread(target=worker, args=(t,), daemon=True)
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        # bounded join (JT12): the orchestrator's 600s subprocess
        # timeout would otherwise be the only thing ending a hung run
        t.join(timeout=540)
        if t.is_alive():
            errs.append("loadgen worker wedged past 540s")
            break
    if errs:
        print(json.dumps({"errors": len(errs), "first": errs[0]}))
        return
    wall = max(s[1] for s in spans) - min(s[0] for s in spans)
    flat = sorted(x for ls in lat for x in ls)
    print(json.dumps({
        "errors": 0,
        "qps": round(n_threads * per_thread / wall, 1),
        "p50_ms": round(flat[len(flat) // 2] * 1e3, 2),
        "p99_ms": round(flat[int(len(flat) * 0.99)] * 1e3, 2),
    }))


def stage_cold(base_dir, out_path):
    from predictionio_tpu.data.storage import EventColumns, set_storage
    from predictionio_tpu.ops.als import predict_rmse
    from predictionio_tpu.parallel.compile_cache import enable_persistent_cache

    enable_persistent_cache()
    n_users, n_items, n_ratings, rank, iterations = knobs()
    detail = {"n_users": n_users, "n_items": n_items, "n_ratings": n_ratings,
              "rank": rank, "iterations": iterations}
    rng = np.random.default_rng(0)

    t0 = time.perf_counter()
    uu, ii, vals = synthesize(n_users, n_items, n_ratings, rng)
    cols = EventColumns(
        entity_codes=uu.astype(np.int32),
        target_codes=ii.astype(np.int32),
        name_codes=np.zeros(n_ratings, np.int32),
        values=vals,
        times_us=np.arange(n_ratings, dtype=np.int64) * 1_000_000,
        entity_vocab=[f"u{i}" for i in range(n_users)],
        target_vocab=[f"i{i}" for i in range(n_items)],
        names=["rate"],
    )
    detail["synth_sec"] = round(time.perf_counter() - t0, 2)

    storage = _storage(base_dir)
    app = storage.apps().insert("bench")
    storage.events().init(app.id)

    t0 = time.perf_counter()
    storage.events().insert_columnar(
        cols, app.id, entity_type="user", target_entity_type="item",
        value_property="rating",
    )
    ingest_sec = time.perf_counter() - t0
    detail["ingest_sec"] = round(ingest_sec, 2)
    detail["ingest_events_per_sec"] = round(n_ratings / ingest_sec, 1)

    # row-path write rate, sampled — the lane the event server pays for
    # live traffic. Since r4 that lane is the NATIVE JSON encoder
    # (EventLogEventStore.insert_json_batch, wired into POST
    # /batch/events.json): the raw API-format JSON array bytes go
    # straight to C++ — parse + EventValidation + wire packing + append
    # in one GIL-released call, no per-row Python objects. The timed
    # region is exactly the server's post-HTTP work (auth/stats
    # excluded); building the JSON bytes is the CLIENT's cost and is
    # reported separately. The legacy Event-object path (the DAO
    # fallback every non-native backend still uses) is kept as a
    # secondary metric.
    sample = min(100_000, n_ratings)
    import datetime as dt

    from predictionio_tpu.data.event import Event

    uu_py, ii_py = uu[:sample].tolist(), ii[:sample].tolist()
    vals_py = vals[:sample].tolist()
    epoch = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
    second = dt.timedelta(seconds=1)
    # the FIRST row append after a bulk columnar ingest absorbs the
    # ingest's amortized one-time costs (the pending index-snapshot
    # flush once kSnapshotInterval bytes accumulated — ~2s after 20M
    # rows; NOT the lazy by_id debt, which fresh-id live appends never
    # pay by design, eventlog.cpp append_packed). Pay and report it
    # separately so the timed sample measures the steady-state row
    # lane. The event name is NOT a training event, so the row stays
    # out of read_training.
    t0 = time.perf_counter()
    storage.events().insert_batch(
        [Event(event="bench-warmup", entity_type="user", entity_id="warmup",
               target_entity_type="item", target_entity_id="w0",
               properties={}, event_time=epoch)],
        app.id,
    )
    detail["post_bulk_append_debt_sec"] = round(time.perf_counter() - t0, 2)

    # client-side JSON build (the SDK's cost, not the server's)
    t0 = time.perf_counter()
    # event name is NOT the training event ("rate"), so the sampled
    # lanes stay out of read_training and the RMSE gates see exactly
    # the synthesized ratings
    raw = json.dumps([
        {"event": "bench-row", "entityType": "user", "entityId": f"u{uu_py[k]}",
         "targetEntityType": "item", "targetEntityId": f"i{ii_py[k]}",
         "properties": {"rating": vals_py[k]},
         "eventTime": f"2026-01-01T{(k // 3600) % 24:02d}:"
                      f"{(k // 60) % 60:02d}:{k % 60:02d}.000Z"}
        for k in range(sample)
    ]).encode()
    t1 = time.perf_counter()
    ids, codes, _, _ = storage.events().insert_json_batch(raw, app.id)
    t2 = time.perf_counter()
    assert all(c == 0 for c in codes) and len(ids) == sample
    detail["json_build_events_per_sec"] = round(sample / (t1 - t0), 1)
    detail["row_lane_events_per_sec"] = round(sample / (t2 - t1), 1)
    detail["row_lane_gate_passed"] = bool(
        detail["row_lane_events_per_sec"] >= 50_000.0)

    # FSYNC=1 lane (the HBase SYNC_WAL durability contract): same
    # batch, group-committed — one fdatasync per call
    from predictionio_tpu.data.backends.eventlog import EventLogEventStore

    fsync_store = EventLogEventStore(
        os.path.join(base_dir, "bench_fsync_lane"), fsync=True)
    fsync_store.init(1)
    t0 = time.perf_counter()
    fsync_store.insert_json_batch(raw, 1)
    t1 = time.perf_counter()
    fsync_store.close()
    detail["row_lane_fsync_events_per_sec"] = round(sample / (t1 - t0), 1)

    # legacy Event-object path (the non-native DAO fallback), two
    # phases: object build + Python-packed append
    t0 = time.perf_counter()
    events = [
        Event(event="bench-row", entity_type="user", entity_id=f"u{uu_py[k]}",
              target_entity_type="item", target_entity_id=f"i{ii_py[k]}",
              properties={"rating": vals_py[k]},
              event_time=epoch + k * second)
        for k in range(sample)
    ]
    t1 = time.perf_counter()
    storage.events().insert_batch(events, app.id)
    t2 = time.perf_counter()
    detail["event_build_events_per_sec"] = round(sample / (t1 - t0), 1)
    detail["insert_batch_events_per_sec"] = round(sample / (t2 - t1), 1)
    detail["python_row_lane_events_per_sec"] = round(sample / (t2 - t0), 1)

    trainer, pd, ho, train_stats, cfg, train_sec = _read_prepare_bin_train(
        detail, n_ratings
    )
    factors = trainer.factors()

    # quality gates (baseline: the global-mean predictor fit on train)
    rmse = predict_rmse(factors, ho)
    base_rmse = float(
        np.sqrt(np.mean((ho[2] - train_stats["train_mean"]) ** 2)))
    detail["rmse_heldout"] = round(rmse, 4)
    detail["rmse_global_mean_baseline"] = round(base_rmse, 4)
    detail["rmse_gate_passed"] = bool(rmse <= 0.85 * base_rmse)
    at_default = knobs() == DEFAULT_KNOBS
    detail["rmse_band"] = list(RMSE_BAND) if at_default else None
    detail["rmse_band_passed"] = (
        bool(RMSE_BAND[0] <= rmse <= RMSE_BAND[1]) if at_default else True
    )

    effective = (trainer.kept_user_entries + trainer.kept_item_entries) / 2
    assert int(effective) == train_stats["n_train"], (
        effective, train_stats["n_train"])
    detail["updates_per_sec"] = round(effective * iterations / train_sec, 1)
    detail["roofline"] = _roofline(trainer, train_sec, iterations)

    # MEASURED roofline (VERDICT r3 item 4): profile ONE alternation
    # under the JAX profiler (the PIO_PROFILE_DIR hook's machinery),
    # parse the xplane trace in a subprocess (per-category device time,
    # XLA cost-model flops + HBM-space bytes), and measure the
    # governing resource empirically — the claim is gather-ISSUE-bound
    # (ops/als.py), so the roof is a pure gather+mask kernel at the
    # real shapes, and the fraction is train slots/s over roof slots/s.
    import jax

    prof_dir = os.environ.get("PIO_PROFILE_DIR",
                              os.path.join(base_dir, "train_profile"))
    t0 = time.perf_counter()
    with jax.profiler.trace(prof_dir):
        trainer.step_n(1)
    profiled_step_sec = time.perf_counter() - t0
    roof = trainer.measure_gather_roof()
    trace = {}
    try:
        proc = subprocess.run(
            [sys.executable, sys.argv[0] if sys.argv[0].endswith(".py")
             else os.path.abspath(__file__),
             "--stage", "parse_profile", "--base", prof_dir],
            capture_output=True, text=True, timeout=600,
        )
        lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
        trace = json.loads(lines[-1]) if lines else {
            "error": f"parse rc={proc.returncode}: {proc.stderr[-300:]}"}
    except Exception as e:  # noqa: BLE001 — measurement must not fail bench
        trace = {"error": str(e)}
    train_slots_per_sec = roof["slots_per_iteration"] / profiled_step_sec
    governing_fraction = train_slots_per_sec / roof["roof_slots_per_sec"]
    measured = {
        "measured": True,
        "governing": "gather-issue",
        "profiled_step_sec": round(profiled_step_sec, 3),
        "train_slots_per_sec": round(train_slots_per_sec / 1e9, 3),
        "gather_roof_slots_per_sec": round(
            roof["roof_slots_per_sec"] / 1e9, 3),
        "slots_unit": "Gslots/s (one slot = one gathered K-vector row)",
        "governing_fraction": round(governing_fraction, 3),
        "trace": trace,
    }
    if trace.get("hbm_bytes_total"):
        measured["achieved_hbm_gb_per_sec_traced"] = round(
            trace["hbm_bytes_total"] / trace["device_time_sec"] / 1e9, 1)
        measured["hbm_fraction_traced"] = round(
            trace["hbm_bytes_total"] / trace["device_time_sec"]
            / V5E_PEAK_HBM_BYTES, 3)
    # the profiled region was exactly ONE alternation
    breakdown = _step_device_breakdown(trace, 1)
    if breakdown is not None:
        measured["step_device_breakdown"] = breakdown
    detail["roofline"]["measured"] = measured
    # release the trainer's HBM before the serving deployment compiles
    del trainer

    _serve_stage(storage, factors, pd, cfg, detail)
    _fleet_stage(storage, cfg, detail)

    # train high-water (obs/memacct.py): the trainer's peak estimate
    # survives the trainer (a plain dict, not an owner-scoped ledger
    # entry) — the serving-residency half (detail.memacct /
    # key.model_hbm_bytes) was sampled inside _serve_stage while the
    # deployment was live. benchcmp gates both (the _bytes suffix =
    # lower-better: resident growth IS the regression)
    from predictionio_tpu.obs import memacct

    detail.setdefault("memacct", {})["train_peaks"] = (
        memacct.train_peaks())
    als_peak = memacct.train_peaks().get("als")
    if als_peak:
        detail["train_peak_bytes"] = int(als_peak["bytes"])

    # clean close persists the eventlog index snapshot, so the warm
    # stage's open skips the full-log replay (production parity: servers
    # close their stores on shutdown)
    storage.events().close()
    set_storage(None)
    with open(out_path, "w") as f:
        json.dump(detail, f)


def stage_twotower(base_dir, out_path):
    """The MFU stage (VERDICT r4 item 5): train the stretch two-tower
    config (BASELINE.json configs[4]) on the real chip and measure
    achieved matmul-FLOP/s against the chip's public bf16 peak.

    Structured synthetic positives (64 user/item clusters, 80% of a
    user's positives inside their cluster) give the loss a real signal
    to learn, so the loss gate measures optimization, not luck: random
    in-batch softmax sits at ~ln(B); the clustered structure must pull
    well below it. Steady-state step time comes from post-compile
    epochs (one jitted lax.scan dispatch per epoch — host cannot gap
    the device); the MFU numerator is the ANALYTIC matmul FLOPs of the
    step (logits + its two backward products + MLP; matmul only — the
    optimizer's elementwise work deliberately doesn't count), and the
    denominator uses the xplane-traced device time for the same epoch,
    with the trace's own XLA-cost-model count reported alongside as a
    cross-check."""
    import jax

    from predictionio_tpu.ops.twotower import TwoTowerConfig, TwoTowerTrainer
    from predictionio_tpu.parallel.compile_cache import enable_persistent_cache

    enable_persistent_cache()
    at_default = knobs() == DEFAULT_KNOBS
    tt_ids = int(os.environ.get("PIO_BENCH_TT_IDS",
                                1_000_000 if at_default else 50_000))
    tt_pos = int(os.environ.get("PIO_BENCH_TT_POS",
                                4_000_000 if at_default else 200_000))
    tt_dim = int(os.environ.get("PIO_BENCH_TT_DIM",
                                128 if at_default else 32))
    tt_batch = int(os.environ.get("PIO_BENCH_TT_BATCH",
                                  8192 if at_default else 1024))
    epochs = 3
    detail = {"config": {"users": tt_ids, "items": tt_ids, "positives": tt_pos,
                         "dim": tt_dim, "batch": tt_batch, "epochs": epochs}}

    rng = np.random.default_rng(1)
    t0 = time.perf_counter()
    n_clusters = 64
    user_cluster = rng.integers(0, n_clusters, size=tt_ids)
    uu = rng.integers(0, tt_ids, size=tt_pos)
    in_cluster = rng.random(tt_pos) < 0.8
    per_cluster = tt_ids // n_clusters
    ii = np.where(
        in_cluster,
        user_cluster[uu] + n_clusters * rng.integers(0, per_cluster, tt_pos),
        rng.integers(0, tt_ids, size=tt_pos),
    ).astype(np.int64)
    detail["synth_sec"] = round(time.perf_counter() - t0, 2)

    cfg = TwoTowerConfig(dim=tt_dim, batch_size=tt_batch, epochs=epochs,
                         learning_rate=3e-3, seed=11)
    t0 = time.perf_counter()
    trainer = TwoTowerTrainer((uu, ii, None), tt_ids, tt_ids, cfg)
    detail["init_sec"] = round(time.perf_counter() - t0, 2)
    # which loss/update paths produced these numbers (ops/pallas vs
    # XLA): a step-time comparison across rounds is meaningless
    # without it — PIO_TT_FLASH_CE / PIO_TT_EMBED_UPDATE A/B from env
    detail["kernels"] = trainer.kernel_plan
    steps = trainer.steps_per_epoch
    detail["steps_per_epoch"] = steps

    epoch_secs = []
    losses = []
    for e in range(epochs):
        t0 = time.perf_counter()
        losses = trainer.run(epochs=e + 1)
        epoch_secs.append(time.perf_counter() - t0)   # raw; round at report
    detail["epoch_secs"] = [round(t, 2) for t in epoch_secs]  # [0]=compile
    detail["losses"] = [round(l, 3) for l in losses]
    steady = min(epoch_secs[1:]) if len(epoch_secs) > 1 else epoch_secs[0]
    detail["step_ms"] = round(steady / steps * 1e3, 3)
    detail["steps_per_sec"] = round(steps / steady, 1)
    detail["examples_per_sec"] = round(steps * trainer.batch / steady, 1)

    # loss gate: must LEARN (decrease) and, at the full stretch config,
    # land well below the ~ln(B) random-softmax floor
    random_floor = float(np.log(trainer.batch))
    detail["random_loss_floor"] = round(random_floor, 2)
    gate = losses[-1] < losses[0]
    tt_overridden = any(f"PIO_BENCH_TT_{k}" in os.environ
                        for k in ("IDS", "POS", "DIM", "BATCH"))
    if at_default and not tt_overridden:
        # absolute bar only at the exact stretch config it was
        # calibrated on; ANY override keeps the relative-only gate
        gate = gate and losses[-1] < 0.75 * random_floor
    detail["loss_gate_passed"] = bool(gate)

    # measured MFU: trace ONE steady-state epoch, parse the xplane
    prof_dir = os.path.join(base_dir, "tt_profile")
    t0 = time.perf_counter()
    with jax.profiler.trace(prof_dir):
        trainer.run(epochs=epochs + 1)
    profiled_epoch_sec = time.perf_counter() - t0
    trace = {}
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--stage", "parse_profile", "--base", prof_dir],
            capture_output=True, text=True, timeout=600,
        )
        lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
        trace = json.loads(lines[-1]) if lines else {
            "error": f"parse rc={proc.returncode}: {proc.stderr[-300:]}"}
    except Exception as e:  # noqa: BLE001 — measurement must not fail bench
        trace = {"error": str(e)}
    detail["profiled_epoch_sec"] = round(profiled_epoch_sec, 2)
    detail["trace"] = trace
    # per-step device-time breakdown (the traced epoch ran `steps`
    # steps): lands in detail.twotower.step_device_breakdown
    breakdown = _step_device_breakdown(trace, steps)
    if breakdown is not None:
        detail["step_device_breakdown"] = breakdown
    # matmul_flops_per_step delegates to the ONE shared formula
    # (obs/perfacct.twotower_matmul_flops — the same count the live
    # pio_train_mfu gauge uses), and the peak is the shared imported
    # constant: the driver-captured twotower_mfu and the production
    # gauge cannot drift apart. The division stays against the v5e
    # CONSTANT (not perfacct.mfu(), which honors the PIO_PEAK_FLOPS
    # live-accounting override): a bench capture must be comparable
    # across rounds regardless of the operator's gauge configuration.
    matmul_flops = trainer.matmul_flops_per_step() * steps
    detail["matmul_flops_per_step"] = trainer.matmul_flops_per_step()
    device_sec = trace.get("device_time_sec") or steady
    detail["mfu_basis"] = (
        "analytic matmul FLOPs (logits fwd+bwd + MLP, "
        "obs/perfacct.twotower_matmul_flops) over "
        f"{'TRACED device time' if trace.get('device_time_sec') else 'steady epoch wall'}"
        " vs 197 TFLOP/s public TPU v5e bf16 peak")
    achieved = matmul_flops / device_sec
    detail["achieved_matmul_tflops"] = round(achieved / 1e12, 2)
    detail["mfu"] = round(achieved / V5E_PEAK_BF16_FLOPS, 4)
    if trace.get("flops_total") and trace.get("device_time_sec"):
        detail["xla_costmodel_tflops"] = round(
            trace["flops_total"] / trace["device_time_sec"] / 1e12, 2)
    # the second honest number: utilization DURING the matmul window
    # (the conv-fusion category's own flops over its own device time) —
    # whole-step MFU divides the same matmuls over everything else the
    # step does (CE elementwise, embedding gathers/scatters)
    conv = (trace.get("by_category") or {}).get("convolution fusion")
    if conv and conv.get("time_frac") and trace.get("device_time_sec"):
        conv_sec = conv["time_frac"] * trace["device_time_sec"]
        detail["matmul_window_tflops"] = round(
            conv["flops"] / conv_sec / 1e12, 1)
        detail["matmul_window_fraction_of_peak"] = round(
            conv["flops"] / conv_sec / V5E_PEAK_BF16_FLOPS, 3)
    with open(out_path, "w") as f:
        json.dump(detail, f)


def _chunk_sweep(full_key, cfg):
    """The H2D chunk-size sweep (detail.datapath.chunk_sweep): re-put
    the CACHED layout at several PIO_BIN_CHUNK_MB settings — mmap load
    + chunked device_put, timed put-dispatch -> confirmed-resident.
    After the first point the file is page-cache-warm, so the sweep
    isolates the transfer pipeline itself (chunking/overlap), not disk;
    chunk 0 = double-buffering off (the old single-shot put per array),
    giving the in-round A/B for the pipeline."""
    from predictionio_tpu.ops import bincache
    from predictionio_tpu.ops.als import ALSTrainer, SideLayout

    points = []
    saved_chunk = os.environ.get("PIO_BIN_CHUNK_MB")
    saved_db = os.environ.get("PIO_TRANSFER_DOUBLE_BUFFER")
    try:
        for mb in (16, 64, 256, 0):
            cached = bincache.load(full_key)
            if cached is None:
                break
            arrays, meta = cached
            if mb > 0:
                os.environ["PIO_BIN_CHUNK_MB"] = str(mb)
                os.environ.pop("PIO_TRANSFER_DOUBLE_BUFFER", None)
            else:
                os.environ["PIO_TRANSFER_DOUBLE_BUFFER"] = "0"
            user_side = SideLayout.from_arrays(arrays, "u_", meta)
            item_side = SideLayout.from_arrays(arrays, "i_", meta)
            trainer = ALSTrainer.from_sides(
                user_side, item_side, int(meta["n_users"]),
                int(meta["n_items"]), int(meta["total_entries"]), cfg)
            dones = trainer.wait_device_timed()
            sec = max(dones[-1] - trainer.put_start, 1e-9)
            points.append({
                "chunk_mb": mb,
                "transfer_sec": round(sec, 3),
                "mb_per_sec": round(trainer.transfer_bytes / sec / 1e6, 1),
            })
            del trainer
    finally:
        for k, v in (("PIO_BIN_CHUNK_MB", saved_chunk),
                     ("PIO_TRANSFER_DOUBLE_BUFFER", saved_db)):
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return points


def stage_warm(base_dir, out_path):
    """Fresh process, same store + same compilation + layout caches:
    the repeat events->model path every retrain / deploy / reload pays.

    The retrain-on-unchanged-data fast path (VERDICT r3 item 2): the
    event log's O(1) fingerprint keys the binned-layout cache the cold
    stage populated, so read/prepare/bin are all SKIPPED — no 20M-row
    re-scan, no re-binning. The device transfer IS re-paid: device
    memory does not survive the process, so the compressed layout's
    bytes must cross the tunnel again (reported with bytes + MB/s so
    tunnel variance is distinguishable from a pipeline regression)."""
    from predictionio_tpu.data.storage import set_storage
    from predictionio_tpu.ops.als import ALSTrainer, LayoutCacheMiss
    from predictionio_tpu.parallel.compile_cache import enable_persistent_cache
    from predictionio_tpu.templates.recommendation import (
        RecoDataSource,
        RecoDataSourceParams,
    )

    enable_persistent_cache()
    n_users, n_items, n_ratings, _, iterations = knobs()
    _storage(base_dir)
    detail = {}
    fp = RecoDataSource(
        RecoDataSourceParams(app_name="bench")).data_fingerprint()
    trainer = None
    if fp is not None:
        try:
            t0 = time.perf_counter()
            trainer = ALSTrainer(None, None, None, _bench_cfg(),
                                 cache_key=fp + _HOLD_TAG)
            detail["bin_sec"] = round(time.perf_counter() - t0, 2)
            detail["read_sec"] = 0.0    # skipped: layout cache hit on
            detail["prepare_sec"] = 0.0  # the unchanged-data fingerprint
            detail["bin_cache_hit"] = True
            detail["transfer_note"] = (
                "re-paid: device memory does not survive the process; "
                "the compressed layout's bytes cross the tunnel again")
        except LayoutCacheMiss:
            trainer = None
    if trainer is not None:
        n_read = n_ratings  # what the skipped read would have returned
        _transfer_and_compile(detail, trainer, iterations, n_read)
        if os.environ.get("PIO_BENCH_CHUNK_SWEEP", "1") != "0":
            from predictionio_tpu.ops.als import layout_cache_key

            detail["datapath"] = {
                "chunk_sweep": _chunk_sweep(
                    layout_cache_key(fp + _HOLD_TAG, _bench_cfg(), 1),
                    _bench_cfg()),
                "note": ("warm re-puts of the cached layout per "
                         "PIO_BIN_CHUNK_MB (page-cache-warm after the "
                         "first point); chunk_mb 0 = double-buffered "
                         "pipeline OFF (single-shot put per array)"),
            }
    else:
        detail["bin_cache_hit"] = False
        _read_prepare_bin_train(detail, n_ratings)
    set_storage(None)
    with open(out_path, "w") as f:
        json.dump(detail, f)


def stage_lint(base_dir, out_path):
    """Project-mode graftlint over the installed package: every per-file
    rule plus the whole-program concurrency pass (JT18-JT21), timed end
    to end — parse, cross-module model build, rule evaluation. The wall
    clock is the gated number (key.lint_project_ms, lower-better in
    bench-compare): the same pass runs in tier-1 and bin/lint, so a
    super-linear regression in the analysis taxes every commit. The
    stage also FAILS on any unsuppressed finding — the bench must not
    bless a tree the lint gate rejects."""
    import predictionio_tpu
    from predictionio_tpu.tools.lint import lint_project

    pkg_dir = os.path.dirname(os.path.abspath(predictionio_tpu.__file__))
    t0 = time.perf_counter()
    findings, files = lint_project([pkg_dir])
    elapsed_ms = (time.perf_counter() - t0) * 1e3
    if findings:
        raise RuntimeError(
            f"graftlint --project: {len(findings)} unsuppressed "
            f"finding(s) — the bench refuses a tree the lint gate rejects")
    detail = {
        "lint_project_ms": round(elapsed_ms, 1),
        "lint_project_files": files,
    }
    with open(out_path, "w") as f:
        json.dump(detail, f)


def stage_prof(base_dir, out_path):
    """Continuous-profiler cost + the first measured serve-path
    interpreter breakdown: an in-process EventServer (memory storage —
    no chip, no JAX) under a few seconds of threaded HTTP load, with
    the always-on sampler retained by ``start()``. Exports
    ``key.prof_overhead_pct`` (lower-better in bench-compare: the
    sampler rides EVERY serving process, so its cost taxes every
    request) and the parse/json/socket/dispatch shares of
    handler-thread samples — the host-side answer to "where does a
    request's interpreter time actually go"."""
    import threading
    import urllib.request

    from predictionio_tpu.data.metadata import AccessKey
    from predictionio_tpu.data.storage import Storage
    from predictionio_tpu.obs import contprof
    from predictionio_tpu.serving.event_server import EventServer

    storage = Storage.from_env({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        **{f"PIO_STORAGE_REPOSITORIES_{r}_{k}": v
           for r in ("METADATA", "EVENTDATA", "MODELDATA")
           for k, v in (("NAME", r.lower()), ("SOURCE", "MEM"))},
    })
    app = storage.apps().insert("bench-prof")
    storage.events().init(app.id)
    access = AccessKey.generate(app.id)
    storage.access_keys().insert(access)

    contprof.PROFILER.reset()
    server = EventServer(storage=storage, host="127.0.0.1", port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        post_url = f"{base}/events.json?accessKey={access.key}"
        body = json.dumps({"event": "view", "entityType": "user",
                           "entityId": "u1"}).encode()
        errs = []
        duration = float(os.environ.get("PIO_BENCH_PROF_SEC", "3.0"))
        deadline = time.perf_counter() + duration

        def worker():
            try:
                while time.perf_counter() < deadline:  # graftlint: disable=JT09 — except below hands the error to errs[]; the stage fails loudly on it
                    req = urllib.request.Request(
                        post_url, data=body,
                        headers={"Content-Type": "application/json"})
                    with urllib.request.urlopen(req, timeout=10) as r:
                        r.read()
                    with urllib.request.urlopen(f"{base}/healthz",
                                                timeout=10) as r:
                        r.read()
            except Exception as e:  # pragma: no cover - fails the stage
                errs.append(e)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(duration + 30.0)
        if errs:
            raise RuntimeError(f"prof stage load failed: {errs[0]!r}")
        snap = contprof.snapshot()
    finally:
        server.stop()
    total = snap["total_samples"]
    if not total:
        raise RuntimeError("prof stage: sampler collected zero samples "
                           "under load — the always-on profiler is dead")
    detail = {
        "prof_overhead_pct": round(
            contprof.PROFILER.overhead_ratio() * 100.0, 3),
        "prof_effective_hz": round(snap["effective_hz"], 2),
        "prof_samples": total,
        "prof_serve_breakdown": contprof.serve_path_breakdown(snap),
    }
    with open(out_path, "w") as f:
        json.dump(detail, f)


def stage_sentinel(base_dir, out_path):
    """Ops-journal + regression-sentinel cost: pure host, no chip, no
    storage. Times (a) the journal's fire-and-forget emit path — the
    cost a breaker flip or canary verdict adds to SERVING code
    (``key.journal_append_us``, lower-better; the acceptance bar is
    single-digit microseconds) and (b) one full sentinel change-point
    scan over a saturated timeline set — 360 samples in every series
    slot, the worst case the snapshot cadence ever pays
    (``key.anomaly_scan_ms``, lower-better)."""
    import collections

    from predictionio_tpu.obs import anomaly, journal, timeline

    # -- journal emit cost (ring only: the serving-path configuration;
    # PIO_JOURNAL_PATH adds one queue append, measured separately in
    # the detail)
    journal.JOURNAL.reset()
    os.environ.pop("PIO_JOURNAL_PATH", None)
    n = int(os.environ.get("PIO_BENCH_JOURNAL_EMITS", "20000"))
    for _ in range(200):  # warm the emit path (metrics labels, ring)
        journal.emit("breaker", target="warm", state="closed")
    t0 = time.perf_counter()
    for i in range(n):
        journal.emit("breaker", target="bench", state="open",
                     failures=i)
    ring_us = (time.perf_counter() - t0) / n * 1e6

    sink = os.path.join(base_dir, "journal_bench.jsonl")
    os.environ["PIO_JOURNAL_PATH"] = sink
    try:
        t0 = time.perf_counter()
        for i in range(n):
            journal.emit("breaker", target="bench", state="open",
                         failures=i)
        queued_us = (time.perf_counter() - t0) / n * 1e6
        if not journal.JOURNAL.flush(timeout=30.0):
            raise RuntimeError("journal writer never drained the "
                               "bench batch")
    finally:
        os.environ.pop("PIO_JOURNAL_PATH", None)
    events, corrupt = journal.read_back(sink)
    if corrupt or len(events) < n:
        raise RuntimeError(
            f"journal durability hole: {len(events)}/{n} lines back, "
            f"{corrupt} corrupt")
    journal.JOURNAL.reset()

    # -- sentinel scan cost over a SATURATED timeline: every series
    # slot full (obs/timeline MAX_SERIES x 360 samples)
    saved = timeline.TIMELINE
    bench_tl = timeline.Timeline()
    cap = 360
    series_n = timeline.MAX_SERIES
    base_ts = 1_000_000.0
    interval = 15.0
    try:
        timeline.TIMELINE = bench_tl
        anomaly.SENTINEL.reset()
        for si in range(series_n):
            name = f"serve_p99_ms.bench{si}"
            pts = bench_tl._series.setdefault(
                name, collections.deque(maxlen=cap))
            for k in range(cap):
                # flat series + one step halfway on even series: the
                # scan pays detection AND attribution work
                v = 10.0 + (5.0 if (si % 2 == 0 and k > cap // 2)
                            else 0.0)
                pts.append((base_ts + k * interval, v))
        journal.emit("reload", instance="bench-instance")
        scans = []
        for _ in range(5):
            t0 = time.perf_counter()
            anomaly.SENTINEL.scan(now=base_ts + cap * interval)
            scans.append((time.perf_counter() - t0) * 1e3)
        scan_ms = min(scans)  # best-of: the cost, not the scheduler
    finally:
        timeline.TIMELINE = saved
        anomaly.SENTINEL.reset()
        journal.JOURNAL.reset()

    detail = {
        "journal_append_us": round(ring_us, 3),
        "journal_append_queued_us": round(queued_us, 3),
        "anomaly_scan_ms": round(scan_ms, 3),
        "anomaly_scan_series": series_n,
        "anomaly_scan_samples": series_n * cap,
    }
    with open(out_path, "w") as f:
        json.dump(detail, f)


def stage_dataobs(base_dir, out_path):
    """Data & ingest observability cost (obs/dataobs.py): pure host,
    no chip, no shared store. Prices (a) the worker-side sketch update
    — count-min + space-saving + HLL + quantile work per event through
    the async queue, enqueue-to-drained (``key.dataobs_update_us``,
    lower-better) and (b) the hook's tax on the eventlog insert_batch
    bulk lane: same batch appended with the hook live vs
    PIO_DATAOBS_DISABLE=1, min-of-N walls
    (``key.dataobs_overhead_pct``, lower-better; the acceptance bar is
    <= 3%, gated)."""
    import datetime as dt

    from predictionio_tpu.data.backends.eventlog import EventLogEventStore
    from predictionio_tpu.data.event import Event
    from predictionio_tpu.obs import dataobs

    rng = np.random.default_rng(7)
    n = int(os.environ.get("PIO_BENCH_DATAOBS_EVENTS", "100000"))
    # Zipf ids: the skewed key stream the sketches exist for
    ents = rng.zipf(1.3, size=n) % 200_000
    names = [f"ev{k % 5}".encode() for k in range(n)]
    ids = [f"u{e}".encode() for e in ents]
    lens = rng.integers(80, 400, size=n).astype(np.int64)

    # -- (a) sketch update cost: enqueue + worker apply, measured
    # enqueue-to-drained so the number prices the FULL sketching work,
    # not just the hot-lane deque append
    dataobs.DATAOBS.reset()
    chunk = 2048
    dataobs.DATAOBS.observe_batch(1, names[:chunk], entity_ids=ids[:chunk],
                                  payload_lens=lens[:chunk])  # warm
    dataobs.DATAOBS.flush(timeout=10.0)
    t0 = time.perf_counter()
    for lo in range(0, n, chunk):
        dataobs.DATAOBS.observe_batch(
            1, names[lo:lo + chunk], entity_ids=ids[lo:lo + chunk],
            payload_lens=lens[lo:lo + chunk])
    if not dataobs.DATAOBS.flush(timeout=60.0):
        raise RuntimeError("dataobs worker never drained the bench batch")
    update_us = (time.perf_counter() - t0) / n * 1e6
    rep = dataobs.DATAOBS.report(top_n=1)
    if rep["events_total"] < n:
        raise RuntimeError(
            f"dataobs dropped events: {rep['events_total']}/{n}")

    # -- (b) ingest-lane overhead: what the guarded hook block in
    # eventlog.insert_batch costs per event, over the lane's own
    # per-event wall. An A/B wall diff on a ~0.3s lane run is
    # dominated by scheduler jitter (±10% — far above the 3% bar), so
    # the GATED number is the direct ratio: the hook's measured cost
    # (enabled() + np.diff over the extent offsets + one observe_batch
    # enqueue per batch) / the lane's measured per-event cost. The A/B
    # walls still run and land in the detail as a sanity record.
    sample = min(50_000, n)
    epoch = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
    second = dt.timedelta(seconds=1)
    events = [
        Event(event=f"ev{k % 5}", entity_type="user",
              entity_id=f"u{ents[k]}", target_entity_type="item",
              target_entity_id=f"i{k % 1000}",
              properties={"rating": float(k % 5)},
              event_time=epoch + k * second)
        for k in range(sample)
    ]
    walls = {"on": [], "off": []}
    try:
        for rep_i in range(3):
            for mode in ("on", "off"):
                if mode == "off":
                    os.environ["PIO_DATAOBS_DISABLE"] = "1"
                else:
                    os.environ.pop("PIO_DATAOBS_DISABLE", None)
                    dataobs.DATAOBS.reset()
                store = EventLogEventStore(
                    os.path.join(base_dir, f"dataobs_lane_{mode}_{rep_i}"))
                store.init(1)
                t0 = time.perf_counter()
                store.insert_batch(events, 1)
                walls[mode].append(time.perf_counter() - t0)
                store.close()
    finally:
        os.environ.pop("PIO_DATAOBS_DISABLE", None)
    on_s, off_s = min(walls["on"]), min(walls["off"])
    lane_us = on_s / sample * 1e6

    # the hook block, exactly as the lane pays it: one enabled() check,
    # one np.diff over the packed-extent offsets, one enqueue carrying
    # the whole batch's field sequences
    dataobs.DATAOBS.reset()
    b_names = names[:sample]
    b_ids = ids[:sample]
    offs = np.concatenate(([0], np.cumsum(lens[:sample]))).astype(np.uint64)
    reps = 20
    t0 = time.perf_counter()
    for _ in range(reps):
        if dataobs.DATAOBS.enabled():
            dataobs.DATAOBS.observe_batch(
                1, b_names, entity_ids=b_ids,
                payload_lens=np.diff(offs.astype(np.int64)))
    hook_us = (time.perf_counter() - t0) / (reps * sample) * 1e6
    if not dataobs.DATAOBS.flush(timeout=60.0):
        raise RuntimeError("dataobs worker never drained the hook batch")
    dataobs.DATAOBS.reset()
    overhead_pct = hook_us / lane_us * 100.0

    detail = {
        "dataobs_update_us": round(update_us, 4),
        "dataobs_hook_us_per_event": round(hook_us, 5),
        "dataobs_overhead_pct": round(overhead_pct, 3),
        "dataobs_lane_on_events_per_sec": round(sample / on_s, 1),
        "dataobs_lane_off_events_per_sec": round(sample / off_s, 1),
        "dataobs_lane_ab_delta_pct": round((on_s - off_s) / off_s * 100.0,
                                           2),
        "dataobs_gate_passed": bool(overhead_pct <= 3.0),
    }
    with open(out_path, "w") as f:
        json.dump(detail, f)


#: hard ceiling for the final stdout line. The driver records only a
#: ~2 KB tail of bench stdout; round 4's single fat line outgrew it and
#: the whole round's headline landed as ``"parsed": null`` in
#: BENCH_r04.json (VERDICT r4 weak #1). The compact line carries the
#: metric, the gate booleans, and the ~dozen key numbers; EVERYTHING
#: else goes to BENCH_DETAIL.json next to this file, committed, and is
#: referenced by path from the line.
MAX_HEADLINE_BYTES = 1536

DETAIL_FILE = "BENCH_DETAIL.json"

#: the one assumed Spark-MLlib-ALS CPU-node throughput proxy —
#: vs_baseline and the detail's baseline_proxy block must agree
BASELINE_PROXY = 1e6


def emit_headline(detail, detail_path=None):
    """Build the compact final-line dict from the merged stage detail,
    write the full detail to ``BENCH_DETAIL.json`` (repo root, beside
    this file), and return the line dict. If the line would exceed
    ``MAX_HEADLINE_BYTES``, optional ``key`` entries are pruned (worst
    first) until it fits — a multi-hour run must ALWAYS end in a
    parseable headline (raising here would reproduce the exact
    BENCH_r04 parsed:null failure this split exists to prevent); the
    pruning is recorded in the detail file."""
    gates = {
        "rmse": bool(detail["rmse_gate_passed"]),
        "rmse_band": bool(detail["rmse_band_passed"]),
        "serve_p50": bool(detail["serve_gate_passed"]),
        "serve_32conn": bool(detail["serve_32_gate_passed"]),
        "row_lane": bool(detail["row_lane_gate_passed"]),
    }
    value = detail["updates_per_sec"] if all(gates.values()) else 0.0
    detail["baseline_proxy"] = {
        "value": BASELINE_PROXY,
        "unit": "ratings*iters/sec",
        "basis": ("ASSUMED Spark-MLlib-ALS CPU-node throughput; the "
                  "reference publishes no numbers (BASELINE.json "
                  "published={}) — this proxy is our own stated "
                  "assumption, not a citation"),
    }
    key = {
        "train_sec": detail.get("train_sec"),
        "events_to_model_sec": detail.get("events_to_model_sec"),
        # the zero-copy data path's own gates: cold host binning and
        # the H2D wire window (benchcmp: _sec suffix = lower-better)
        "bin_sec": detail.get("bin_sec"),
        "transfer_sec": detail.get("transfer_sec"),
        "warm_events_to_model_sec": detail.get("warm", {})
        .get("events_to_model_sec"),
        "warm_transfer_mb_per_sec": detail.get("warm", {})
        .get("transfer_mb_per_sec"),
        "row_lane_events_per_sec": detail.get("row_lane_events_per_sec"),
        "rmse_heldout": detail.get("rmse_heldout"),
        "serve_p50_ms": detail.get("serve_p50_ms"),
        "serve_p99_ms": detail.get("serve_p99_ms"),
        "serve_32_srv_p50_ms": detail.get("serve_p50_ms_32conn_serverside"),
        "serve_32_srv_p99_ms": detail.get("serve_p99_ms_32conn_serverside"),
        "serve_32_qps": detail.get("serve_qps_32conn"),
        # the fleet sweep's 128-conn router-path numbers (best replica
        # count; bench-compare gates the p99 lower-better, qps higher)
        "fleet_qps_128conn": detail.get("fleet_qps_128conn"),
        "fleet_srv_p99_ms_128conn": detail.get("fleet_srv_p99_ms_128conn"),
        # observability federation (obs/collect.py): one full member
        # /metrics merge and one cross-process trace stitch over the
        # bench fleet (benchcmp: _ms suffix = lower-better)
        "fleet_scrape_ms": detail.get("fleet_scrape_ms"),
        "trace_stitch_ms": detail.get("trace_stitch_ms"),
        # streaming freshness (PR 9): append->servable-changed-prediction
        # through the fold-in path (benchcmp: _ms suffix = lower-better)
        # and fold-in throughput (per_sec = higher-better)
        "event_to_servable_ms": detail.get("event_to_servable_ms"),
        "foldin_events_per_sec": detail.get("foldin_events_per_sec"),
        # candidate generation (index subsystem): fastest backend at
        # recall >= 0.95 vs brute force (qps = higher-better in
        # benchcmp) + its build cost (_sec = lower-better)
        "retrieval_qps_recall95": detail.get("retrieval_qps_recall95"),
        "index_build_sec": detail.get("index_build_sec"),
        # model-quality plane (ROADMAP item D): the drift probe's
        # recall-vs-retrain (benchcmp: "recall" = higher-better) and
        # the canary verdict's render cost ("_ms" = lower-better)
        "quality_recall_vs_retrain": detail.get(
            "quality_recall_vs_retrain"),
        "canary_verdict_ms": detail.get("canary_verdict_ms"),
        # device-memory accounting (obs/memacct.py): serving residency
        # of the trained model (+index) and the train high-water mark
        # (benchcmp: _bytes suffix = lower-better — growth is the regression)
        "model_hbm_bytes": detail.get("model_hbm_bytes"),
        "train_peak_bytes": detail.get("train_peak_bytes"),
        # correctness tooling (tools/lint): project-mode graftlint wall
        # clock over the package (benchcmp: _ms suffix = lower-better —
        # the pass runs in tier-1 + bin/lint, so analysis cost taxes
        # every commit)
        "lint_project_ms": detail.get("lint_project_ms"),
        # continuous profiling plane (obs/contprof.py): sampler cost
        # under serve load (benchcmp: "overhead" = lower-better — the
        # sampler rides every serving process)
        "prof_overhead_pct": detail.get("prof_overhead_pct"),
        # ops journal + regression sentinel (obs/journal.py,
        # obs/anomaly.py): the emit cost a breaker flip adds to serving
        # code (benchcmp: _us suffix = lower-better) and one full
        # change-point scan over a saturated 360-sample timeline set
        # (_ms = lower-better)
        "journal_append_us": detail.get("journal_append_us"),
        "anomaly_scan_ms": detail.get("anomaly_scan_ms"),
        # data & ingest observability (obs/dataobs.py): per-event
        # sketch update through the async queue (benchcmp: _us suffix =
        # lower-better) and the hook's tax on the insert_batch bulk
        # lane ("overhead" = lower-better; gated <= 3%)
        "dataobs_update_us": detail.get("dataobs_update_us"),
        "dataobs_overhead_pct": detail.get("dataobs_overhead_pct"),
    }
    if "twotower" in detail:
        tt = detail["twotower"]
        gates["twotower_loss"] = bool(tt.get("loss_gate_passed", False))
        key["twotower_mfu"] = tt.get("mfu")
        key["twotower_step_ms"] = tt.get("step_ms")
        if not gates["twotower_loss"]:
            value = 0.0
    if "dataobs_overhead_pct" in detail:
        gates["dataobs_overhead"] = bool(
            detail.get("dataobs_gate_passed", False))
        if not gates["dataobs_overhead"]:
            value = 0.0
    line = {
        "metric": "als_ml20m_rating_updates_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "ratings*iters/sec",
        "vs_baseline": round(value / BASELINE_PROXY, 2),
        "gates": gates,
        "key": {k: v for k, v in key.items() if v is not None},
        "detail_file": DETAIL_FILE,
    }
    pruned = []
    while (len(json.dumps(line).encode()) > MAX_HEADLINE_BYTES
           and line["key"]):
        pruned.append(line["key"].popitem()[0])  # last = least essential
    if pruned:
        detail["headline_pruned_keys"] = pruned
    if detail_path is None:
        detail_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), DETAIL_FILE)
    try:
        with open(detail_path, "w") as f:
            json.dump(detail, f, indent=1, sort_keys=True)
    except OSError as e:
        # a failed detail write must never cost the headline (the whole
        # point of the split is that the line ALWAYS lands)
        line["detail_file"] = None
        line["detail_error"] = str(e)[:120]
    return line


def orchestrate():
    """Parent: never touches JAX (the chip is exclusive per process);
    runs the two stages as children sharing one store + compile cache."""
    base_dir = tempfile.mkdtemp(prefix="pio_bench_")
    env = dict(os.environ)
    env["PIO_COMPILE_CACHE_DIR"] = os.path.join(base_dir, "compile_cache")
    env["PIO_BIN_CACHE_DIR"] = os.path.join(base_dir, "bin_cache")
    try:
        stages = {}
        # lint FIRST (pure AST, no store/JAX — fails fast on a dirty
        # tree before the expensive stages spend chip time); stream
        # stays LAST (it appends events — see stage_stream); retrieval
        # only READS the cold stage's trained instance; quality appends
        # a small fold batch, so it runs after warm (whose
        # unchanged-data fast path the appends would evict)
        # prof rides second: pure host HTTP load (no chip), and its
        # overhead number should reflect a quiet machine, before the
        # heavy stages contend for cores
        # sentinel rides beside prof: pure host math (journal ring +
        # change-point scan), cheapest on a quiet machine
        # dataobs likewise: sketch math + a private eventlog store, and
        # its <=3% overhead gate wants an uncontended box
        for stage in ("lint", "prof", "sentinel", "dataobs", "cold",
                      "warm", "twotower", "retrieval", "quality",
                      "stream"):
            out = os.path.join(base_dir, f"{stage}.json")
            # child stdout -> our stderr: the stdout contract is ONE line
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--stage", stage, "--base", base_dir, "--out", out],
                env=env, stdout=sys.stderr, stderr=sys.stderr,
            )
            if proc.returncode != 0:
                raise RuntimeError(f"bench {stage} stage failed "
                                   f"(rc {proc.returncode})")
            with open(out) as f:
                stages[stage] = json.load(f)

        detail = stages["cold"]
        detail["warm"] = stages["warm"]
        detail["twotower"] = stages["twotower"]
        # stream/retrieval/quality keys land at top level: emit_headline
        # reads detail["event_to_servable_ms"] /
        # ["retrieval_qps_recall95"] / ["index_build_sec"] /
        # ["foldin_events_per_sec"] / ["quality_recall_vs_retrain"] /
        # ["canary_verdict_ms"]
        detail.update(stages["lint"])
        detail.update(stages["prof"])
        detail.update(stages["sentinel"])
        detail.update(stages["dataobs"])
        detail.update(stages["retrieval"])
        detail.update(stages["quality"])
        detail.update(stages["stream"])
        print(json.dumps(emit_headline(detail)))
    finally:
        shutil.rmtree(base_dir, ignore_errors=True)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--stage",
                        choices=["lint", "prof", "sentinel", "dataobs",
                                 "cold", "warm", "twotower", "retrieval",
                                 "quality", "stream", "parse_profile",
                                 "loadgen"])
    parser.add_argument("--base")
    parser.add_argument("--out")
    args = parser.parse_args()
    if args.stage == "lint":
        stage_lint(args.base, args.out)
    elif args.stage == "prof":
        stage_prof(args.base, args.out)
    elif args.stage == "sentinel":
        stage_sentinel(args.base, args.out)
    elif args.stage == "dataobs":
        stage_dataobs(args.base, args.out)
    elif args.stage == "cold":
        stage_cold(args.base, args.out)
    elif args.stage == "warm":
        stage_warm(args.base, args.out)
    elif args.stage == "twotower":
        stage_twotower(args.base, args.out)
    elif args.stage == "retrieval":
        stage_retrieval(args.base, args.out)
    elif args.stage == "quality":
        stage_quality(args.base, args.out)
    elif args.stage == "stream":
        stage_stream(args.base, args.out)
    elif args.stage == "parse_profile":
        _parse_train_profile(args.base)
    elif args.stage == "loadgen":
        stage_loadgen(args.base)
    else:
        orchestrate()


if __name__ == "__main__":
    main()
