// raggedbin: native fill pass for ragged->static-shape binning.
//
// The host-side data loader feeding the TPU training path
// (predictionio_tpu/ops/ragged.py). The numpy implementation must
// argsort the full COO stream to group entries (O(nnz log nnz) + three
// 20M-element scattered fancy-index writes); this native pass exploits
// what numpy cannot express: a per-group cursor walk over the input in
// arrival order is already chronological within each group, so one
// O(nnz) sequential pass assigns every entry its (row, slot) and writes
// the padded blocks directly.
//
// Reference analogue: MLlib ALS's InBlock/OutBlock construction, which
// Spark does with a cluster shuffle (SURVEY.md §2.9); here it is a
// single-machine native pass from the event store into pinned host
// buffers.
//
// Layout math (counts, row starts, padding) stays in Python where it is
// vectorized and cheap; this file only does the two O(nnz) passes that
// numpy cannot vectorize.
//
// Build: g++ -O3 -std=c++17 -shared -fPIC raggedbin.cpp -o _raggedbin.so

#include <cstdint>
#include <cstring>
#include <vector>

#include "binlayout.h"

extern "C" {

// Fill segmented virtual rows (SegmentedGroups layout, ragged.py):
//   group_row_start[g] — first global row of group g (shard-padded layout)
//   counts_true[g]     — true entry count of group g
//   max_len            — cap per group keeping the LATEST entries; -1 = none
//   L                  — slots per row;  g_per_shard — groups per shard
// Outputs (pre-zeroed by the caller; seg pre-filled with the pad value):
//   idx_out  [rows, L] int32
//   val_out  [rows, L] float32
//   mask_out [rows, L] float32
//   seg_out  [rows]    int32
// Returns 0 on success, -1 on bad input (group id out of range).
int rb_fill_segmented(
    const int64_t* group_idx, const int64_t* item_idx, const float* values,
    int64_t nnz, int64_t n_groups,
    const int64_t* group_row_start, const int64_t* counts_true,
    int64_t max_len, int64_t L, int64_t g_per_shard,
    int32_t* idx_out, float* val_out, float* mask_out, int32_t* seg_out) {
  std::vector<int64_t> cursor(n_groups, 0);
  for (int64_t k = 0; k < nnz; ++k) {
    int64_t g = group_idx[k];
    if (g < 0 || g >= n_groups) return -1;
    int64_t pos = cursor[g]++;
    if (max_len >= 0) {
      int64_t drop = counts_true[g] - max_len;
      if (drop > 0) {
        if (pos < drop) continue;  // keep only the latest max_len entries
        pos -= drop;
      }
    }
    int64_t row = group_row_start[g] + pos / L;
    int64_t slot = pos % L;
    int64_t at = row * L + slot;
    idx_out[at] = static_cast<int32_t>(item_idx[k]);
    val_out[at] = values[k];
    mask_out[at] = 1.0f;
    seg_out[row] = static_cast<int32_t>(g % g_per_shard);
  }
  return 0;
}

// Fill per-group padded blocks (PaddedGroups layout: row == group).
// Same truncation semantics (keep the latest L entries).
int rb_fill_padded(
    const int64_t* group_idx, const int64_t* item_idx, const float* values,
    int64_t nnz, int64_t n_groups, const int64_t* counts_true, int64_t L,
    int32_t* idx_out, float* val_out, float* mask_out) {
  std::vector<int64_t> cursor(n_groups, 0);
  for (int64_t k = 0; k < nnz; ++k) {
    int64_t g = group_idx[k];
    if (g < 0 || g >= n_groups) return -1;
    int64_t pos = cursor[g]++;
    int64_t drop = counts_true[g] - L;
    if (drop > 0) {
      if (pos < drop) continue;
      pos -= drop;
    }
    int64_t at = g * L + pos;
    idx_out[at] = static_cast<int32_t>(item_idx[k]);
    val_out[at] = values[k];
    mask_out[at] = 1.0f;
  }
  return 0;
}

void rb_free(void* p) { free(p); }

// Single-pass COO -> transfer-compressed segmented layout: plans the
// blocks/padding (binlayout.h — the one port of the Python layout
// math), then fills the WIRE streams directly (uint16 idx_lo [+ uint8
// idx_hi], uint8 affine value codes or f32+mask, int32 seg/counts)
// into 64-byte-aligned buffers. Replaces the old two-stage
// build_segmented_groups -> compress_side pipeline, which materialized
// [R, L] float32 val + mask + int32 idx (12-16 B/slot) only to
// re-scan them down to 3-4 B/slot (np.unique + searchsorted + bit
// splits over 20M+ elements).
//
// ``seg_len`` -1 = auto (size from the group-size histogram);
// ``max_len`` -1 = uncapped. Returns 0 ok, -1 index out of range,
// -2 allocation failure, -3 item index exceeds the 24-bit wire
// format. Buffers in *out are caller-owned (rb_free each).
int rb_bin_compressed(
    const int64_t* group_idx, const int64_t* item_idx, const float* values,
    int64_t nnz, int64_t n_groups,
    int64_t seg_len, int64_t max_len, int64_t n_shards, int64_t block_size,
    double row_cost_slots, binlayout::CSide* out) {
  memset(out, 0, sizeof(*out));
  std::vector<int64_t> counts(n_groups, 0);
  for (int64_t k = 0; k < nnz; ++k) {
    int64_t g = group_idx[k];
    if (g < 0 || g >= n_groups) return -1;
    ++counts[g];
  }
  binlayout::SidePlan plan;
  binlayout::plan_segmented(std::move(counts), n_groups, seg_len, max_len,
                            n_shards, block_size, row_cost_slots, &plan);
  binlayout::SideOut side;
  int rc = binlayout::fill_compressed(group_idx, item_idx, values, nnz,
                                      plan, &side);
  if (rc != 0) {
    side.free_all();
    return rc;
  }
  binlayout::export_side(plan, &side, out);
  return 0;
}

}  // extern "C"
