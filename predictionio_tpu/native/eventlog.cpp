// eventlog: append-only binary event log with in-memory index.
//
// The native data plane of the EVENTDATA storage tier — the role HBase
// plays in the reference (data/.../storage/hbase/HBEventsUtil.scala:47:
// rowkey = MD5(entity) || time || uuid, scans via partial row keys +
// column filters). Same design pressures, single-binary execution:
//   - append-only log per (app, channel), like an HBase region's WAL+store
//   - in-memory index of (time, entity-hash, name-hash) per record, so
//     filtered scans (PEvents.find semantics, storage/PEvents.scala:70)
//     touch only the index until materialization
//   - deletes are tombstones (HBase delete markers) carrying the log
//     offset at delete time, so they mask only earlier records — an id
//     re-inserted after a delete is live again
//   - single writer process: an flock(2) on <dir>/LOCK is held for the
//     handle's lifetime; a second process gets a clean open error
//     instead of silent corruption (concurrent access goes through the
//     event server REST API, as HBase clients go through the region
//     server)
//
// Record wire format (little-endian), produced by the Python binding:
//   u32  record_len            (bytes after this field)
//   u8   id[16]                (event id, raw uuid bytes)
//   i64  event_time_us         (epoch micros, UTC)
//   i64  creation_time_us
//   u16  len_event
//   u16  len_entity_type
//   u16  len_entity_id
//   u16  len_target_type       (0xFFFF = absent)
//   u16  len_target_id         (0xFFFF = absent)
//   u32  len_extra             (opaque JSON: properties/tags/prId/tz)
//   bytes: event, entity_type, entity_id, [target_type], [target_id], extra
//
// Tombstone file format: 24-byte entries, u8 id[16] + u64 cutoff_offset.
//
// Concurrency (in-process): one writer at a time (exclusive lock on
// append/delete), many readers (shared lock on find/get). The file is
// mmap'ed in 64 MiB-rounded chunks so most appends need no remap; only
// bytes below file_size are ever dereferenced.
//
// Build: g++ -O3 -std=c++17 -shared -fPIC -pthread eventlog.cpp -o _eventlog.so

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <limits>
#include <memory>
#include <mutex>
#include <random>
#include <shared_mutex>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <vector>

#include <fcntl.h>
#include <sys/file.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include "binlayout.h"

namespace {

constexpr uint32_t kHeaderLen = 46;  // bytes after record_len, before strings
constexpr uint16_t kAbsent = 0xFFFF;
constexpr uint64_t kMapChunk = 64ULL << 20;  // mapping granularity
// index snapshot (see write_index_snapshot): rewritten on close and
// after every kSnapshotInterval of appended bytes, so reopening a 20M-
// event log costs one sequential array read + a short suffix replay
// instead of re-parsing the whole log (the open-cost complaint HBase
// answers with persistent region indexes)
constexpr uint64_t kSnapshotInterval = 1ULL << 30;
constexpr uint32_t kIndexMagic = 0x58494C45;  // "ELIX"
constexpr uint32_t kIndexVersion = 2;
// Compaction commit protocol: log+tombstones for generation N live in
// log.<N>.bin / tombstones.<N>.bin (generation 0 keeps the legacy
// names log.bin / tombstones.bin). The CURRENT file names the active
// generation; el_compact writes the next generation's files, then
// commits by atomically renaming CURRENT — so a crash at ANY point
// leaves a consistent (old or new) generation, never a compacted log
// paired with stale tombstone cutoffs that could mask relocated live
// records. Orphaned files from aborted compactions are removed on
// open (safe under the flock).

inline uint64_t fnv1a(const uint8_t* data, size_t n, uint64_t h = 1469598103934665603ULL) {
  for (size_t i = 0; i < n; ++i) {
    h ^= data[i];
    h *= 1099511628211ULL;
  }
  return h;
}

struct RecMeta {
  uint64_t offset;    // offset of the u32 record_len field
  uint32_t len;       // record_len
  int64_t time_us;
  int64_t ctime_us;
  uint64_t etype_hash;
  uint64_t eid_hash;
  uint64_t name_hash;
  uint64_t ttype_hash;  // 0 when absent
  uint64_t tid_hash;    // 0 when absent
  uint8_t has_target_type;
  uint8_t has_target_id;
};

struct Header {
  const uint8_t* id;
  int64_t time_us;
  int64_t ctime_us;
  uint16_t len_event, len_etype, len_eid, len_ttype, len_tid;
  uint32_t len_extra;
  const uint8_t *event, *etype, *eid, *ttype, *tid;
};

// parse one record payload (the bytes after record_len); returns false on corruption
bool parse(const uint8_t* p, uint32_t len, Header* h) {
  if (len < kHeaderLen) return false;
  h->id = p;
  memcpy(&h->time_us, p + 16, 8);
  memcpy(&h->ctime_us, p + 24, 8);
  memcpy(&h->len_event, p + 32, 2);
  memcpy(&h->len_etype, p + 34, 2);
  memcpy(&h->len_eid, p + 36, 2);
  memcpy(&h->len_ttype, p + 38, 2);
  memcpy(&h->len_tid, p + 40, 2);
  memcpy(&h->len_extra, p + 42, 4);
  uint64_t need = kHeaderLen;
  need += h->len_event + h->len_etype + h->len_eid;
  uint16_t ltt = (h->len_ttype == kAbsent) ? 0 : h->len_ttype;
  uint16_t lti = (h->len_tid == kAbsent) ? 0 : h->len_tid;
  need += ltt + lti + h->len_extra;
  if (need != len) return false;
  const uint8_t* s = p + kHeaderLen;
  h->event = s;
  s += h->len_event;
  h->etype = s;
  s += h->len_etype;
  h->eid = s;
  s += h->len_eid;
  h->ttype = (h->len_ttype == kAbsent) ? nullptr : s;
  s += ltt;
  h->tid = (h->len_tid == kAbsent) ? nullptr : s;
  return true;
}

struct Log {
  int fd = -1;
  int tomb_fd = -1;
  int lock_fd = -1;
  std::string dir;
  uint64_t generation = 0;        // compaction generation (see CURRENT)
  uint64_t file_size = 0;
  uint64_t snapshot_covered = 0;  // log bytes covered by index.bin
  uint8_t* map = nullptr;
  uint64_t map_size = 0;
  bool broken = false;  // mapping failed after a durable append; reads error
  std::vector<RecMeta> recs;
  std::unordered_map<std::string, uint64_t> by_id;  // raw 16-byte id -> rec index
  std::unordered_map<std::string, uint64_t> tombs;  // id -> max cutoff offset
  bool has_dupes = false;  // an id was ever re-inserted; scans must
                           // consult by_id for liveness when set
  bool needs_id_verify = false;  // records were replayed past an index
                                 // snapshot after an unclean shutdown:
                                 // their dupe status is unknown until
                                 // ensure_id_index runs once
  // records appended via el_append_columnar carry fresh random ids, so
  // they are indexed lazily: by_id covers recs[0, indexed_upto) and is
  // completed on demand by el_get/el_delete (ensure_id_index). A bulk
  // 20M-row ingest therefore skips ~20M hash-map node inserts.
  uint64_t indexed_upto = 0;
  bool fsync_on_append = false;
  mutable std::shared_mutex mu;

  // every record is live: no tombstones and no superseded ids, so
  // scans skip the per-record by_id lookup (the dominant cost of a
  // 20M-row scan — one random DRAM access per record otherwise).
  // Unindexed records are fresh-id columnar appends — never dupes.
  bool all_live() const {
    return tombs.empty() && !has_dupes && !needs_id_verify;
  }

  ~Log() {
    if (map) munmap(map, map_size);
    if (fd >= 0) close(fd);
    if (tomb_fd >= 0) close(tomb_fd);
    if (lock_fd >= 0) close(lock_fd);  // releases the flock
  }

  // (re)map so that [0, file_size) is addressable; rounds the mapping up
  // to kMapChunk so appends rarely remap. Call with exclusive lock held.
  bool ensure_mapped() {
    if (file_size <= map_size && map) return true;
    if (file_size == 0) return true;
    uint64_t want = ((file_size + kMapChunk - 1) / kMapChunk) * kMapChunk;
    if (map) {
      munmap(map, map_size);
      map = nullptr;
      map_size = 0;
    }
    void* m = mmap(nullptr, want, PROT_READ, MAP_SHARED, fd, 0);
    if (m == MAP_FAILED) return false;
    map = static_cast<uint8_t*>(m);
    map_size = want;
    return true;
  }

  bool dead(const std::string& id, uint64_t offset) const {
    auto it = tombs.find(id);
    return it != tombs.end() && it->second > offset;
  }

  void index_record(uint64_t offset, uint32_t len, const Header& h,
                    bool fresh_ids = false) {
    RecMeta m;
    m.offset = offset;
    m.len = len;
    m.time_us = h.time_us;
    m.ctime_us = h.ctime_us;
    m.etype_hash = fnv1a(h.etype, h.len_etype);
    m.eid_hash = fnv1a(h.eid, h.len_eid);
    m.name_hash = fnv1a(h.event, h.len_event);
    m.has_target_type = h.ttype != nullptr;
    m.has_target_id = h.tid != nullptr;
    m.ttype_hash = h.ttype ? fnv1a(h.ttype, h.len_ttype) : 0;
    m.tid_hash = h.tid ? fnv1a(h.tid, h.len_tid) : 0;
    if (fresh_ids) {
      // fresh random ids can't collide: defer by_id (ensure_id_index).
      // Invariant: by_id covers exactly [0, indexed_upto) — non-fresh
      // appends pay any debt first (append_packed), so the debt region
      // is always a fresh-ids suffix and eager inserts below always
      // run with indexed_upto == recs.size().
      recs.push_back(m);
      return;
    }
    ++indexed_upto;
    std::string id(reinterpret_cast<const char*>(h.id), 16);
    if (!dead(id, offset)) {
      auto [it, inserted] = by_id.try_emplace(std::move(id), recs.size());
      if (!inserted) {
        it->second = recs.size();
        has_dupes = true;
      }
    }
    recs.push_back(m);
  }

  // complete by_id over [indexed_upto, recs.size()) — called (with the
  // exclusive lock) before any id-keyed operation
  void ensure_id_index() {
    if (indexed_upto == recs.size()) return;
    by_id.reserve(by_id.size() + (recs.size() - indexed_upto));
    for (uint64_t i = indexed_upto; i < recs.size(); ++i) {
      Header h;
      parse(map + recs[i].offset + 4, recs[i].len, &h);
      std::string id(reinterpret_cast<const char*>(h.id), 16);
      if (!dead(id, recs[i].offset)) {
        auto [it, inserted] = by_id.try_emplace(std::move(id), i);
        if (!inserted) {
          it->second = i;
          has_dupes = true;
        }
      }
    }
    indexed_upto = recs.size();
    needs_id_verify = false;  // dupe status now exact
  }
};

struct FindReq {
  int64_t start_us;   // INT64_MIN = unbounded
  int64_t until_us;   // INT64_MAX = unbounded
  const char* entity_type;  // nullptr = no filter
  const char* entity_id;
  int32_t target_type_mode;  // 0 = no filter, 1 = must be absent, 2 = equals
  int32_t target_id_mode;
  const char* target_entity_type;
  const char* target_entity_id;
  const char* event_names;  // '\0'-joined
  int32_t n_event_names;    // 0 = no filter
  int32_t reversed;
  int64_t limit;  // -1 = all
};

bool bytes_eq(const uint8_t* a, uint32_t alen, const char* b) {
  return alen == strlen(b) && memcmp(a, b, alen) == 0;
}

// precomputed filter hashes for one FindReq
struct FilterCtx {
  uint64_t etype_h = 0, eid_h = 0, ttype_h = 0, tid_h = 0;
  std::vector<std::pair<uint64_t, const char*>> name_hashes;
};

FilterCtx make_filter_ctx(const FindReq* req) {
  FilterCtx c;
  if (req->entity_type)
    c.etype_h = fnv1a(reinterpret_cast<const uint8_t*>(req->entity_type),
                      strlen(req->entity_type));
  if (req->entity_id)
    c.eid_h = fnv1a(reinterpret_cast<const uint8_t*>(req->entity_id),
                    strlen(req->entity_id));
  if (req->target_type_mode == 2)
    c.ttype_h = fnv1a(reinterpret_cast<const uint8_t*>(req->target_entity_type),
                      strlen(req->target_entity_type));
  if (req->target_id_mode == 2)
    c.tid_h = fnv1a(reinterpret_cast<const uint8_t*>(req->target_entity_id),
                    strlen(req->target_entity_id));
  const char* p = req->event_names;
  for (int32_t i = 0; i < req->n_event_names; ++i) {
    size_t l = strlen(p);
    c.name_hashes.emplace_back(fnv1a(reinterpret_cast<const uint8_t*>(p), l), p);
    p += l + 1;
  }
  return c;
}

// One record's filter check: index-hash prefilter, then header parse,
// liveness (current by_id entry) and byte-wise string confirmation
// (hash-collision guard). Fills *hd on a true return so callers parse
// only once. Caller must hold a shared lock.
bool match_rec(const Log* log, const FindReq* req, const FilterCtx& c,
               uint64_t i, Header* hd) {
  const RecMeta& m = log->recs[i];
  if (m.time_us < req->start_us || m.time_us >= req->until_us) return false;
  if (req->entity_type && m.etype_hash != c.etype_h) return false;
  if (req->entity_id && m.eid_hash != c.eid_h) return false;
  if (req->target_type_mode == 1 && m.has_target_type) return false;
  if (req->target_type_mode == 2 && (!m.has_target_type || m.ttype_hash != c.ttype_h)) return false;
  if (req->target_id_mode == 1 && m.has_target_id) return false;
  if (req->target_id_mode == 2 && (!m.has_target_id || m.tid_hash != c.tid_h)) return false;
  if (req->n_event_names > 0) {
    bool any = false;
    for (const auto& nh : c.name_hashes) {
      if (nh.first == m.name_hash) { any = true; break; }
    }
    if (!any) return false;
  }
  parse(log->map + m.offset + 4, m.len, hd);
  if (!log->all_live()) {
    auto live = log->by_id.find(std::string(reinterpret_cast<const char*>(hd->id), 16));
    if (live == log->by_id.end() || live->second != i) return false;
  }
  if (req->entity_type && !bytes_eq(hd->etype, hd->len_etype, req->entity_type)) return false;
  if (req->entity_id && !bytes_eq(hd->eid, hd->len_eid, req->entity_id)) return false;
  if (req->target_type_mode == 2 &&
      !bytes_eq(hd->ttype, hd->len_ttype, req->target_entity_type)) return false;
  if (req->target_id_mode == 2 &&
      !bytes_eq(hd->tid, hd->len_tid, req->target_entity_id)) return false;
  if (req->n_event_names > 0) {
    bool any = false;
    for (const auto& nh : c.name_hashes) {
      if (bytes_eq(hd->event, hd->len_event, nh.second)) { any = true; break; }
    }
    if (!any) return false;
  }
  return true;
}

// Filtered index scan shared by el_find / sorted columnar finds: fills
// `hits` with live matching record indices, sorted by (time, ctime,
// arrival). Caller must hold a shared lock.
void collect_hits(const Log* log, const FindReq* req, std::vector<uint64_t>* hits) {
  FilterCtx ctx = make_filter_ctx(req);
  Header hd;
  for (uint64_t i = 0; i < log->recs.size(); ++i) {
    if (match_rec(log, req, ctx, i, &hd)) hits->push_back(i);
  }

  auto key_less = [log](uint64_t a, uint64_t b) {
    const RecMeta& ma = log->recs[a];
    const RecMeta& mb = log->recs[b];
    if (ma.time_us != mb.time_us) return ma.time_us < mb.time_us;
    if (ma.ctime_us != mb.ctime_us) return ma.ctime_us < mb.ctime_us;
    return a < b;
  };
  if (req->reversed)
    std::sort(hits->begin(), hits->end(), [&](uint64_t a, uint64_t b) { return key_less(b, a); });
  else
    std::sort(hits->begin(), hits->end(), key_less);
  if (req->limit >= 0 && hits->size() > static_cast<uint64_t>(req->limit))
    hits->resize(req->limit);
}

// ---------------------------------------------------------------------------
// minimal JSON walking over the record's `extra` blob (written by our own
// packer: compact json.dumps output) to pull one numeric property out of
// the "p" object without materializing Python events
// ---------------------------------------------------------------------------

// advance past one JSON value starting at s (s < e); returns nullptr on
// malformed input
const char* skip_json_value(const char* s, const char* e);

const char* skip_ws(const char* s, const char* e) {
  while (s < e && (*s == ' ' || *s == '\t' || *s == '\n' || *s == '\r')) ++s;
  return s;
}

const char* skip_json_string(const char* s, const char* e) {  // s at opening quote
  ++s;
  while (s < e) {
    if (*s == '\\') { s += 2; continue; }
    if (*s == '"') return s + 1;
    ++s;
  }
  return nullptr;
}

const char* skip_json_container(const char* s, const char* e, char open, char close) {
  int depth = 0;
  while (s < e) {
    if (*s == '"') {
      s = skip_json_string(s, e);
      if (!s) return nullptr;
      continue;
    }
    if (*s == open) ++depth;
    else if (*s == close) {
      if (--depth == 0) return s + 1;
    }
    ++s;
  }
  return nullptr;
}

const char* skip_json_value(const char* s, const char* e) {
  s = skip_ws(s, e);
  if (s >= e) return nullptr;
  if (*s == '"') return skip_json_string(s, e);
  if (*s == '{') return skip_json_container(s, e, '{', '}');
  if (*s == '[') return skip_json_container(s, e, '[', ']');
  while (s < e && *s != ',' && *s != '}' && *s != ']') ++s;  // number/true/false/null
  return s;
}

// extract extra["p"][key] as a double; NaN when absent or non-numeric
double extract_prop(const uint8_t* extra, uint32_t len, const char* key) {
  const double nan = std::numeric_limits<double>::quiet_NaN();
  const char* s = reinterpret_cast<const char*>(extra);
  const char* e = s + len;
  // fast path: records written by el_append_columnar (and any compact
  // extra whose first property is the key) start {"p":{"<key>":
  {
    size_t klen = strlen(key);
    if (len > 8 + klen && memcmp(s, "{\"p\":{\"", 7) == 0 &&
        memcmp(s + 7, key, klen) == 0 && s[7 + klen] == '"' &&
        s[8 + klen] == ':') {
      const char* v = s + 9 + klen;
      if (v < e && (*v == '-' || (*v >= '0' && *v <= '9'))) {
        char numbuf[64];
        size_t n = std::min<size_t>(e - v, 63);
        memcpy(numbuf, v, n);
        numbuf[n] = 0;
        return strtod(numbuf, nullptr);
      }
    }
  }
  s = skip_ws(s, e);
  if (s >= e || *s != '{') return nan;
  ++s;
  size_t klen = strlen(key);
  // walk the top-level object to find "p"
  while (true) {
    s = skip_ws(s, e);
    if (s >= e || *s == '}') return nan;
    if (*s == ',') { ++s; continue; }
    if (*s != '"') return nan;
    const char* kstart = s + 1;
    const char* kend_q = skip_json_string(s, e);
    if (!kend_q) return nan;
    const char* kend = kend_q - 1;
    s = skip_ws(kend_q, e);
    if (s >= e || *s != ':') return nan;
    ++s;
    s = skip_ws(s, e);
    bool is_p = (kend - kstart) == 1 && *kstart == 'p';
    if (!is_p) {
      s = skip_json_value(s, e);
      if (!s) return nan;
      continue;
    }
    // inside "p": walk its pairs for `key`
    if (s >= e || *s != '{') return nan;
    ++s;
    while (true) {
      s = skip_ws(s, e);
      if (s >= e || *s == '}') return nan;
      if (*s == ',') { ++s; continue; }
      if (*s != '"') return nan;
      const char* pstart = s + 1;
      const char* pend_q = skip_json_string(s, e);
      if (!pend_q) return nan;
      const char* pend = pend_q - 1;
      s = skip_ws(pend_q, e);
      if (s >= e || *s != ':') return nan;
      ++s;
      s = skip_ws(s, e);
      if (static_cast<size_t>(pend - pstart) == klen &&
          memcmp(pstart, key, klen) == 0) {
        if (s < e && (*s == '-' || (*s >= '0' && *s <= '9'))) {
          char numbuf[64];
          size_t n = std::min<size_t>(e - s, 63);
          memcpy(numbuf, s, n);
          numbuf[n] = 0;
          return strtod(numbuf, nullptr);
        }
        return nan;  // present but not numeric
      }
      s = skip_json_value(s, e);
      if (!s) return nan;
    }
  }
}

// the value_property of one parsed record (NaN when absent/non-numeric)
double header_value(const Header& hd, const char* value_prop) {
  if (!hd.len_extra) return std::numeric_limits<double>::quiet_NaN();
  const uint8_t* extra = hd.tid   ? hd.tid + hd.len_tid
                       : hd.ttype ? hd.ttype + hd.len_ttype
                                  : hd.eid + hd.len_eid;
  return extract_prop(extra, hd.len_extra, value_prop);
}

// worker count for the parallel fused columnar scan: opt-out/override
// via PIO_EVENTLOG_SCAN_THREADS; single-threaded below 2M records
// (thread spin-up + merge overhead beats the win on small scans)
unsigned scan_thread_count(uint64_t nrec) {
  const char* env = getenv("PIO_EVENTLOG_SCAN_THREADS");
  if (env && *env) {
    long v = strtol(env, nullptr, 10);
    // <=0 (incl. "0", the natural opt-out spelling, and garbage) means
    // single-threaded — never "ignore the override and auto-scale"
    if (v < 1) return 1;
    return static_cast<unsigned>(std::min<long>(v, 64));
  }
  if (nrec < 2000000) return 1;
  unsigned hw = std::thread::hardware_concurrency();
  return hw ? std::min(hw, 8u) : 1;
}

// dict encoder for string columns: string -> code in first-seen order,
// dictionary emitted as concatenated bytes + exact prefix offsets (ids
// may legally contain ANY byte, including NUL, so a separator-joined
// format would be ambiguous). Keys are string_views into the mmap'ed
// log (stable under the shared lock held for the whole scan), so
// encoding 20M rows allocates nothing per row.
struct DictEncoder {
  std::unordered_map<std::string_view, int32_t> codes;
  std::vector<std::string_view> order;

  int32_t encode(const uint8_t* s, uint32_t len) {
    std::string_view key(reinterpret_cast<const char*>(s), len);
    auto it = codes.find(key);
    if (it != codes.end()) return it->second;
    int32_t code = static_cast<int32_t>(order.size());
    codes.emplace(key, code);
    order.push_back(key);
    return code;
  }

  // concatenated dictionary bytes + (order.size()+1) prefix offsets;
  // caller owns both (el_free)
  uint8_t* dump(uint64_t* nbytes, uint64_t** offsets_out) const {
    uint64_t total = 0;
    for (const auto& s : order) total += s.size();
    uint8_t* buf = static_cast<uint8_t*>(malloc(total ? total : 1));
    if (!buf) return nullptr;
    uint64_t* offs =
        static_cast<uint64_t*>(malloc(sizeof(uint64_t) * (order.size() + 1)));
    if (!offs) {
      free(buf);
      return nullptr;
    }
    uint64_t w = 0;
    size_t i = 0;
    for (const auto& s : order) {
      offs[i++] = w;
      memcpy(buf + w, s.data(), s.size());
      w += s.size();
    }
    offs[i] = w;
    *nbytes = total;
    *offsets_out = offs;
    return buf;
  }
};

// Copy accumulated column vectors + dictionaries into malloc'd outputs
// (the shared tail of el_find_columnar / el_find_columnar_since). On
// allocation failure everything allocated so far is freed and -1 comes
// back; otherwise the row count.
int64_t finish_columns(
    const DictEncoder& ents, const DictEncoder& tgts, const DictEncoder& names,
    const std::vector<int32_t>& ent_v, const std::vector<int32_t>& tgt_v,
    const std::vector<int32_t>& name_v, const std::vector<double>& val_v,
    const std::vector<int64_t>& time_v,
    int32_t** ent_codes_out, int32_t** tgt_codes_out,
    int32_t** name_codes_out, double** values_out, int64_t** times_us_out,
    uint8_t** ent_dict_out, uint64_t* ent_dict_bytes, int64_t* n_ent,
    uint8_t** tgt_dict_out, uint64_t* tgt_dict_bytes, int64_t* n_tgt,
    uint8_t** name_dict_out, uint64_t* name_dict_bytes, int64_t* n_names,
    uint64_t** ent_offsets_out, uint64_t** tgt_offsets_out,
    uint64_t** name_offsets_out) {
  auto copy_out = [](const auto& v, auto** out) {
    using T = typename std::remove_reference_t<decltype(v)>::value_type;
    T* buf = static_cast<T*>(malloc(sizeof(T) * (v.size() ? v.size() : 1)));
    if (!buf) return false;
    memcpy(buf, v.data(), sizeof(T) * v.size());
    *out = buf;
    return true;
  };
  int32_t* ent_codes = nullptr;
  int32_t* tgt_codes = nullptr;
  int32_t* name_codes = nullptr;
  double* values = nullptr;
  int64_t* times_us = nullptr;
  if (!copy_out(ent_v, &ent_codes) || !copy_out(tgt_v, &tgt_codes) ||
      !copy_out(name_v, &name_codes) || !copy_out(val_v, &values) ||
      !copy_out(time_v, &times_us)) {
    free(ent_codes); free(tgt_codes); free(name_codes); free(values); free(times_us);
    return -1;
  }

  uint64_t* ent_offs = nullptr;
  uint64_t* tgt_offs = nullptr;
  uint64_t* name_offs = nullptr;
  uint8_t* ent_dict = ents.dump(ent_dict_bytes, &ent_offs);
  uint8_t* tgt_dict = tgts.dump(tgt_dict_bytes, &tgt_offs);
  uint8_t* name_dict = names.dump(name_dict_bytes, &name_offs);
  if (!ent_dict || !tgt_dict || !name_dict) {
    free(ent_codes); free(tgt_codes); free(name_codes); free(values); free(times_us);
    free(ent_dict); free(tgt_dict); free(name_dict);
    free(ent_offs); free(tgt_offs); free(name_offs);
    return -1;
  }
  *ent_codes_out = ent_codes;
  *tgt_codes_out = tgt_codes;
  *name_codes_out = name_codes;
  *values_out = values;
  *times_us_out = times_us;
  *ent_dict_out = ent_dict;
  *tgt_dict_out = tgt_dict;
  *name_dict_out = name_dict;
  *ent_offsets_out = ent_offs;
  *tgt_offsets_out = tgt_offs;
  *name_offsets_out = name_offs;
  *n_ent = static_cast<int64_t>(ents.order.size());
  *n_tgt = static_cast<int64_t>(tgts.order.size());
  *n_names = static_cast<int64_t>(names.order.size());
  return static_cast<int64_t>(ent_v.size());
}

// Fused filter + dict-encode scan in LOG order (no sort, each record
// parsed exactly once), single- or multi-threaded — the shared body of
// el_find_columnar's bulk fast path and el_bin_columnar. Caller must
// hold a shared lock. ``want_times`` skips the per-row time vector
// (the binning lane never reads it; at 20M rows that is 160 MB of
// writes saved).
void fused_scan(const Log* log, const FindReq* req, const char* value_prop,
                bool want_times,
                DictEncoder* ents, DictEncoder* tgts, DictEncoder* names,
                std::vector<int32_t>* ent_v, std::vector<int32_t>* tgt_v,
                std::vector<int32_t>* name_v, std::vector<double>* val_v,
                std::vector<int64_t>* time_v) {
  const double nan = std::numeric_limits<double>::quiet_NaN();
  FilterCtx ctx = make_filter_ctx(req);
  const uint64_t nrec = log->recs.size();
  const unsigned nt = scan_thread_count(nrec);
  if (nt <= 1) {
    Header hd;
    for (uint64_t i = 0; i < nrec; ++i) {
      if (!match_rec(log, req, ctx, i, &hd)) continue;
      ent_v->push_back(ents->encode(hd.eid, hd.len_eid));
      tgt_v->push_back(hd.tid ? tgts->encode(hd.tid, hd.len_tid) : -1);
      name_v->push_back(names->encode(hd.event, hd.len_event));
      if (want_times) time_v->push_back(hd.time_us);
      val_v->push_back(value_prop ? header_value(hd, value_prop) : nan);
    }
    return;
  }
  // parallel fused scan: workers filter+encode contiguous record
  // ranges with LOCAL dictionaries (mmap/recs/by_id are read-only
  // under the shared lock), then ranges merge in order. Every
  // range-r global-first-seen id precedes every range-(r+1) one,
  // and within a range local first-seen order IS record order, so
  // the merged code assignment is byte-identical to the
  // sequential scan's.
  struct ColPart {
    DictEncoder ents, tgts, names;
    std::vector<int32_t> ent, tgt, name;
    std::vector<double> val;
    std::vector<int64_t> time;
  };
  std::vector<ColPart> parts(nt);
  std::vector<std::thread> workers;
  workers.reserve(nt);
  for (unsigned t = 0; t < nt; ++t) {
    const uint64_t lo = nrec * t / nt, hi = nrec * (t + 1) / nt;
    workers.emplace_back([&, t, lo, hi]() {
      ColPart& p = parts[t];
      Header hd;
      for (uint64_t i = lo; i < hi; ++i) {
        if (!match_rec(log, req, ctx, i, &hd)) continue;
        p.ent.push_back(p.ents.encode(hd.eid, hd.len_eid));
        p.tgt.push_back(hd.tid ? p.tgts.encode(hd.tid, hd.len_tid) : -1);
        p.name.push_back(p.names.encode(hd.event, hd.len_event));
        if (want_times) p.time.push_back(hd.time_us);
        p.val.push_back(value_prop ? header_value(hd, value_prop) : nan);
      }
    });
  }
  for (auto& w : workers) w.join();
  uint64_t total = 0;
  for (const auto& p : parts) total += p.ent.size();
  ent_v->reserve(total);
  tgt_v->reserve(total);
  name_v->reserve(total);
  val_v->reserve(total);
  if (want_times) time_v->reserve(total);
  auto remap = [](DictEncoder& global, const DictEncoder& local) {
    std::vector<int32_t> table(local.order.size());
    for (size_t i = 0; i < local.order.size(); ++i) {
      const std::string_view& sv = local.order[i];
      table[i] = global.encode(
          reinterpret_cast<const uint8_t*>(sv.data()),
          static_cast<uint32_t>(sv.size()));
    }
    return table;
  };
  for (const auto& p : parts) {
    const std::vector<int32_t> ent_map = remap(*ents, p.ents);
    const std::vector<int32_t> tgt_map = remap(*tgts, p.tgts);
    const std::vector<int32_t> name_map = remap(*names, p.names);
    for (size_t i = 0; i < p.ent.size(); ++i) {
      ent_v->push_back(ent_map[p.ent[i]]);
      tgt_v->push_back(p.tgt[i] >= 0 ? tgt_map[p.tgt[i]] : -1);
      name_v->push_back(name_map[p.name[i]]);
    }
    val_v->insert(val_v->end(), p.val.begin(), p.val.end());
    if (want_times)
      time_v->insert(time_v->end(), p.time.begin(), p.time.end());
  }
}

// ---------------------------------------------------------------------------
// persisted index snapshot: header + the raw RecMeta array. A local
// cache file (same-machine, same-build reader — sizeof(RecMeta) is
// checked), written atomically via tmp+rename. by_id is NOT persisted:
// it is rebuilt lazily (ensure_id_index) only when an id-keyed
// operation or a non-all-live scan needs it; the all-live fast path —
// bulk training reads — never does.
// ---------------------------------------------------------------------------

struct IndexHeader {
  uint32_t magic;
  uint32_t version;
  uint32_t recmeta_size;
  uint8_t has_dupes;
  uint8_t pad[3];
  uint64_t generation;
  uint64_t covered_bytes;
  uint64_t n_recs;
  uint64_t checksum;  // fnv1a over the RecMeta array bytes
};

bool write_all(int fd, const void* data, uint64_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(data);
  uint64_t w = 0;
  while (w < n) {
    ssize_t r = write(fd, p + w, n - w);
    if (r < 0) return false;
    w += static_cast<uint64_t>(r);
  }
  return true;
}

// make directory-entry operations (create/rename/unlink) durable —
// without this, a power failure can persist them in ANY order and
// break the compaction commit protocol's ordering assumptions
bool fsync_dir(const std::string& dir) {
  int dfd = open(dir.c_str(), O_RDONLY | O_DIRECTORY);
  if (dfd < 0) return false;
  bool ok = fsync(dfd) == 0;
  close(dfd);
  return ok;
}

std::string log_path_for(const std::string& dir, uint64_t gen) {
  return gen == 0 ? dir + "/log.bin"
                  : dir + "/log." + std::to_string(gen) + ".bin";
}

std::string tomb_path_for(const std::string& dir, uint64_t gen) {
  return gen == 0 ? dir + "/tombstones.bin"
                  : dir + "/tombstones." + std::to_string(gen) + ".bin";
}

// active generation: contents of <dir>/CURRENT (absent -> 0)
uint64_t read_generation(const std::string& dir) {
  FILE* f = fopen((dir + "/CURRENT").c_str(), "r");
  if (!f) return 0;
  unsigned long long gen = 0;
  int n = fscanf(f, "%llu", &gen);
  fclose(f);
  return n == 1 ? static_cast<uint64_t>(gen) : 0;
}

// atomically commit a new generation; returns false (leaving the old
// generation active) on any failure
bool commit_generation(const std::string& dir, uint64_t gen) {
  std::string tmp = dir + "/CURRENT.tmp";
  int fd = open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return false;
  std::string body = std::to_string(gen) + "\n";
  bool ok = write_all(fd, body.data(), body.size()) && fdatasync(fd) == 0;
  close(fd);
  if (!ok || rename(tmp.c_str(), (dir + "/CURRENT").c_str()) != 0) {
    unlink(tmp.c_str());
    return false;
  }
  return true;
}

// remove log/tombstone files of other generations (aborted compactions
// or superseded generations); caller holds the flock
void remove_orphan_generations(const std::string& dir, uint64_t keep_gen) {
  for (uint64_t g = 0; g <= keep_gen + 1; ++g) {
    if (g == keep_gen) continue;
    unlink(log_path_for(dir, g).c_str());
    unlink(tomb_path_for(dir, g).c_str());
  }
}

// caller holds the exclusive lock
bool write_index_snapshot(Log* log) {
  // the header's has_dupes must be exact — resolve any post-crash
  // lazily-replayed region before persisting it
  if (log->needs_id_verify) log->ensure_id_index();
  std::string tmp = log->dir + "/index.bin.tmp";
  std::string final_path = log->dir + "/index.bin";
  int fd = open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return false;
  IndexHeader hdr{};
  hdr.magic = kIndexMagic;
  hdr.version = kIndexVersion;
  hdr.recmeta_size = sizeof(RecMeta);
  hdr.has_dupes = log->has_dupes ? 1 : 0;
  hdr.generation = log->generation;
  hdr.covered_bytes = log->file_size;
  hdr.n_recs = log->recs.size();
  hdr.checksum = fnv1a(reinterpret_cast<const uint8_t*>(log->recs.data()),
                       sizeof(RecMeta) * log->recs.size());
  bool ok = write_all(fd, &hdr, sizeof(hdr)) &&
            write_all(fd, log->recs.data(), sizeof(RecMeta) * log->recs.size());
  if (ok) ok = fdatasync(fd) == 0;
  close(fd);
  if (!ok || rename(tmp.c_str(), final_path.c_str()) != 0) {
    unlink(tmp.c_str());
    return false;
  }
  log->snapshot_covered = log->file_size;
  return true;
}

// loads recs/has_dupes from index.bin when it matches this log; returns
// the number of log bytes covered (0 = no usable snapshot, replay all).
// A corrupt/stale cache file must DEGRADE (full replay), never crash or
// poison the index: the header is bounds-checked against the index
// file's own size before any allocation, the array is checksummed, and
// the record chain is verified contiguous over [0, covered_bytes).
uint64_t load_index_snapshot(Log* log) {
  std::string path = log->dir + "/index.bin";
  int fd = open(path.c_str(), O_RDONLY);
  if (fd < 0) return 0;
  struct stat ist;
  IndexHeader hdr{};
  // n_recs is validated by DIVISION against the index file's own size
  // (a multiply could wrap uint64 and let a corrupt header through to
  // the resize below)
  bool ok = fstat(fd, &ist) == 0 &&
            read(fd, &hdr, sizeof(hdr)) == static_cast<ssize_t>(sizeof(hdr)) &&
            hdr.magic == kIndexMagic && hdr.version == kIndexVersion &&
            hdr.recmeta_size == sizeof(RecMeta) &&
            hdr.generation == log->generation &&
            hdr.covered_bytes <= log->file_size &&
            static_cast<uint64_t>(ist.st_size) >= sizeof(IndexHeader) &&
            (static_cast<uint64_t>(ist.st_size) - sizeof(IndexHeader)) %
                    sizeof(RecMeta) == 0 &&
            (static_cast<uint64_t>(ist.st_size) - sizeof(IndexHeader)) /
                    sizeof(RecMeta) == hdr.n_recs;
  if (ok) {
    log->recs.resize(hdr.n_recs);
    uint64_t want = sizeof(RecMeta) * hdr.n_recs;
    uint64_t got = 0;
    while (got < want) {
      ssize_t r = read(fd, reinterpret_cast<uint8_t*>(log->recs.data()) + got,
                       want - got);
      if (r <= 0) break;
      got += static_cast<uint64_t>(r);
    }
    ok = got == want &&
         fnv1a(reinterpret_cast<const uint8_t*>(log->recs.data()), want) ==
             hdr.checksum;
    // the snapshot must describe THIS log's exact record chain:
    // contiguous from offset 0 to covered_bytes, in-bounds lengths
    if (ok) {
      uint64_t expect = 0;
      for (const RecMeta& m : log->recs) {
        if (m.offset != expect || m.len < kHeaderLen ||
            m.offset + 4 + m.len > hdr.covered_bytes) {
          ok = false;
          break;
        }
        expect = m.offset + 4 + m.len;
      }
      if (ok && expect != hdr.covered_bytes) ok = false;
    }
    // spot-parse the last record as a final cross-check against the log
    if (ok && !log->recs.empty()) {
      Header h;
      const RecMeta& last = log->recs.back();
      ok = parse(log->map + last.offset + 4, last.len, &h);
    }
  }
  close(fd);
  if (!ok) {
    log->recs.clear();
    return 0;
  }
  log->has_dupes = hdr.has_dupes != 0;
  log->indexed_upto = 0;  // by_id rebuilt lazily when actually needed
  log->snapshot_covered = hdr.covered_bytes;
  return hdr.covered_bytes;
}

}  // namespace

extern "C" {

void el_free(uint8_t* p) { free(p); }

void* el_open(const char* dir, int fsync_on_append) {
  std::string base(dir);
  if (mkdir(base.c_str(), 0755) != 0 && errno != EEXIST) return nullptr;
  auto log = std::make_unique<Log>();
  log->dir = base;
  log->fsync_on_append = fsync_on_append != 0;

  // single-writer-process guard: held until el_close
  std::string lock_path = base + "/LOCK";
  log->lock_fd = open(lock_path.c_str(), O_RDWR | O_CREAT, 0644);
  if (log->lock_fd < 0) return nullptr;
  if (flock(log->lock_fd, LOCK_EX | LOCK_NB) != 0) return nullptr;

  log->generation = read_generation(base);
  remove_orphan_generations(base, log->generation);
  std::string log_path = log_path_for(base, log->generation);
  log->fd = open(log_path.c_str(), O_RDWR | O_CREAT | O_APPEND, 0644);
  if (log->fd < 0) return nullptr;
  std::string tomb_path = tomb_path_for(base, log->generation);
  log->tomb_fd = open(tomb_path.c_str(), O_RDWR | O_CREAT | O_APPEND, 0644);
  if (log->tomb_fd < 0) return nullptr;

  // load tombstones first: cutoffs decide liveness during log replay
  struct stat st;
  if (fstat(log->tomb_fd, &st) != 0) return nullptr;
  for (off_t off = 0; off + 24 <= st.st_size; off += 24) {
    uint8_t entry[24];
    if (pread(log->tomb_fd, entry, 24, off) != 24) return nullptr;
    std::string id(reinterpret_cast<const char*>(entry), 16);
    uint64_t cutoff;
    memcpy(&cutoff, entry + 16, 8);
    uint64_t& slot = log->tombs[id];
    if (cutoff > slot) slot = cutoff;
  }

  if (fstat(log->fd, &st) != 0) return nullptr;
  log->file_size = static_cast<uint64_t>(st.st_size);
  if (!log->ensure_mapped()) return nullptr;

  // fast open: load the persisted index snapshot (clean shutdowns
  // cover the whole log), then replay only the uncovered suffix; a
  // torn tail (crash mid-append) is truncated away, mirroring WAL
  // replay semantics. Suffix records are indexed lazily — their dupe
  // status is resolved by ensure_id_index on first need.
  uint64_t off = load_index_snapshot(log.get());
  uint64_t n_suffix = 0;
  while (off + 4 <= log->file_size) {
    uint32_t len;
    memcpy(&len, log->map + off, 4);
    if (off + 4 + len > log->file_size) break;  // torn tail
    Header h;
    if (!parse(log->map + off + 4, len, &h)) break;
    if (log->snapshot_covered > 0) {
      log->index_record(off, len, h, /*fresh_ids=*/true);
      ++n_suffix;
    } else {
      log->index_record(off, len, h);
    }
    off += 4 + len;
  }
  if (n_suffix > 0) log->needs_id_verify = true;
  if (off < log->file_size) {
    if (ftruncate(log->fd, off) != 0) return nullptr;
    log->file_size = off;
  }
  return log.release();
}

void el_close(void* h) {
  Log* log = static_cast<Log*>(h);
  if (!log->broken && log->file_size != log->snapshot_covered)
    write_index_snapshot(log);
  delete log;
}

namespace {

// scans that must consult by_id for liveness (tombstones/dupes exist)
// need the id index completed first; take the exclusive lock only when
// there is lazy-indexing debt to pay
void ensure_index_for_scan(Log* log) {
  bool need;
  {
    std::shared_lock lk(log->mu);
    need = !log->all_live() && log->indexed_upto != log->recs.size();
  }
  if (need) {
    std::unique_lock lk(log->mu);
    if (!log->broken) log->ensure_id_index();
  }
}

}  // namespace

int64_t el_count(void* h) {
  Log* log = static_cast<Log*>(h);
  // non-all-live logs need exact liveness (e.g. tombstones + a lazily
  // indexed region after a snapshot load)
  ensure_index_for_scan(log);
  std::shared_lock lk(log->mu);
  // unindexed (fresh-id columnar) records are all live
  return static_cast<int64_t>(log->by_id.size() +
                              (log->recs.size() - log->indexed_upto));
}

namespace {

// write + index a batch of records already known to be well-formed
// (validated by el_append_batch, or built by el_append_columnar —
// fresh_ids = the batch's ids were freshly generated, enabling lazy
// id indexing)
int64_t append_packed(Log* log, const uint8_t* buf, uint64_t nbytes, int64_t n,
                      bool fresh_ids = false) {
  std::unique_lock lk(log->mu);
  if (log->broken) return -1;
  uint64_t written = 0;
  while (written < nbytes) {
    ssize_t w = write(log->fd, buf + written, nbytes - written);
    if (w < 0) {
      // partial batch on disk: re-truncate to the pre-batch size
      if (ftruncate(log->fd, log->file_size) != 0) {}
      return -1;
    }
    written += static_cast<uint64_t>(w);
  }
  if (log->fsync_on_append) fdatasync(log->fd);

  uint64_t base = log->file_size;
  log->file_size += nbytes;
  // index from the caller's buffer so indexing does not depend on the
  // remap succeeding; reserve up front so a 20M-row ingest doesn't
  // rehash the id map dozens of times. Caller-supplied ids could
  // duplicate an unindexed record, so pay any lazy-indexing debt first
  // (dup detection must see every id).
  // geometric growth floor: reserve(size + n) alone reallocates to
  // EXACTLY that size, so every subsequent append batch would copy the
  // whole 20M-entry index again (~1.6 GB per 100k-row batch on a
  // ML-20M log — measured as a steady-state row-lane collapse)
  if (log->recs.capacity() < log->recs.size() + n)
    log->recs.reserve(std::max(log->recs.size() + n,
                               log->recs.capacity() * 2));
  if (!fresh_ids) {
    log->ensure_id_index();
    // same doubling floor for the hash map: an exact-size reserve
    // rehashes ~all nodes on EVERY batch of a repeated ingest
    size_t want = log->by_id.size() + n;
    if (log->by_id.bucket_count() * log->by_id.max_load_factor() < want)
      log->by_id.reserve(std::max(want, log->by_id.size() * 2));
  }
  uint64_t off = 0;
  while (off < nbytes) {
    uint32_t len;
    memcpy(&len, buf + off, 4);
    Header h2;
    parse(buf + off + 4, len, &h2);
    log->index_record(base + off, len, h2, fresh_ids);
    off += 4 + len;
  }
  if (!log->ensure_mapped()) log->broken = true;
  // amortized snapshot: bounds both crash-replay work and the close-
  // time snapshot write after a bulk ingest
  if (!log->broken &&
      log->file_size - log->snapshot_covered >= kSnapshotInterval)
    write_index_snapshot(log);
  return n;
}

}  // namespace

// Appends a batch of pre-packed records. Validates the whole batch before
// writing anything (all-or-nothing). Returns records appended, or -1.
// The append is durable even if the subsequent remap fails (the handle
// then reports errors on reads until reopened, rather than crashing).
// ``fresh_ids`` != 0 asserts every id in the batch was freshly
// generated by the caller (the event server's normal live lane):
// collision with an existing id is impossible, so the append uses the
// lazy id index — no per-row by_id insert and, crucially, no paying of
// a 20M-record lazy-indexing debt left by a columnar bulk ingest.
int64_t el_append_batch(void* h, const uint8_t* buf, uint64_t nbytes,
                        int32_t fresh_ids) {
  Log* log = static_cast<Log*>(h);
  // validation pass (no lock needed; reads only the input)
  uint64_t off = 0;
  int64_t n = 0;
  Header hdr;
  while (off < nbytes) {
    if (off + 4 > nbytes) return -1;
    uint32_t len;
    memcpy(&len, buf + off, 4);
    if (off + 4 + len > nbytes) return -1;
    if (!parse(buf + off + 4, len, &hdr)) return -1;
    off += 4 + len;
    ++n;
  }
  return append_packed(log, buf, nbytes, n, fresh_ids != 0);
}

// ---------------------------------------------------------------------------
// JSON row ingest — the live event-server lane without per-row Python
// objects (the role of EventAPI's request pipeline,
// data/.../api/EventAPI.scala:209, rebuilt as a native batch encoder:
// one call parses the API-format JSON array, validates each row by the
// EventValidation contract (Event.scala:69-116), packs wire records and
// appends them under one lock + one fsync, with the GIL released).
// ---------------------------------------------------------------------------

namespace {

// per-row validation error codes; messages live in the Python binding
// and mirror data/event.py validate_event
enum RowErr : uint8_t {
  kRowOk = 0,
  kMissingEvent = 1,
  kMissingEntityType = 2,
  kMissingEntityId = 3,
  kEmptyEvent = 4,
  kEmptyEntityType = 5,
  kEmptyEntityId = 6,
  kTargetTogether = 7,
  kEmptyTargetType = 8,
  kEmptyTargetId = 9,
  kUnsetNeedsProps = 10,
  kReservedEventName = 11,
  kSpecialHasTarget = 12,
  kReservedEntityType = 13,
  kReservedTargetType = 14,
  kReservedPropertyKey = 15,
  kBadTime = 16,
  kRowNotObject = 17,
  kTooLong = 18,  // a string field exceeds the u16 wire limit
};

struct JsonCur {
  const char* p;
  const char* end;
  bool ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
    return p < end;
  }
  bool lit(char c) {
    if (!ws() || *p != c) return false;
    ++p;
    return true;
  }
  char peek() { return ws() ? *p : '\0'; }
};

int hex_nibble(char c) {
  if (c >= '0' && c <= '9') return c - '0';
  if (c >= 'a' && c <= 'f') return c - 'a' + 10;
  if (c >= 'A' && c <= 'F') return c - 'A' + 10;
  return -1;
}

// raw contents between the quotes (escapes untouched but VALIDATED);
// cursor must be AT the opening quote
bool scan_quoted(JsonCur& c, std::string_view* out, bool* has_escape) {
  if (c.p >= c.end || *c.p != '"') return false;
  ++c.p;
  const char* s = c.p;
  *has_escape = false;
  while (c.p < c.end) {
    unsigned char ch = static_cast<unsigned char>(*c.p);
    if (ch == '"') {
      *out = std::string_view(s, static_cast<size_t>(c.p - s));
      ++c.p;
      return true;
    }
    if (ch < 0x20) return false;  // RFC 8259: raw control chars are
    // invalid in strings — json.loads rejects them, and an accepted
    // raw slice would poison every later read (fuzz-found regression)
    if (ch == '\\') {
      // escapes must be VALID even when the slice is stored raw:
      // json.loads rejects \q / bad \uXXXX, so an unvalidated pass
      // here would store a slice the read path cannot decode
      // (code-review regression)
      *has_escape = true;
      if (c.p + 1 >= c.end) return false;
      char e = c.p[1];
      if (e == '"' || e == '\\' || e == '/' || e == 'b' || e == 'f' ||
          e == 'n' || e == 'r' || e == 't') {
        c.p += 2;
        continue;
      }
      if (e == 'u') {
        if (c.p + 6 > c.end) return false;
        for (int k = 2; k < 6; ++k)
          if (hex_nibble(c.p[k]) < 0) return false;
        c.p += 6;
        continue;
      }
      return false;
    }
    ++c.p;
  }
  return false;
}

// resolve JSON escapes (incl. \uXXXX with surrogate pairs) to UTF-8
bool unescape(std::string_view raw, std::string* out) {
  out->clear();
  out->reserve(raw.size());
  for (size_t i = 0; i < raw.size();) {
    char ch = raw[i];
    if (ch != '\\') {
      out->push_back(ch);
      ++i;
      continue;
    }
    if (i + 1 >= raw.size()) return false;
    char e = raw[i + 1];
    i += 2;
    switch (e) {
      case '"': out->push_back('"'); break;
      case '\\': out->push_back('\\'); break;
      case '/': out->push_back('/'); break;
      case 'b': out->push_back('\b'); break;
      case 'f': out->push_back('\f'); break;
      case 'n': out->push_back('\n'); break;
      case 'r': out->push_back('\r'); break;
      case 't': out->push_back('\t'); break;
      case 'u': {
        if (i + 4 > raw.size()) return false;
        uint32_t cp = 0;
        for (int k = 0; k < 4; ++k) {
          int v = hex_nibble(raw[i + k]);
          if (v < 0) return false;
          cp = cp * 16 + static_cast<uint32_t>(v);
        }
        i += 4;
        if (cp >= 0xD800 && cp <= 0xDBFF) {  // high surrogate
          if (i + 6 > raw.size() || raw[i] != '\\' || raw[i + 1] != 'u')
            return false;
          uint32_t lo = 0;
          for (int k = 0; k < 4; ++k) {
            int v = hex_nibble(raw[i + 2 + k]);
            if (v < 0) return false;
            lo = lo * 16 + static_cast<uint32_t>(v);
          }
          if (lo < 0xDC00 || lo > 0xDFFF) return false;
          cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
          i += 6;
        } else if (cp >= 0xDC00 && cp <= 0xDFFF) {
          return false;  // lone low surrogate
        }
        if (cp < 0x80) {
          out->push_back(static_cast<char>(cp));
        } else if (cp < 0x800) {
          out->push_back(static_cast<char>(0xC0 | (cp >> 6)));
          out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
        } else if (cp < 0x10000) {
          out->push_back(static_cast<char>(0xE0 | (cp >> 12)));
          out->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
          out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
        } else {
          out->push_back(static_cast<char>(0xF0 | (cp >> 18)));
          out->push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
          out->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
          out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
        }
        break;
      }
      default:
        return false;
    }
  }
  return true;
}

bool get_string(JsonCur& c, std::string* out) {
  std::string_view raw;
  bool esc;
  if (!c.ws() || !scan_quoted(c, &raw, &esc)) return false;
  if (!esc) {
    out->assign(raw.data(), raw.size());
    return true;
  }
  return unescape(raw, out);
}

// Skip (and optionally capture the raw slice of) any JSON value —
// STRICT grammar: captured slices are stored verbatim in the record's
// extra blob and re-parsed by json.loads on every read, so anything
// json.loads would reject must be rejected HERE (a stored malformed
// slice would poison every later read of the app — code-review
// regression: the old joint-depth scan accepted '[}' and 'truex').
bool skip_value(JsonCur& c, std::string_view* raw_out, int depth = 0) {
  if (depth > 64 || !c.ws()) return false;  // recursion bound
  const char* s = c.p;
  char ch = *c.p;
  if (ch == '"') {
    std::string_view sv;
    bool e;
    if (!scan_quoted(c, &sv, &e)) return false;
  } else if (ch == '{') {
    ++c.p;
    bool first = true;
    while (true) {
      if (!c.ws()) return false;
      if (*c.p == '}') {
        ++c.p;
        break;
      }
      if (!first) {
        if (*c.p != ',') return false;
        ++c.p;
        if (!c.ws()) return false;
      }
      first = false;
      std::string_view k;
      bool e;
      if (!scan_quoted(c, &k, &e)) return false;
      if (!c.lit(':')) return false;
      if (!skip_value(c, nullptr, depth + 1)) return false;
    }
  } else if (ch == '[') {
    ++c.p;
    bool first = true;
    while (true) {
      if (!c.ws()) return false;
      if (*c.p == ']') {
        ++c.p;
        break;
      }
      if (!first) {
        if (*c.p != ',') return false;
        ++c.p;
      }
      first = false;
      if (!skip_value(c, nullptr, depth + 1)) return false;
    }
  } else if (ch == 't') {
    if (c.end - c.p < 4 || memcmp(c.p, "true", 4) != 0) return false;
    c.p += 4;
  } else if (ch == 'f') {
    if (c.end - c.p < 5 || memcmp(c.p, "false", 5) != 0) return false;
    c.p += 5;
  } else if (ch == 'n') {
    if (c.end - c.p < 4 || memcmp(c.p, "null", 4) != 0) return false;
    c.p += 4;
  } else {
    // number: -?int frac? exp? (RFC 8259)
    if (ch == '-') ++c.p;
    if (c.p >= c.end || *c.p < '0' || *c.p > '9') return false;
    if (*c.p == '0') {
      ++c.p;
    } else {
      while (c.p < c.end && *c.p >= '0' && *c.p <= '9') ++c.p;
    }
    if (c.p < c.end && *c.p == '.') {
      ++c.p;
      if (c.p >= c.end || *c.p < '0' || *c.p > '9') return false;
      while (c.p < c.end && *c.p >= '0' && *c.p <= '9') ++c.p;
    }
    if (c.p < c.end && (*c.p == 'e' || *c.p == 'E')) {
      ++c.p;
      if (c.p < c.end && (*c.p == '+' || *c.p == '-')) ++c.p;
      if (c.p >= c.end || *c.p < '0' || *c.p > '9') return false;
      while (c.p < c.end && *c.p >= '0' && *c.p <= '9') ++c.p;
    }
  }
  // a value must terminate at a structural boundary, never run into
  // trailing junk ('truex', '1.5abc')
  if (c.p < c.end) {
    char t = *c.p;
    if (t != ',' && t != '}' && t != ']' && t != ' ' && t != '\t' &&
        t != '\n' && t != '\r')
      return false;
  }
  if (raw_out) *raw_out = std::string_view(s, static_cast<size_t>(c.p - s));
  return true;
}

// days-from-civil (public-domain Hinnant algorithm) for ISO parsing
int64_t days_from_civil(int64_t y, unsigned m, unsigned d) {
  y -= m <= 2;
  const int64_t era = (y >= 0 ? y : y - 399) / 400;
  const unsigned yoe = static_cast<unsigned>(y - era * 400);
  const unsigned doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
  const unsigned doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  return era * 146097 + static_cast<int64_t>(doe) - 719468;
}

bool two_digits(std::string_view s, size_t at, unsigned* out) {
  if (at + 2 > s.size() || s[at] < '0' || s[at] > '9' || s[at + 1] < '0' ||
      s[at + 1] > '9')
    return false;
  *out = static_cast<unsigned>((s[at] - '0') * 10 + (s[at + 1] - '0'));
  return true;
}

// Parse the dashed ISO-8601 subset the API contract uses:
//   YYYY-MM-DD([T ]HH:MM(:SS(.ffffff)?)?)?(Z|±HH(:)?MM)?
// Returns 0 ok, 1 invalid (Python's parser would reject it too),
// 2 unsupported shape (fall back to the Python path, which accepts
// more ISO variants than this fast lane).
int parse_iso_us(std::string_view s, int64_t* out_us, int64_t* offset_us) {
  *offset_us = 0;
  if (s.size() < 10) return 2;
  for (int k : {0, 1, 2, 3})
    if (s[k] < '0' || s[k] > '9') return 2;
  if (s[4] != '-' || s[7] != '-') return 2;
  unsigned month, day;
  int64_t year = (s[0] - '0') * 1000 + (s[1] - '0') * 100 + (s[2] - '0') * 10 +
                 (s[3] - '0');
  if (!two_digits(s, 5, &month) || !two_digits(s, 8, &day)) return 2;
  if (month < 1 || month > 12 || day < 1) return 1;
  static const unsigned kDays[12] = {31, 28, 31, 30, 31, 30,
                                     31, 31, 30, 31, 30, 31};
  unsigned dmax = kDays[month - 1];
  if (month == 2 && (year % 4 == 0 && (year % 100 != 0 || year % 400 == 0)))
    dmax = 29;
  if (day > dmax) return 1;  // fromisoformat rejects impossible dates too
  size_t i = 10;
  unsigned hh = 0, mm = 0, ss = 0;
  int64_t frac_us = 0;
  if (i < s.size() && (s[i] == 'T' || s[i] == ' ')) {
    ++i;
    if (!two_digits(s, i, &hh)) return 2;
    i += 2;
    if (i >= s.size() || s[i] != ':') return 2;
    ++i;
    if (!two_digits(s, i, &mm)) return 2;
    i += 2;
    if (i < s.size() && s[i] == ':') {
      ++i;
      if (!two_digits(s, i, &ss)) return 2;
      i += 2;
      if (i < s.size() && s[i] == '.') {
        ++i;
        size_t fs = i;
        int64_t v = 0;
        while (i < s.size() && s[i] >= '0' && s[i] <= '9') {
          if (i - fs < 6) v = v * 10 + (s[i] - '0');
          ++i;
        }
        size_t ndig = i - fs;
        if (ndig == 0 || ndig > 6) return 1;  // fromisoformat rejects too
        for (size_t k = ndig; k < 6; ++k) v *= 10;
        frac_us = v;
      }
    }
    if (hh > 23 || mm > 59 || ss > 59) return 1;
  }
  if (i < s.size()) {  // timezone designator
    char z = s[i];
    if (z == 'Z') {
      ++i;
    } else if (z == '+' || z == '-') {
      ++i;
      unsigned oh, om = 0;
      if (!two_digits(s, i, &oh)) return 2;
      i += 2;
      if (i < s.size() && s[i] == ':') ++i;
      if (i < s.size()) {
        if (!two_digits(s, i, &om)) return 2;
        i += 2;
      }
      if (oh > 23 || om > 59) return 1;
      int64_t off = (static_cast<int64_t>(oh) * 60 + om) * 60 * 1000000LL;
      *offset_us = (z == '-') ? -off : off;
    } else {
      return 2;
    }
  }
  if (i != s.size()) return 2;
  int64_t days = days_from_civil(year, month, day);
  int64_t local_us = days * 86400000000LL +
                     (static_cast<int64_t>(hh) * 3600 + mm * 60 + ss) *
                         1000000LL +
                     frac_us;
  *out_us = local_us - *offset_us;
  return 0;
}

bool reserved_prefix(std::string_view s) {
  return (!s.empty() && s[0] == '$') ||
         (s.size() >= 4 && s.compare(0, 4, "pio_") == 0);
}

bool is_special_event(std::string_view s) {
  return s == "$set" || s == "$unset" || s == "$delete";
}

// one parsed row (string storage owned by the caller-scoped strings)
struct JsonRow {
  std::string event, etype, eid, ttype, tid;
  bool has_ttype = false, has_tid = false;
  std::string_view props_raw;   // raw {...} slice, empty = absent
  bool props_empty = true;
  bool props_reserved_key = false;
  uint8_t err = 0;              // deferred mid-parse row error (kBadTime)
  std::string_view time_raw;    // raw quoted eventTime value (with quotes)
  std::string_view ctime_raw;
  std::string_view tags_raw;    // raw [...] slice
  std::string_view prid_raw;    // raw quoted prId
  int64_t t_us = 0, c_us = 0;
  int64_t t_off_us = 0, c_off_us = 0;
  bool has_time = false, has_ctime = false;
};

// parse one event object; returns 0 ok, -2 unsupported, or a RowErr > 0
// (the row is skipped but parsing continues at the object end)
int parse_row(JsonCur& c, JsonRow* row) {
  if (c.peek() != '{') return kRowNotObject;
  ++c.p;
  bool first = true;
  bool saw_event = false, saw_etype = false, saw_eid = false;
  while (true) {
    if (!c.ws()) return -2;
    if (*c.p == '}') {
      ++c.p;
      break;
    }
    if (!first) {
      // strict RFC-8259 member separator, same grammar as skip_value's
      // object branch: a missing comma must reject (fallback lane 400s
      // it), never silently accept what json.loads would refuse
      if (*c.p != ',') return -2;
      ++c.p;
      if (!c.ws()) return -2;
    }
    first = false;
    std::string key;
    if (!get_string(c, &key)) return -2;
    if (!c.lit(':')) return -2;
    if (key == "event") {
      if (!get_string(c, &row->event)) return -2;
      saw_event = true;
    } else if (key == "entityType") {
      if (!get_string(c, &row->etype)) return -2;
      saw_etype = true;
    } else if (key == "entityId") {
      if (!get_string(c, &row->eid)) return -2;
      saw_eid = true;
    } else if (key == "targetEntityType") {
      if (c.peek() == 'n') {  // null -> absent (from_dict d.get semantics)
        if (!skip_value(c, nullptr)) return -2;
      } else {
        if (!get_string(c, &row->ttype)) return -2;
        row->has_ttype = true;
      }
    } else if (key == "targetEntityId") {
      if (c.peek() == 'n') {
        if (!skip_value(c, nullptr)) return -2;
      } else {
        if (!get_string(c, &row->tid)) return -2;
        row->has_tid = true;
      }
    } else if (key == "properties") {
      char pk = c.peek();
      if (pk == 'n') {
        if (!skip_value(c, nullptr)) return -2;  // null -> absent
      } else if (pk != '{') {
        return -2;  // non-object properties: let Python shape the error
      } else {
        // walk the top level: reserved-prefix key check + emptiness,
        // then keep the raw slice verbatim (no re-serialization)
        const char* start = c.p;
        ++c.p;
        bool pfirst = true;
        while (true) {
          if (!c.ws()) return -2;
          if (*c.p == '}') {
            ++c.p;
            break;
          }
          if (!pfirst) {
            // strict comma: the raw slice is stored VERBATIM and
            // re-read with json.loads — accepting {"a":1 "b":2} here
            // would poison every later read of this app (get/find/
            // training all json.loads the stored blob)
            if (*c.p != ',') return -2;
            ++c.p;
            if (!c.ws()) return -2;
          }
          pfirst = false;
          std::string_view kraw;
          bool kesc;
          if (!scan_quoted(c, &kraw, &kesc)) return -2;
          if (kesc) return -2;  // escaped key could hide a prefix: fallback
          if (reserved_prefix(kraw)) row->props_reserved_key = true;
          row->props_empty = false;
          if (!c.lit(':')) return -2;
          if (!skip_value(c, nullptr)) return -2;
        }
        row->props_raw =
            std::string_view(start, static_cast<size_t>(c.p - start));
      }
    } else if (key == "eventTime" || key == "creationTime") {
      if (!c.ws()) return -2;
      std::string_view raw;
      bool is_ctime = key[0] == 'c';
      if (*c.p == '"') {
        std::string_view sv;
        bool esc;
        const char* start = c.p;
        if (!scan_quoted(c, &sv, &esc)) return -2;
        if (esc) return -2;
        raw = std::string_view(start, static_cast<size_t>(c.p - start));
        int64_t us, off;
        int rc = parse_iso_us(sv, &us, &off);
        if (rc == 2) return -2;
        if (rc == 1) {
          // deferred: the object must still be consumed to its end so
          // the array parse stays in sync for the rows after this one
          row->err = kBadTime;
          us = 0;
          off = 0;
        }
        if (is_ctime) {
          row->c_us = us;
          row->c_off_us = off;
          row->ctime_raw = raw;
          row->has_ctime = true;
        } else {
          row->t_us = us;
          row->t_off_us = off;
          row->time_raw = raw;
          row->has_time = true;
        }
      } else {
        // epoch millis (int or float), the SDKs' alternative form
        std::string_view num;
        if (!skip_value(c, &num)) return -2;
        char tmp[64];
        if (num.size() >= sizeof(tmp)) return -2;
        memcpy(tmp, num.data(), num.size());
        tmp[num.size()] = 0;
        char* endp = nullptr;
        double ms = strtod(tmp, &endp);
        if (endp != tmp + num.size()) return -2;
        int64_t us = static_cast<int64_t>(ms * 1000.0);
        if (is_ctime) {
          row->c_us = us;
          row->has_ctime = true;
        } else {
          row->t_us = us;
          row->has_time = true;
        }
      }
    } else if (key == "tags") {
      if (c.peek() == 'n') {
        if (!skip_value(c, nullptr)) return -2;
      } else {
        if (c.peek() != '[') return -2;
        if (!skip_value(c, &row->tags_raw)) return -2;
        if (row->tags_raw == "[]") row->tags_raw = {};
      }
    } else if (key == "prId") {
      if (c.peek() == 'n') {
        if (!skip_value(c, nullptr)) return -2;
      } else {
        if (c.peek() != '"') return -2;
        if (!skip_value(c, &row->prid_raw)) return -2;
      }
    } else if (key == "eventId") {
      // a caller-stamped id breaks the fresh-ids lazy-index invariant:
      // that lane (replicated writes) stays on the Python path
      if (c.peek() == 'n') {
        if (!skip_value(c, nullptr)) return -2;
      } else {
        return -2;
      }
    } else {
      if (!skip_value(c, nullptr)) return -2;  // unknown keys ignored
    }
  }
  if (!saw_event) return kMissingEvent;
  if (!saw_etype) return kMissingEntityType;
  if (!saw_eid) return kMissingEntityId;
  // the binding returns event names / entity types as NUL-joined
  // buffers: an embedded \u0000 would misalign every later row, so
  // that (pathological) shape goes to the Python path
  if (row->event.find('\0') != std::string::npos ||
      row->etype.find('\0') != std::string::npos)
    return -2;
  return row->err;
}

// the EventValidation contract (Event.scala:69-116 / data/event.py)
uint8_t validate_row(const JsonRow& r) {
  if (r.event.empty()) return kEmptyEvent;
  if (r.etype.empty()) return kEmptyEntityType;
  if (r.eid.empty()) return kEmptyEntityId;
  if (r.has_ttype != r.has_tid) return kTargetTogether;
  if (r.has_ttype && r.ttype.empty()) return kEmptyTargetType;
  if (r.has_tid && r.tid.empty()) return kEmptyTargetId;
  if (r.event == "$unset" && r.props_empty) return kUnsetNeedsProps;
  if (reserved_prefix(r.event) && !is_special_event(r.event))
    return kReservedEventName;
  if (is_special_event(r.event) && r.has_tid) return kSpecialHasTarget;
  if (reserved_prefix(r.etype) && r.etype != "pio_pr")
    return kReservedEntityType;
  if (r.has_ttype && reserved_prefix(r.ttype) && r.ttype != "pio_pr")
    return kReservedTargetType;
  if (r.props_reserved_key) return kReservedPropertyKey;
  if (r.event.size() >= kAbsent || r.etype.size() >= kAbsent ||
      r.eid.size() >= kAbsent || r.ttype.size() >= kAbsent ||
      r.tid.size() >= kAbsent)
    return kTooLong;
  return kRowOk;
}

// strict UTF-8 validation (DFA-free scalar scan): the Python lane's
// json.loads refuses invalid UTF-8, and anything appended here must
// decode again on the read path
bool valid_utf8(const uint8_t* p, uint64_t n) {
  uint64_t i = 0;
  while (i < n) {
    uint8_t c = p[i];
    if (c < 0x80) { ++i; continue; }
    int extra;
    uint32_t cp;
    if ((c & 0xE0) == 0xC0) { extra = 1; cp = c & 0x1F; }
    else if ((c & 0xF0) == 0xE0) { extra = 2; cp = c & 0x0F; }
    else if ((c & 0xF8) == 0xF0) { extra = 3; cp = c & 0x07; }
    else return false;
    if (i + extra >= n) return false;
    for (int k = 1; k <= extra; ++k) {
      if ((p[i + k] & 0xC0) != 0x80) return false;
      cp = (cp << 6) | (p[i + k] & 0x3F);
    }
    if (extra == 1 && cp < 0x80) return false;          // overlong
    if (extra == 2 && cp < 0x800) return false;
    if (extra == 3 && cp < 0x10000) return false;
    if (cp > 0x10FFFF || (cp >= 0xD800 && cp <= 0xDFFF)) return false;
    i += 1 + extra;
  }
  return true;
}

}  // namespace

// Native live-lane ingest: one call takes the API-format JSON array the
// event server receives, validates, packs and appends — no per-row
// Python work. Returns rows APPENDED (valid rows), with *out_n = total
// rows parsed; or -2 (unsupported construct anywhere: caller falls back
// to the Python path), -3 (malformed JSON), -4 (strict mode and some
// row failed validation: NOTHING appended; first bad row's code in
// *out_n's row slot... see binding), -1 (I/O error). Outputs (malloc'd,
// el_free): ids = n*16 raw bytes (zeroed for failed rows), codes = n
// RowErr bytes, names/etypes = NUL-joined per-row event names and
// entity types (for stats + whitelists).
int64_t el_append_json(void* h, const uint8_t* body, uint64_t nbytes,
                       int64_t now_us, int32_t strict,
                       uint8_t** out_ids, uint8_t** out_codes,
                       uint8_t** out_names, uint64_t* out_names_bytes,
                       uint8_t** out_etypes, uint64_t* out_etypes_bytes,
                       int64_t* out_n) {
  Log* log = static_cast<Log*>(h);
  *out_ids = nullptr;
  *out_codes = nullptr;
  *out_names = nullptr;
  *out_etypes = nullptr;
  *out_n = 0;
  if (!valid_utf8(body, nbytes)) return -3;  // json.loads parity
  JsonCur c{reinterpret_cast<const char*>(body),
            reinterpret_cast<const char*>(body) + nbytes};
  if (!c.lit('[')) return -3;

  std::mt19937_64 rng(std::random_device{}() ^
                      static_cast<uint64_t>(now_us) ^
                      reinterpret_cast<uintptr_t>(h));
  std::vector<uint8_t> buf;
  buf.reserve(nbytes + (nbytes >> 2));
  std::vector<uint8_t> ids;
  std::vector<uint8_t> codes;
  std::string names_join, etypes_join;
  int64_t n_valid = 0;

  bool first = true;
  while (true) {
    if (!c.ws()) return -3;
    if (*c.p == ']') {
      ++c.p;
      break;
    }
    if (!first) {
      if (*c.p != ',') return -3;
      ++c.p;
      // a comma commits to another element: '[{...},]' is a json.loads
      // error and must not be acked (strict RFC-8259, ADVICE r4 family)
      if (!c.ws()) return -3;
      if (*c.p == ']') return -3;
    }
    first = false;
    if (c.peek() != '{') {
      // non-object element: a per-row 400 like the Python path's
      // "event must be a JSON object", never a whole-batch failure
      if (!skip_value(c, nullptr)) return -3;
      codes.push_back(kRowNotObject);
      names_join.push_back('\0');
      etypes_join.push_back('\0');
      if (strict) {
        *out_n = static_cast<int64_t>(codes.size());
        uint8_t* cd = static_cast<uint8_t*>(malloc(codes.size()));
        if (cd) memcpy(cd, codes.data(), codes.size());
        *out_codes = cd;
        return -4;
      }
      ids.insert(ids.end(), 16, 0);
      continue;
    }
    JsonRow row;
    int rc = parse_row(c, &row);
    if (rc == -2) return -2;
    uint8_t code = rc > 0 ? static_cast<uint8_t>(rc) : validate_row(row);
    codes.push_back(code);
    names_join += row.event;
    names_join.push_back('\0');
    etypes_join += row.etype;
    etypes_join.push_back('\0');
    if (code != kRowOk) {
      if (strict) {
        *out_n = static_cast<int64_t>(codes.size());
        // surface the code via the codes buffer in strict mode too
        uint8_t* cd = static_cast<uint8_t*>(malloc(codes.size()));
        if (cd) memcpy(cd, codes.data(), codes.size());
        *out_codes = cd;
        return -4;
      }
      ids.insert(ids.end(), 16, 0);
      continue;
    }
    // pack the wire record (format documented at the top of this file)
    std::string extra;
    {
      auto add = [&extra](const char* k, std::string_view raw) {
        extra += extra.empty() ? "{" : ",";
        extra += '"';
        extra += k;
        extra += "\":";
        extra.append(raw.data(), raw.size());
      };
      if (row.has_time && row.t_off_us != 0) add("et", row.time_raw);
      if (row.has_ctime && row.c_off_us != 0) add("ct", row.ctime_raw);
      if (!row.props_raw.empty()) add("p", row.props_raw);
      if (!row.tags_raw.empty()) add("t", row.tags_raw);
      if (!row.prid_raw.empty()) add("pr", row.prid_raw);
      if (!extra.empty()) extra += '}';
    }
    int64_t t_us = row.has_time ? row.t_us : now_us;
    int64_t c_us = row.has_ctime ? row.c_us : now_us;
    uint32_t l_ev = static_cast<uint32_t>(row.event.size());
    uint32_t l_et = static_cast<uint32_t>(row.etype.size());
    uint32_t l_ei = static_cast<uint32_t>(row.eid.size());
    uint32_t l_tt = row.has_ttype ? static_cast<uint32_t>(row.ttype.size()) : 0;
    uint32_t l_ti = row.has_tid ? static_cast<uint32_t>(row.tid.size()) : 0;
    uint32_t l_ex = static_cast<uint32_t>(extra.size());
    uint32_t rec_len = kHeaderLen + l_ev + l_et + l_ei + l_tt + l_ti + l_ex;
    size_t base = buf.size();
    buf.resize(base + 4 + rec_len);
    uint8_t* p = buf.data() + base;
    memcpy(p, &rec_len, 4);
    p += 4;
    uint64_t id_hi = rng(), id_lo = rng();
    memcpy(p, &id_hi, 8);
    memcpy(p + 8, &id_lo, 8);
    ids.insert(ids.end(), p, p + 16);
    memcpy(p + 16, &t_us, 8);
    memcpy(p + 24, &c_us, 8);
    uint16_t u16;
    u16 = static_cast<uint16_t>(l_ev); memcpy(p + 32, &u16, 2);
    u16 = static_cast<uint16_t>(l_et); memcpy(p + 34, &u16, 2);
    u16 = static_cast<uint16_t>(l_ei); memcpy(p + 36, &u16, 2);
    u16 = row.has_ttype ? static_cast<uint16_t>(l_tt) : kAbsent;
    memcpy(p + 38, &u16, 2);
    u16 = row.has_tid ? static_cast<uint16_t>(l_ti) : kAbsent;
    memcpy(p + 40, &u16, 2);
    memcpy(p + 42, &l_ex, 4);
    uint8_t* s = p + kHeaderLen;
    memcpy(s, row.event.data(), l_ev); s += l_ev;
    memcpy(s, row.etype.data(), l_et); s += l_et;
    memcpy(s, row.eid.data(), l_ei); s += l_ei;
    if (row.has_ttype) { memcpy(s, row.ttype.data(), l_tt); s += l_tt; }
    if (row.has_tid) { memcpy(s, row.tid.data(), l_ti); s += l_ti; }
    if (l_ex) memcpy(s, extra.data(), l_ex);
    ++n_valid;
  }
  if (c.ws()) return -3;  // trailing garbage after the array

  int64_t n_rows = static_cast<int64_t>(codes.size());
  if (n_valid > 0) {
    int64_t appended =
        append_packed(log, buf.data(), buf.size(), n_valid, /*fresh_ids=*/true);
    if (appended != n_valid) return -1;
  }
  uint8_t* oi = static_cast<uint8_t*>(malloc(ids.size() ? ids.size() : 1));
  uint8_t* oc = static_cast<uint8_t*>(malloc(codes.size() ? codes.size() : 1));
  uint8_t* on = static_cast<uint8_t*>(
      malloc(names_join.size() ? names_join.size() : 1));
  uint8_t* oe = static_cast<uint8_t*>(
      malloc(etypes_join.size() ? etypes_join.size() : 1));
  if (!oi || !oc || !on || !oe) {
    free(oi); free(oc); free(on); free(oe);
    return -1;
  }
  memcpy(oi, ids.data(), ids.size());
  memcpy(oc, codes.data(), codes.size());
  memcpy(on, names_join.data(), names_join.size());
  memcpy(oe, etypes_join.data(), etypes_join.size());
  *out_ids = oi;
  *out_codes = oc;
  *out_names = on;
  *out_names_bytes = names_join.size();
  *out_etypes = oe;
  *out_etypes_bytes = etypes_join.size();
  *out_n = n_rows;
  return n_valid;
}

// Vectorized row-lane append — the native bulk call behind
// EventLogEventStore.insert_batch's fast lane. The Python side hands
// over COLUMN streams (per-field concatenated bytes + exact prefix
// offsets, times as int64 arrays, presence flags, ids as n*16 raw
// bytes) assembled with numpy/bytes-join at C speed; this call packs
// every wire record and appends them under ONE lock + (optional) one
// fsync with the GIL released — replacing the per-row struct.pack +
// join Python loop that made insert_batch ~30x slower than the
// columnar bulk lane (r03).
//
// ``flags`` bit0 = has targetEntityType, bit1 = has targetEntityId.
// Returns rows appended, -1 on I/O error, -2 when a string field
// exceeds the u16 wire limit (the caller maps it to the same error
// the struct.pack('H') overflow used to raise).
int64_t el_append_rows(
    void* h, int64_t n, const uint8_t* ids,
    const int64_t* times_us, const int64_t* ctimes_us,
    const uint8_t* flags,
    const uint8_t* ev_b, const uint64_t* ev_off,
    const uint8_t* et_b, const uint64_t* et_off,
    const uint8_t* ei_b, const uint64_t* ei_off,
    const uint8_t* tt_b, const uint64_t* tt_off,
    const uint8_t* ti_b, const uint64_t* ti_off,
    const uint8_t* ex_b, const uint64_t* ex_off,
    int32_t fresh_ids) {
  Log* log = static_cast<Log*>(h);
  uint64_t total = 0;
  for (int64_t r = 0; r < n; ++r) {
    uint64_t l_ev = ev_off[r + 1] - ev_off[r];
    uint64_t l_et = et_off[r + 1] - et_off[r];
    uint64_t l_ei = ei_off[r + 1] - ei_off[r];
    bool has_tt = flags[r] & 1, has_ti = flags[r] & 2;
    uint64_t l_tt = has_tt ? tt_off[r + 1] - tt_off[r] : 0;
    uint64_t l_ti = has_ti ? ti_off[r + 1] - ti_off[r] : 0;
    uint64_t l_ex = ex_off[r + 1] - ex_off[r];
    if (l_ev >= kAbsent || l_et >= kAbsent || l_ei >= kAbsent ||
        l_tt >= kAbsent || l_ti >= kAbsent || l_ex >= (1ULL << 32))
      return -2;
    total += 4 + kHeaderLen + l_ev + l_et + l_ei + l_tt + l_ti + l_ex;
  }
  std::vector<uint8_t> buf(total);
  uint8_t* p = buf.data();
  for (int64_t r = 0; r < n; ++r) {
    uint32_t l_ev = static_cast<uint32_t>(ev_off[r + 1] - ev_off[r]);
    uint32_t l_et = static_cast<uint32_t>(et_off[r + 1] - et_off[r]);
    uint32_t l_ei = static_cast<uint32_t>(ei_off[r + 1] - ei_off[r]);
    bool has_tt = flags[r] & 1, has_ti = flags[r] & 2;
    uint32_t l_tt = has_tt ? static_cast<uint32_t>(tt_off[r + 1] - tt_off[r]) : 0;
    uint32_t l_ti = has_ti ? static_cast<uint32_t>(ti_off[r + 1] - ti_off[r]) : 0;
    uint32_t l_ex = static_cast<uint32_t>(ex_off[r + 1] - ex_off[r]);
    uint32_t rec_len = kHeaderLen + l_ev + l_et + l_ei + l_tt + l_ti + l_ex;
    memcpy(p, &rec_len, 4);
    p += 4;
    memcpy(p, ids + r * 16, 16);
    memcpy(p + 16, &times_us[r], 8);
    memcpy(p + 24, &ctimes_us[r], 8);
    uint16_t u16;
    u16 = static_cast<uint16_t>(l_ev); memcpy(p + 32, &u16, 2);
    u16 = static_cast<uint16_t>(l_et); memcpy(p + 34, &u16, 2);
    u16 = static_cast<uint16_t>(l_ei); memcpy(p + 36, &u16, 2);
    u16 = has_tt ? static_cast<uint16_t>(l_tt) : kAbsent;
    memcpy(p + 38, &u16, 2);
    u16 = has_ti ? static_cast<uint16_t>(l_ti) : kAbsent;
    memcpy(p + 40, &u16, 2);
    memcpy(p + 42, &l_ex, 4);
    uint8_t* s = p + kHeaderLen;
    memcpy(s, ev_b + ev_off[r], l_ev); s += l_ev;
    memcpy(s, et_b + et_off[r], l_et); s += l_et;
    memcpy(s, ei_b + ei_off[r], l_ei); s += l_ei;
    if (has_tt) { memcpy(s, tt_b + tt_off[r], l_tt); s += l_tt; }
    if (has_ti) { memcpy(s, ti_b + ti_off[r], l_ti); s += l_ti; }
    if (l_ex) memcpy(s, ex_b + ex_off[r], l_ex);
    p += rec_len;
  }
  return append_packed(log, buf.data(), total, n, fresh_ids != 0);
}

// O(1) content fingerprint of the log: (generation, log bytes, record
// count, tombstone count). An append-only log + monotonically renamed
// compaction generations means this quadruple changes whenever the
// data does — the cheap cache key the binned-layout cache uses to skip
// re-reading 20M rows on retrain-with-unchanged-data (the HBase
// region-sequence-id role).
void el_fingerprint(void* h, uint64_t out[4]) {
  Log* log = static_cast<Log*>(h);
  std::shared_lock lk(log->mu);
  out[0] = log->generation;
  out[1] = log->file_size;
  out[2] = log->recs.size();
  out[3] = log->tombs.size();
}

int el_delete(void* h, const uint8_t* id16) {
  Log* log = static_cast<Log*>(h);
  std::unique_lock lk(log->mu);
  if (log->broken) return -1;
  log->ensure_id_index();
  std::string id(reinterpret_cast<const char*>(id16), 16);
  auto it = log->by_id.find(id);
  if (it == log->by_id.end()) return 0;
  // cutoff = current end of log: masks every existing record with this
  // id, while a future re-insert (offset >= cutoff) is live again
  uint8_t entry[24];
  memcpy(entry, id16, 16);
  memcpy(entry + 16, &log->file_size, 8);
  if (write(log->tomb_fd, entry, 24) != 24) return -1;
  if (log->fsync_on_append) fdatasync(log->tomb_fd);
  uint64_t& slot = log->tombs[id];
  if (log->file_size > slot) slot = log->file_size;
  log->by_id.erase(it);
  return 1;
}

// Copies the record with the given id into *out (u32 len + payload).
// Returns total bytes, 0 if absent, -1 on error.
int64_t el_get(void* h, const uint8_t* id16, uint8_t** out) {
  Log* log = static_cast<Log*>(h);
  {
    std::unique_lock ul(log->mu);
    if (log->broken) return -1;
    log->ensure_id_index();
  }
  std::shared_lock lk(log->mu);
  if (log->broken) return -1;
  auto it = log->by_id.find(std::string(reinterpret_cast<const char*>(id16), 16));
  if (it == log->by_id.end()) return 0;
  const RecMeta& m = log->recs[it->second];
  uint64_t total = 4 + m.len;
  uint8_t* buf = static_cast<uint8_t*>(malloc(total));
  if (!buf) return -1;
  memcpy(buf, log->map + m.offset, total);
  *out = buf;
  return static_cast<int64_t>(total);
}

// Filtered scan with PEvents.find semantics: half-open [start, until)
// time window, hash-prefiltered string matches confirmed byte-wise,
// results ordered by (event_time, creation_time, arrival), optional
// reverse + limit. Output: concatenated records; returns the count.
int64_t el_find(void* h, const FindReq* req, uint8_t** out, uint64_t* out_bytes) {
  Log* log = static_cast<Log*>(h);
  ensure_index_for_scan(log);
  std::shared_lock lk(log->mu);
  if (log->broken) return -1;

  std::vector<uint64_t> hits;
  collect_hits(log, req, &hits);

  uint64_t total = 0;
  for (uint64_t i : hits) total += 4 + log->recs[i].len;
  uint8_t* buf = total ? static_cast<uint8_t*>(malloc(total)) : nullptr;
  if (total && !buf) return -1;
  uint64_t w = 0;
  for (uint64_t i : hits) {
    const RecMeta& m = log->recs[i];
    memcpy(buf + w, log->map + m.offset, 4 + m.len);
    w += 4 + m.len;
  }
  *out = buf;
  *out_bytes = total;
  return static_cast<int64_t>(hits.size());
}

// Columnar filtered scan: the bulk training-read path (the role of the
// reference's region-parallel HBase scans feeding RDDs,
// hbase/HBPEvents.scala:48) — matching events come back dict-encoded
// (entity id / target id / event name as int32 codes + concatenated
// dictionaries with exact prefix offsets, first-seen order) plus one
// numeric property extracted from the record's JSON extra
// (`value_prop`; NaN when absent), so a 20M-event read never
// materializes per-event Python objects. Offsets (n_x + 1 uint64s per
// dictionary) make ids containing ANY byte — including NUL — round-trip
// exactly, matching the npz wire format of the REST tier.
// Output arrays are malloc'd; caller frees each with el_free. Rows with
// no target id get tgt_code = -1. Returns the row count, or -1.
int64_t el_find_columnar(
    void* h, const FindReq* req, const char* value_prop, int32_t time_ordered,
    int32_t** ent_codes_out, int32_t** tgt_codes_out,
    int32_t** name_codes_out, double** values_out, int64_t** times_us_out,
    uint8_t** ent_dict_out, uint64_t* ent_dict_bytes, int64_t* n_ent,
    uint8_t** tgt_dict_out, uint64_t* tgt_dict_bytes, int64_t* n_tgt,
    uint8_t** name_dict_out, uint64_t* name_dict_bytes, int64_t* n_names,
    uint64_t** ent_offsets_out, uint64_t** tgt_offsets_out,
    uint64_t** name_offsets_out) {
  Log* log = static_cast<Log*>(h);
  ensure_index_for_scan(log);
  std::shared_lock lk(log->mu);
  if (log->broken) return -1;

  const double nan = std::numeric_limits<double>::quiet_NaN();
  DictEncoder ents, tgts, names;
  ents.codes.reserve(1 << 16);
  tgts.codes.reserve(1 << 16);
  std::vector<int32_t> ent_v, tgt_v, name_v;
  std::vector<double> val_v;
  std::vector<int64_t> time_v;
  // no up-front reserve sized to the log: a selective scan would commit
  // ~28 B/record regardless of matches; amortized growth is fine

  auto emit = [&](const Header& hd) {
    ent_v.push_back(ents.encode(hd.eid, hd.len_eid));
    tgt_v.push_back(hd.tid ? tgts.encode(hd.tid, hd.len_tid) : -1);
    name_v.push_back(names.encode(hd.event, hd.len_event));
    time_v.push_back(hd.time_us);
    val_v.push_back(value_prop ? header_value(hd, value_prop) : nan);
  };

  if (time_ordered || req->limit >= 0) {
    // order (and therefore limit) needs the full hit set first
    std::vector<uint64_t> hits;
    collect_hits(log, req, &hits);
    Header hd;
    for (uint64_t i : hits) {
      parse(log->map + log->recs[i].offset + 4, log->recs[i].len, &hd);
      emit(hd);
    }
  } else {
    // fused fast path (bulk training reads): filter + encode in ONE
    // pass, records in log order, no sort — a 20M-row scan parses each
    // record exactly once (single- or multi-threaded, see fused_scan)
    fused_scan(log, req, value_prop, /*want_times=*/true,
               &ents, &tgts, &names,
               &ent_v, &tgt_v, &name_v, &val_v, &time_v);
  }

  return finish_columns(
      ents, tgts, names, ent_v, tgt_v, name_v, val_v, time_v,
      ent_codes_out, tgt_codes_out, name_codes_out, values_out, times_us_out,
      ent_dict_out, ent_dict_bytes, n_ent,
      tgt_dict_out, tgt_dict_bytes, n_tgt,
      name_dict_out, name_dict_bytes, n_names,
      ent_offsets_out, tgt_offsets_out, name_offsets_out);
}

// Sequence-offset columnar read — the streaming delta lane (ROADMAP
// item C): live records [since_rec, end) of generation ``since_gen``
// matching ``req``, dict-encoded like el_find_columnar but in ARRIVAL
// order with no sort and no limit (the tailer's contract is "exactly
// the live rows appended since the cursor"). The advancing cursor
// comes back as (*out_gen, *out_rec) = (generation, record count) —
// the same primitives el_fingerprint exposes — so a cursor survives
// process restarts: reopening replays/loads the index to the same
// record count (a torn tail truncates PAST records away, which the
// past-the-end check below turns into a rebase, never silent loss).
// A cursor from another generation (a compaction renumbered records)
// or past the current end (a crash dropped unsynced appends) cannot be
// mapped onto this log: the scan restarts from record 0 with
// *out_rebased = 1, telling the caller these rows are a RESYNC of the
// whole live set, not a delta.
int64_t el_find_columnar_since(
    void* h, const FindReq* req, const char* value_prop,
    uint64_t since_gen, uint64_t since_rec,
    uint64_t* out_gen, uint64_t* out_rec, int32_t* out_rebased,
    int32_t** ent_codes_out, int32_t** tgt_codes_out,
    int32_t** name_codes_out, double** values_out, int64_t** times_us_out,
    uint8_t** ent_dict_out, uint64_t* ent_dict_bytes, int64_t* n_ent,
    uint8_t** tgt_dict_out, uint64_t* tgt_dict_bytes, int64_t* n_tgt,
    uint8_t** name_dict_out, uint64_t* name_dict_bytes, int64_t* n_names,
    uint64_t** ent_offsets_out, uint64_t** tgt_offsets_out,
    uint64_t** name_offsets_out) {
  Log* log = static_cast<Log*>(h);
  ensure_index_for_scan(log);
  std::shared_lock lk(log->mu);
  if (log->broken) return -1;

  uint64_t start = since_rec;
  *out_rebased = 0;
  if (since_gen != log->generation || since_rec > log->recs.size()) {
    start = 0;
    *out_rebased = 1;
  }
  const double nan = std::numeric_limits<double>::quiet_NaN();
  DictEncoder ents, tgts, names;
  std::vector<int32_t> ent_v, tgt_v, name_v;
  std::vector<double> val_v;
  std::vector<int64_t> time_v;
  FilterCtx ctx = make_filter_ctx(req);
  Header hd;
  const uint64_t nrec = log->recs.size();
  for (uint64_t i = start; i < nrec; ++i) {
    if (!match_rec(log, req, ctx, i, &hd)) continue;
    ent_v.push_back(ents.encode(hd.eid, hd.len_eid));
    tgt_v.push_back(hd.tid ? tgts.encode(hd.tid, hd.len_tid) : -1);
    name_v.push_back(names.encode(hd.event, hd.len_event));
    time_v.push_back(hd.time_us);
    val_v.push_back(value_prop ? header_value(hd, value_prop) : nan);
  }
  *out_gen = log->generation;
  *out_rec = nrec;
  return finish_columns(
      ents, tgts, names, ent_v, tgt_v, name_v, val_v, time_v,
      ent_codes_out, tgt_codes_out, name_codes_out, values_out, times_us_out,
      ent_dict_out, ent_dict_bytes, n_ent,
      tgt_dict_out, tgt_dict_bytes, n_tgt,
      name_dict_out, name_dict_bytes, n_names,
      ent_offsets_out, tgt_offsets_out, name_offsets_out);
}

// Columnar bulk append: the native ingest path behind pio import /
// insert_columnar (the role of the reference's PEvents.write RDD bulk
// writes, hbase/HBPEvents.scala:124) — rows arrive dict-encoded
// (codes + '\0'-joined vocab with prefix offsets) and are packed into
// wire records in C++, so a 20M-event ingest never builds per-event
// Python objects. Event ids are fresh random 16-byte ids; out_ids
// (optional, n*16 bytes caller-allocated) receives them. `values[i]`
// NaN means "no property"; otherwise extra = {"p":{"<value_prop>":v}}.
// Returns rows appended, or -1.
int64_t el_append_columnar(
    void* h, int64_t n,
    const char* entity_type, const char* target_entity_type,
    const char* value_prop,
    const uint8_t* ent_dict, const uint64_t* ent_offsets, int64_t n_ent,
    const uint8_t* tgt_dict, const uint64_t* tgt_offsets, int64_t n_tgt,
    const uint8_t* name_dict, const uint64_t* name_offsets, int64_t n_names,
    const int32_t* ent_codes, const int32_t* tgt_codes,
    const int32_t* name_codes, const int64_t* times_us,
    const double* values, uint8_t* out_ids) {
  Log* log = static_cast<Log*>(h);
  size_t l_etype = strlen(entity_type);
  size_t l_ttype = target_entity_type ? strlen(target_entity_type) : 0;
  size_t l_prop = value_prop ? strlen(value_prop) : 0;
  // u16 header fields: any string length >= 0xFFFF (the kAbsent
  // sentinel) would wrap or alias the framing — fail the whole batch,
  // mirroring the Python row path where struct.pack('H') raises
  if (l_etype >= kAbsent || l_ttype >= kAbsent) return -1;

  int64_t now_us;
  {
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    now_us = static_cast<int64_t>(ts.tv_sec) * 1000000 + ts.tv_nsec / 1000;
  }
  std::mt19937_64 rng(std::random_device{}() ^
                      static_cast<uint64_t>(now_us) ^
                      reinterpret_cast<uintptr_t>(h));

  std::vector<uint8_t> buf;
  buf.reserve(static_cast<size_t>(n) * 96);
  char extra[96];
  std::unordered_map<double, std::string> fmt_cache;
  for (int64_t r = 0; r < n; ++r) {
    int32_t ec = ent_codes[r];
    if (ec < 0 || ec >= n_ent) return -1;
    const uint8_t* eid = ent_dict + ent_offsets[ec];
    uint32_t l_eid = static_cast<uint32_t>(ent_offsets[ec + 1] - ent_offsets[ec]);
    if (l_eid >= kAbsent) return -1;
    int32_t tc = tgt_codes ? tgt_codes[r] : -1;
    const uint8_t* tid = nullptr;
    uint32_t l_tid = 0;
    if (tc >= 0) {
      if (tc >= n_tgt || !target_entity_type) return -1;
      tid = tgt_dict + tgt_offsets[tc];
      l_tid = static_cast<uint32_t>(tgt_offsets[tc + 1] - tgt_offsets[tc]);
      if (l_tid >= kAbsent) return -1;
    }
    int32_t nc = name_codes[r];
    if (nc < 0 || nc >= n_names) return -1;
    const uint8_t* name = name_dict + name_offsets[nc];
    uint32_t l_name = static_cast<uint32_t>(name_offsets[nc + 1] - name_offsets[nc]);
    if (l_name >= kAbsent) return -1;

    uint32_t l_extra = 0;
    const char* extra_src = extra;
    if (value_prop && values && values[r] == values[r]) {  // not NaN
      // ratings repeat from a tiny value set; format each distinct
      // double once (snprintf %.17g is ~300ns, the cache ~30ns)
      auto it = fmt_cache.find(values[r]);
      if (it == fmt_cache.end()) {
        int w = snprintf(extra, sizeof(extra), "{\"p\":{\"%s\":%.17g}}",
                         value_prop, values[r]);
        if (w <= 0 || static_cast<size_t>(w) >= sizeof(extra)) return -1;
        it = fmt_cache.emplace(values[r], std::string(extra, w)).first;
      }
      extra_src = it->second.data();
      l_extra = static_cast<uint32_t>(it->second.size());
    }

    bool has_target = tc >= 0;
    uint32_t rec_len = kHeaderLen + l_name + l_etype + l_eid +
                       (has_target ? l_ttype + l_tid : 0) + l_extra;
    size_t base = buf.size();
    buf.resize(base + 4 + rec_len);
    uint8_t* p = buf.data() + base;
    memcpy(p, &rec_len, 4);
    p += 4;
    uint64_t id_hi = rng(), id_lo = rng();
    memcpy(p, &id_hi, 8);
    memcpy(p + 8, &id_lo, 8);
    if (out_ids) memcpy(out_ids + r * 16, p, 16);
    memcpy(p + 16, &times_us[r], 8);
    memcpy(p + 24, &now_us, 8);
    uint16_t u16;
    u16 = static_cast<uint16_t>(l_name); memcpy(p + 32, &u16, 2);
    u16 = static_cast<uint16_t>(l_etype); memcpy(p + 34, &u16, 2);
    u16 = static_cast<uint16_t>(l_eid); memcpy(p + 36, &u16, 2);
    u16 = has_target ? static_cast<uint16_t>(l_ttype) : kAbsent; memcpy(p + 38, &u16, 2);
    u16 = has_target ? static_cast<uint16_t>(l_tid) : kAbsent; memcpy(p + 40, &u16, 2);
    memcpy(p + 42, &l_extra, 4);
    uint8_t* s = p + kHeaderLen;
    memcpy(s, name, l_name); s += l_name;
    memcpy(s, entity_type, l_etype); s += l_etype;
    memcpy(s, eid, l_eid); s += l_eid;
    if (has_target) {
      memcpy(s, target_entity_type, l_ttype); s += l_ttype;
      memcpy(s, tid, l_tid); s += l_tid;
    }
    if (l_extra) memcpy(s, extra_src, l_extra);
  }
  // records were built here (fresh ids) — no validation pass, lazy id index
  return append_packed(log, buf.data(), buf.size(), n, /*fresh_ids=*/true);
}

namespace {

double mono_sec() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<double>(ts.tv_sec) + ts.tv_nsec * 1e-9;
}

}  // namespace

// Out-params of el_bin_columnar (mirrored by a ctypes Structure in the
// Python binding — every field is 8 bytes, so the layout is
// padding-free). All pointers are malloc'd/aligned outputs the caller
// frees via el_free; zeroed on entry and on error.
struct BinColumnarOut {
  binlayout::CSide user_side;   // grouped by entity id
  binlayout::CSide item_side;   // grouped by target id
  uint8_t* ent_dict;            // concatenated entity-id bytes
  uint64_t* ent_offsets;        // n_ent + 1 exact prefix offsets
  uint8_t* tgt_dict;
  uint64_t* tgt_offsets;
  int32_t* hold_u;              // held-out COO (skip_mod rows)
  int32_t* hold_i;
  float* hold_v;
  uint64_t ent_dict_bytes;
  uint64_t tgt_dict_bytes;
  int64_t n_ent;
  int64_t n_tgt;
  int64_t n_hold;
  int64_t n_rows;               // kept (binned) interaction rows
  double scan_sec;              // filter+encode+vocab-dump wall time
  double bin_sec;               // value-resolve + plan + fill wall time
};

static void free_bin_columnar(BinColumnarOut* out) {
  binlayout::SideOut u{out->user_side.idx_lo, out->user_side.idx_hi,
                       out->user_side.val_u8, out->user_side.val_f32,
                       out->user_side.mask, out->user_side.seg,
                       out->user_side.counts};
  u.free_all();
  binlayout::SideOut i{out->item_side.idx_lo, out->item_side.idx_hi,
                       out->item_side.val_u8, out->item_side.val_f32,
                       out->item_side.mask, out->item_side.seg,
                       out->item_side.counts};
  i.free_all();
  free(out->ent_dict); free(out->ent_offsets);
  free(out->tgt_dict); free(out->tgt_offsets);
  free(out->hold_u); free(out->hold_i); free(out->hold_v);
  memset(out, 0, sizeof(*out));
}

// The fused ingest->bin lane (zero-copy data path): ONE call takes the
// mmap'd log to both sides' device-ready compressed layouts.
//
//   scan     fused filter + dict-encode in log order (the same code
//            path el_find_columnar's bulk reads use), vocabularies
//            dumped under the shared lock
//   resolve  per-row float32 value: per-event-name overrides (the
//            "buy means rating 4.0" rule, resolved against the name
//            dictionary), NaN -> 0.0 otherwise — exactly the Python
//            template's nan_to_num + np.where
//   filter   rows without a target id are dropped (read_interactions
//            semantics); ``skip_mod > 0`` holds OUT every row whose
//            kept-ordinal % skip_mod == skip_rem (the bench's 5%
//            held-out split) and returns those as COO for evaluation
//   bin      binlayout plan + single-pass compressed fill per side
//            (group axis = entity for user_side, target for
//            item_side), outside the lock so a 20M-row bin never
//            blocks writers
//
// No per-row Python objects, no intermediate f32 val/mask arrays, no
// Event materialization anywhere. Returns kept row count, or -1
// (error/bad index), -2 (allocation), -3 (>24-bit index). seg_len -1 =
// auto; max_len_* -1 = uncapped.
int64_t el_bin_columnar(
    void* h, const FindReq* req, const char* value_prop,
    const char* override_names, const double* override_values,
    int32_t n_overrides, int64_t skip_mod, int64_t skip_rem,
    int64_t seg_len, int64_t max_len_user, int64_t max_len_item,
    int64_t n_shards, int64_t block_size, double row_cost_slots,
    BinColumnarOut* out) {
  Log* log = static_cast<Log*>(h);
  memset(out, 0, sizeof(*out));
  double t0 = mono_sec();
  ensure_index_for_scan(log);

  std::vector<int32_t> ent_v, tgt_v, name_v;
  std::vector<double> val_v;
  std::vector<int64_t> time_v;  // unused (want_times=false)
  std::vector<double> override_by_code;
  int64_t n_ent = 0, n_tgt = 0;
  {
    std::shared_lock lk(log->mu);
    if (log->broken) return -1;
    DictEncoder ents, tgts, names;
    ents.codes.reserve(1 << 16);
    tgts.codes.reserve(1 << 16);
    fused_scan(log, req, value_prop, /*want_times=*/false,
               &ents, &tgts, &names,
               &ent_v, &tgt_v, &name_v, &val_v, &time_v);
    // vocabularies + override resolution must happen under the lock:
    // the encoders key string_views into the mmap'd log
    out->ent_dict = ents.dump(&out->ent_dict_bytes, &out->ent_offsets);
    out->tgt_dict = tgts.dump(&out->tgt_dict_bytes, &out->tgt_offsets);
    if (!out->ent_dict || !out->tgt_dict) {
      free_bin_columnar(out);
      return -2;
    }
    n_ent = static_cast<int64_t>(ents.order.size());
    n_tgt = static_cast<int64_t>(tgts.order.size());
    const double nan = std::numeric_limits<double>::quiet_NaN();
    override_by_code.assign(names.order.size(), nan);
    const char* p = override_names;
    for (int32_t i = 0; i < n_overrides; ++i) {
      size_t l = strlen(p);
      auto it = names.codes.find(std::string_view(p, l));
      if (it != names.codes.end()) override_by_code[it->second] = override_values[i];
      p += l + 1;
    }
  }
  out->n_ent = n_ent;
  out->n_tgt = n_tgt;
  out->scan_sec = mono_sec() - t0;
  t0 = mono_sec();

  // resolve + filter into the kept COO (and the held-out COO)
  const int64_t n_scanned = static_cast<int64_t>(ent_v.size());
  std::vector<int32_t> u_codes, i_codes;
  std::vector<float> vals;
  u_codes.reserve(n_scanned);
  i_codes.reserve(n_scanned);
  vals.reserve(n_scanned);
  std::vector<int32_t> hold_u, hold_i;
  std::vector<float> hold_v;
  int64_t ordinal = 0;
  for (int64_t k = 0; k < n_scanned; ++k) {
    int32_t tc = tgt_v[k];
    if (tc < 0) continue;  // read_interactions drops target-less rows
    double ov = override_by_code.empty()
                    ? std::numeric_limits<double>::quiet_NaN()
                    : override_by_code[name_v[k]];
    float v;
    if (ov == ov) {
      v = static_cast<float>(ov);
    } else {
      double raw = val_v[k];
      v = raw == raw ? static_cast<float>(raw) : 0.0f;  // nan_to_num
    }
    bool held = skip_mod > 0 && (ordinal % skip_mod) == skip_rem;
    ++ordinal;
    if (held) {
      hold_u.push_back(ent_v[k]);
      hold_i.push_back(tc);
      hold_v.push_back(v);
    } else {
      u_codes.push_back(ent_v[k]);
      i_codes.push_back(tc);
      vals.push_back(v);
    }
  }
  // release the scan vectors before the fill allocates its buffers
  ent_v.clear(); ent_v.shrink_to_fit();
  tgt_v.clear(); tgt_v.shrink_to_fit();
  name_v.clear(); name_v.shrink_to_fit();
  val_v.clear(); val_v.shrink_to_fit();

  const int64_t nnz = static_cast<int64_t>(u_codes.size());
  auto bin_side = [&](const std::vector<int32_t>& grp,
                      const std::vector<int32_t>& itm, int64_t n_groups,
                      int64_t max_len, binlayout::CSide* side) -> int {
    std::vector<int64_t> counts(n_groups, 0);
    for (int64_t k = 0; k < nnz; ++k) {
      if (grp[k] < 0 || grp[k] >= n_groups) return -1;
      ++counts[grp[k]];
    }
    binlayout::SidePlan plan;
    binlayout::plan_segmented(std::move(counts), n_groups, seg_len,
                              max_len, n_shards, block_size,
                              row_cost_slots, &plan);
    binlayout::SideOut so;
    int rc = binlayout::fill_compressed(
        grp.data(), itm.data(), vals.data(), nnz, plan, &so);
    if (rc != 0) {
      so.free_all();
      return rc;
    }
    binlayout::export_side(plan, &so, side);
    return 0;
  };
  int rc = bin_side(u_codes, i_codes, n_ent, max_len_user, &out->user_side);
  if (rc == 0)
    rc = bin_side(i_codes, u_codes, n_tgt, max_len_item, &out->item_side);
  if (rc != 0) {
    free_bin_columnar(out);
    return rc == -1 ? -1 : rc;
  }

  if (!hold_u.empty()) {
    out->hold_u = static_cast<int32_t*>(malloc(hold_u.size() * 4));
    out->hold_i = static_cast<int32_t*>(malloc(hold_i.size() * 4));
    out->hold_v = static_cast<float*>(malloc(hold_v.size() * 4));
    if (!out->hold_u || !out->hold_i || !out->hold_v) {
      free_bin_columnar(out);
      return -2;
    }
    memcpy(out->hold_u, hold_u.data(), hold_u.size() * 4);
    memcpy(out->hold_i, hold_i.data(), hold_i.size() * 4);
    memcpy(out->hold_v, hold_v.data(), hold_v.size() * 4);
  }
  out->n_hold = static_cast<int64_t>(hold_u.size());
  out->n_rows = nnz;
  out->bin_sec = mono_sec() - t0;
  return nnz;
}

// Compaction: rewrite the log keeping only LIVE records (drops
// tombstone-masked records and superseded duplicate ids — the space
// HBase reclaims with major compaction), truncate the tombstone file,
// and persist a fresh index snapshot. Record order is preserved.
// Returns the number of records dropped, or -1; before/after log byte
// sizes come back via the out params.
int64_t el_compact(void* h, uint64_t* before_bytes, uint64_t* after_bytes) {
  Log* log = static_cast<Log*>(h);
  std::unique_lock lk(log->mu);
  if (log->broken) return -1;
  log->ensure_id_index();
  *before_bytes = log->file_size;

  if (log->all_live()) {  // nothing to drop
    *after_bytes = log->file_size;
    if (log->file_size != log->snapshot_covered) write_index_snapshot(log);
    return 0;
  }

  uint64_t new_gen = log->generation + 1;
  std::string new_log_path = log_path_for(log->dir, new_gen);
  std::string new_tomb_path = tomb_path_for(log->dir, new_gen);
  int nfd = open(new_log_path.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (nfd < 0) return -1;

  std::vector<RecMeta> new_recs;
  std::unordered_map<std::string, uint64_t> new_by_id;
  new_recs.reserve(log->by_id.size());
  new_by_id.reserve(log->by_id.size());
  uint64_t new_size = 0;
  int64_t dropped = 0;
  bool ok = true;
  // buffered copy: records are contiguous runs of live bytes most of
  // the time; coalesce adjacent live records into one write
  uint64_t run_start = 0, run_len = 0;
  auto flush_run = [&]() {
    if (run_len && ok) ok = write_all(nfd, log->map + run_start, run_len);
    run_len = 0;
  };
  Header hd;
  for (uint64_t i = 0; i < log->recs.size() && ok; ++i) {
    const RecMeta& m = log->recs[i];
    parse(log->map + m.offset + 4, m.len, &hd);
    std::string id(reinterpret_cast<const char*>(hd.id), 16);
    auto it = log->by_id.find(id);
    if (it == log->by_id.end() || it->second != i) {
      ++dropped;
      flush_run();
      continue;
    }
    if (run_len == 0) run_start = m.offset;
    else if (run_start + run_len != m.offset) {
      flush_run();
      run_start = m.offset;
    }
    run_len += 4 + m.len;
    RecMeta nm = m;
    nm.offset = new_size;
    new_by_id.emplace(std::move(id), new_recs.size());
    new_recs.push_back(nm);
    new_size += 4 + m.len;
  }
  flush_run();
  if (ok) ok = fdatasync(nfd) == 0;
  close(nfd);
  // the new generation's tombstone file starts empty
  if (ok) {
    int tfd = open(new_tomb_path.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
    ok = tfd >= 0;
    if (ok) {
      ok = fdatasync(tfd) == 0;
      close(tfd);
    }
  }
  // the new generation's directory entries must be durable BEFORE the
  // commit record can name them (else CURRENT=N could survive a power
  // cut whose log.<N>.bin dirent did not)
  if (ok) ok = fsync_dir(log->dir);
  // commit point: CURRENT now names the new generation. A crash before
  // this line leaves the old generation fully intact (the new files are
  // orphans, removed on next open); a crash after it leaves the
  // compacted log with its empty tombstones — never a mix.
  if (!ok || !commit_generation(log->dir, new_gen)) {
    unlink(new_log_path.c_str());
    unlink(new_tomb_path.c_str());
    return -1;
  }
  // ...and the commit itself must be durable before the OLD generation
  // may disappear (else the old files' unlinks could persist while the
  // CURRENT rename did not, leaving CURRENT=old pointing at nothing)
  fsync_dir(log->dir);

  if (log->map) {
    munmap(log->map, log->map_size);
    log->map = nullptr;
    log->map_size = 0;
  }
  close(log->fd);
  close(log->tomb_fd);
  log->fd = open(new_log_path.c_str(), O_RDWR | O_CREAT | O_APPEND, 0644);
  log->tomb_fd = open(new_tomb_path.c_str(), O_RDWR | O_CREAT | O_APPEND, 0644);
  if (log->fd < 0 || log->tomb_fd < 0) {
    log->broken = true;
    return -1;
  }
  log->generation = new_gen;
  log->file_size = new_size;
  log->recs = std::move(new_recs);
  log->by_id = std::move(new_by_id);
  log->indexed_upto = log->recs.size();
  log->has_dupes = false;
  log->needs_id_verify = false;
  log->tombs.clear();
  log->snapshot_covered = 0;  // the on-disk snapshot is for the old gen
  if (!log->ensure_mapped()) {
    log->broken = true;
    return -1;
  }
  remove_orphan_generations(log->dir, new_gen);
  write_index_snapshot(log);
  *after_bytes = log->file_size;
  return dropped;
}

}  // extern "C"
