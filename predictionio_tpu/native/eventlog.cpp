// eventlog: append-only binary event log with in-memory index.
//
// The native data plane of the EVENTDATA storage tier — the role HBase
// plays in the reference (data/.../storage/hbase/HBEventsUtil.scala:47:
// rowkey = MD5(entity) || time || uuid, scans via partial row keys +
// column filters). Same design pressures, single-binary execution:
//   - append-only log per (app, channel), like an HBase region's WAL+store
//   - in-memory index of (time, entity-hash, name-hash) per record, so
//     filtered scans (PEvents.find semantics, storage/PEvents.scala:70)
//     touch only the index until materialization
//   - deletes are tombstones (HBase delete markers) carrying the log
//     offset at delete time, so they mask only earlier records — an id
//     re-inserted after a delete is live again
//   - single writer process: an flock(2) on <dir>/LOCK is held for the
//     handle's lifetime; a second process gets a clean open error
//     instead of silent corruption (concurrent access goes through the
//     event server REST API, as HBase clients go through the region
//     server)
//
// Record wire format (little-endian), produced by the Python binding:
//   u32  record_len            (bytes after this field)
//   u8   id[16]                (event id, raw uuid bytes)
//   i64  event_time_us         (epoch micros, UTC)
//   i64  creation_time_us
//   u16  len_event
//   u16  len_entity_type
//   u16  len_entity_id
//   u16  len_target_type       (0xFFFF = absent)
//   u16  len_target_id         (0xFFFF = absent)
//   u32  len_extra             (opaque JSON: properties/tags/prId/tz)
//   bytes: event, entity_type, entity_id, [target_type], [target_id], extra
//
// Tombstone file format: 24-byte entries, u8 id[16] + u64 cutoff_offset.
//
// Concurrency (in-process): one writer at a time (exclusive lock on
// append/delete), many readers (shared lock on find/get). The file is
// mmap'ed in 64 MiB-rounded chunks so most appends need no remap; only
// bytes below file_size are ever dereferenced.
//
// Build: g++ -O3 -std=c++17 -shared -fPIC -pthread eventlog.cpp -o _eventlog.so

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include <fcntl.h>
#include <sys/file.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kHeaderLen = 46;  // bytes after record_len, before strings
constexpr uint16_t kAbsent = 0xFFFF;
constexpr uint64_t kMapChunk = 64ULL << 20;  // mapping granularity

inline uint64_t fnv1a(const uint8_t* data, size_t n, uint64_t h = 1469598103934665603ULL) {
  for (size_t i = 0; i < n; ++i) {
    h ^= data[i];
    h *= 1099511628211ULL;
  }
  return h;
}

struct RecMeta {
  uint64_t offset;    // offset of the u32 record_len field
  uint32_t len;       // record_len
  int64_t time_us;
  int64_t ctime_us;
  uint64_t etype_hash;
  uint64_t eid_hash;
  uint64_t name_hash;
  uint64_t ttype_hash;  // 0 when absent
  uint64_t tid_hash;    // 0 when absent
  uint8_t has_target_type;
  uint8_t has_target_id;
};

struct Header {
  const uint8_t* id;
  int64_t time_us;
  int64_t ctime_us;
  uint16_t len_event, len_etype, len_eid, len_ttype, len_tid;
  uint32_t len_extra;
  const uint8_t *event, *etype, *eid, *ttype, *tid;
};

// parse one record payload (the bytes after record_len); returns false on corruption
bool parse(const uint8_t* p, uint32_t len, Header* h) {
  if (len < kHeaderLen) return false;
  h->id = p;
  memcpy(&h->time_us, p + 16, 8);
  memcpy(&h->ctime_us, p + 24, 8);
  memcpy(&h->len_event, p + 32, 2);
  memcpy(&h->len_etype, p + 34, 2);
  memcpy(&h->len_eid, p + 36, 2);
  memcpy(&h->len_ttype, p + 38, 2);
  memcpy(&h->len_tid, p + 40, 2);
  memcpy(&h->len_extra, p + 42, 4);
  uint64_t need = kHeaderLen;
  need += h->len_event + h->len_etype + h->len_eid;
  uint16_t ltt = (h->len_ttype == kAbsent) ? 0 : h->len_ttype;
  uint16_t lti = (h->len_tid == kAbsent) ? 0 : h->len_tid;
  need += ltt + lti + h->len_extra;
  if (need != len) return false;
  const uint8_t* s = p + kHeaderLen;
  h->event = s;
  s += h->len_event;
  h->etype = s;
  s += h->len_etype;
  h->eid = s;
  s += h->len_eid;
  h->ttype = (h->len_ttype == kAbsent) ? nullptr : s;
  s += ltt;
  h->tid = (h->len_tid == kAbsent) ? nullptr : s;
  return true;
}

struct Log {
  int fd = -1;
  int tomb_fd = -1;
  int lock_fd = -1;
  uint64_t file_size = 0;
  uint8_t* map = nullptr;
  uint64_t map_size = 0;
  bool broken = false;  // mapping failed after a durable append; reads error
  std::vector<RecMeta> recs;
  std::unordered_map<std::string, uint64_t> by_id;  // raw 16-byte id -> rec index
  std::unordered_map<std::string, uint64_t> tombs;  // id -> max cutoff offset
  bool fsync_on_append = false;
  mutable std::shared_mutex mu;

  ~Log() {
    if (map) munmap(map, map_size);
    if (fd >= 0) close(fd);
    if (tomb_fd >= 0) close(tomb_fd);
    if (lock_fd >= 0) close(lock_fd);  // releases the flock
  }

  // (re)map so that [0, file_size) is addressable; rounds the mapping up
  // to kMapChunk so appends rarely remap. Call with exclusive lock held.
  bool ensure_mapped() {
    if (file_size <= map_size && map) return true;
    if (file_size == 0) return true;
    uint64_t want = ((file_size + kMapChunk - 1) / kMapChunk) * kMapChunk;
    if (map) {
      munmap(map, map_size);
      map = nullptr;
      map_size = 0;
    }
    void* m = mmap(nullptr, want, PROT_READ, MAP_SHARED, fd, 0);
    if (m == MAP_FAILED) return false;
    map = static_cast<uint8_t*>(m);
    map_size = want;
    return true;
  }

  bool dead(const std::string& id, uint64_t offset) const {
    auto it = tombs.find(id);
    return it != tombs.end() && it->second > offset;
  }

  void index_record(uint64_t offset, uint32_t len, const Header& h) {
    RecMeta m;
    m.offset = offset;
    m.len = len;
    m.time_us = h.time_us;
    m.ctime_us = h.ctime_us;
    m.etype_hash = fnv1a(h.etype, h.len_etype);
    m.eid_hash = fnv1a(h.eid, h.len_eid);
    m.name_hash = fnv1a(h.event, h.len_event);
    m.has_target_type = h.ttype != nullptr;
    m.has_target_id = h.tid != nullptr;
    m.ttype_hash = h.ttype ? fnv1a(h.ttype, h.len_ttype) : 0;
    m.tid_hash = h.tid ? fnv1a(h.tid, h.len_tid) : 0;
    std::string id(reinterpret_cast<const char*>(h.id), 16);
    if (!dead(id, offset)) by_id[id] = recs.size();
    recs.push_back(m);
  }
};

struct FindReq {
  int64_t start_us;   // INT64_MIN = unbounded
  int64_t until_us;   // INT64_MAX = unbounded
  const char* entity_type;  // nullptr = no filter
  const char* entity_id;
  int32_t target_type_mode;  // 0 = no filter, 1 = must be absent, 2 = equals
  int32_t target_id_mode;
  const char* target_entity_type;
  const char* target_entity_id;
  const char* event_names;  // '\0'-joined
  int32_t n_event_names;    // 0 = no filter
  int32_t reversed;
  int64_t limit;  // -1 = all
};

bool bytes_eq(const uint8_t* a, uint32_t alen, const char* b) {
  return alen == strlen(b) && memcmp(a, b, alen) == 0;
}

}  // namespace

extern "C" {

void el_free(uint8_t* p) { free(p); }

void* el_open(const char* dir, int fsync_on_append) {
  std::string base(dir);
  if (mkdir(base.c_str(), 0755) != 0 && errno != EEXIST) return nullptr;
  auto log = std::make_unique<Log>();
  log->fsync_on_append = fsync_on_append != 0;

  // single-writer-process guard: held until el_close
  std::string lock_path = base + "/LOCK";
  log->lock_fd = open(lock_path.c_str(), O_RDWR | O_CREAT, 0644);
  if (log->lock_fd < 0) return nullptr;
  if (flock(log->lock_fd, LOCK_EX | LOCK_NB) != 0) return nullptr;

  std::string log_path = base + "/log.bin";
  log->fd = open(log_path.c_str(), O_RDWR | O_CREAT | O_APPEND, 0644);
  if (log->fd < 0) return nullptr;
  std::string tomb_path = base + "/tombstones.bin";
  log->tomb_fd = open(tomb_path.c_str(), O_RDWR | O_CREAT | O_APPEND, 0644);
  if (log->tomb_fd < 0) return nullptr;

  // load tombstones first: cutoffs decide liveness during log replay
  struct stat st;
  if (fstat(log->tomb_fd, &st) != 0) return nullptr;
  for (off_t off = 0; off + 24 <= st.st_size; off += 24) {
    uint8_t entry[24];
    if (pread(log->tomb_fd, entry, 24, off) != 24) return nullptr;
    std::string id(reinterpret_cast<const char*>(entry), 16);
    uint64_t cutoff;
    memcpy(&cutoff, entry + 16, 8);
    uint64_t& slot = log->tombs[id];
    if (cutoff > slot) slot = cutoff;
  }

  if (fstat(log->fd, &st) != 0) return nullptr;
  log->file_size = static_cast<uint64_t>(st.st_size);
  if (!log->ensure_mapped()) return nullptr;

  // replay the log into the index; a torn tail (crash mid-append) is
  // truncated away, mirroring WAL replay semantics
  uint64_t off = 0;
  while (off + 4 <= log->file_size) {
    uint32_t len;
    memcpy(&len, log->map + off, 4);
    if (off + 4 + len > log->file_size) break;  // torn tail
    Header h;
    if (!parse(log->map + off + 4, len, &h)) break;
    log->index_record(off, len, h);
    off += 4 + len;
  }
  if (off < log->file_size) {
    if (ftruncate(log->fd, off) != 0) return nullptr;
    log->file_size = off;
  }
  return log.release();
}

void el_close(void* h) { delete static_cast<Log*>(h); }

int64_t el_count(void* h) {
  Log* log = static_cast<Log*>(h);
  std::shared_lock lk(log->mu);
  return static_cast<int64_t>(log->by_id.size());
}

// Appends a batch of pre-packed records. Validates the whole batch before
// writing anything (all-or-nothing). Returns records appended, or -1.
// The append is durable even if the subsequent remap fails (the handle
// then reports errors on reads until reopened, rather than crashing).
int64_t el_append_batch(void* h, const uint8_t* buf, uint64_t nbytes) {
  Log* log = static_cast<Log*>(h);
  // validation pass (no lock needed; reads only the input)
  uint64_t off = 0;
  int64_t n = 0;
  Header hdr;
  while (off < nbytes) {
    if (off + 4 > nbytes) return -1;
    uint32_t len;
    memcpy(&len, buf + off, 4);
    if (off + 4 + len > nbytes) return -1;
    if (!parse(buf + off + 4, len, &hdr)) return -1;
    off += 4 + len;
    ++n;
  }

  std::unique_lock lk(log->mu);
  if (log->broken) return -1;
  uint64_t written = 0;
  while (written < nbytes) {
    ssize_t w = write(log->fd, buf + written, nbytes - written);
    if (w < 0) {
      // partial batch on disk: re-truncate to the pre-batch size
      if (ftruncate(log->fd, log->file_size) != 0) {}
      return -1;
    }
    written += static_cast<uint64_t>(w);
  }
  if (log->fsync_on_append) fdatasync(log->fd);

  uint64_t base = log->file_size;
  log->file_size += nbytes;
  // index from the caller's buffer (already validated) so indexing does
  // not depend on the remap succeeding
  off = 0;
  while (off < nbytes) {
    uint32_t len;
    memcpy(&len, buf + off, 4);
    Header h2;
    parse(buf + off + 4, len, &h2);
    log->index_record(base + off, len, h2);
    off += 4 + len;
  }
  if (!log->ensure_mapped()) log->broken = true;
  return n;
}

int el_delete(void* h, const uint8_t* id16) {
  Log* log = static_cast<Log*>(h);
  std::unique_lock lk(log->mu);
  std::string id(reinterpret_cast<const char*>(id16), 16);
  auto it = log->by_id.find(id);
  if (it == log->by_id.end()) return 0;
  // cutoff = current end of log: masks every existing record with this
  // id, while a future re-insert (offset >= cutoff) is live again
  uint8_t entry[24];
  memcpy(entry, id16, 16);
  memcpy(entry + 16, &log->file_size, 8);
  if (write(log->tomb_fd, entry, 24) != 24) return -1;
  if (log->fsync_on_append) fdatasync(log->tomb_fd);
  uint64_t& slot = log->tombs[id];
  if (log->file_size > slot) slot = log->file_size;
  log->by_id.erase(it);
  return 1;
}

// Copies the record with the given id into *out (u32 len + payload).
// Returns total bytes, 0 if absent, -1 on error.
int64_t el_get(void* h, const uint8_t* id16, uint8_t** out) {
  Log* log = static_cast<Log*>(h);
  std::shared_lock lk(log->mu);
  if (log->broken) return -1;
  auto it = log->by_id.find(std::string(reinterpret_cast<const char*>(id16), 16));
  if (it == log->by_id.end()) return 0;
  const RecMeta& m = log->recs[it->second];
  uint64_t total = 4 + m.len;
  uint8_t* buf = static_cast<uint8_t*>(malloc(total));
  if (!buf) return -1;
  memcpy(buf, log->map + m.offset, total);
  *out = buf;
  return static_cast<int64_t>(total);
}

// Filtered scan with PEvents.find semantics: half-open [start, until)
// time window, hash-prefiltered string matches confirmed byte-wise,
// results ordered by (event_time, creation_time, arrival), optional
// reverse + limit. Output: concatenated records; returns the count.
int64_t el_find(void* h, const FindReq* req, uint8_t** out, uint64_t* out_bytes) {
  Log* log = static_cast<Log*>(h);
  std::shared_lock lk(log->mu);
  if (log->broken) return -1;

  uint64_t etype_h = req->entity_type
      ? fnv1a(reinterpret_cast<const uint8_t*>(req->entity_type), strlen(req->entity_type))
      : 0;
  uint64_t eid_h = req->entity_id
      ? fnv1a(reinterpret_cast<const uint8_t*>(req->entity_id), strlen(req->entity_id))
      : 0;
  uint64_t ttype_h = (req->target_type_mode == 2)
      ? fnv1a(reinterpret_cast<const uint8_t*>(req->target_entity_type),
              strlen(req->target_entity_type))
      : 0;
  uint64_t tid_h = (req->target_id_mode == 2)
      ? fnv1a(reinterpret_cast<const uint8_t*>(req->target_entity_id),
              strlen(req->target_entity_id))
      : 0;
  std::vector<std::pair<uint64_t, const char*>> name_hashes;
  {
    const char* p = req->event_names;
    for (int32_t i = 0; i < req->n_event_names; ++i) {
      size_t l = strlen(p);
      name_hashes.emplace_back(fnv1a(reinterpret_cast<const uint8_t*>(p), l), p);
      p += l + 1;
    }
  }

  std::vector<uint64_t> hits;
  for (uint64_t i = 0; i < log->recs.size(); ++i) {
    const RecMeta& m = log->recs[i];
    if (m.time_us < req->start_us || m.time_us >= req->until_us) continue;
    if (req->entity_type && m.etype_hash != etype_h) continue;
    if (req->entity_id && m.eid_hash != eid_h) continue;
    if (req->target_type_mode == 1 && m.has_target_type) continue;
    if (req->target_type_mode == 2 && (!m.has_target_type || m.ttype_hash != ttype_h)) continue;
    if (req->target_id_mode == 1 && m.has_target_id) continue;
    if (req->target_id_mode == 2 && (!m.has_target_id || m.tid_hash != tid_h)) continue;
    if (req->n_event_names > 0) {
      bool any = false;
      for (const auto& nh : name_hashes) {
        if (nh.first == m.name_hash) { any = true; break; }
      }
      if (!any) continue;
    }
    // materialize the header to (a) confirm string matches byte-wise
    // (hash-collision guard), (b) drop tombstoned/superseded records:
    // a record is live only if it is the current by_id entry for its id
    Header hd;
    parse(log->map + m.offset + 4, m.len, &hd);
    auto live = log->by_id.find(std::string(reinterpret_cast<const char*>(hd.id), 16));
    if (live == log->by_id.end() || live->second != i) continue;
    if (req->entity_type && !bytes_eq(hd.etype, hd.len_etype, req->entity_type)) continue;
    if (req->entity_id && !bytes_eq(hd.eid, hd.len_eid, req->entity_id)) continue;
    if (req->target_type_mode == 2 &&
        !bytes_eq(hd.ttype, hd.len_ttype, req->target_entity_type)) continue;
    if (req->target_id_mode == 2 &&
        !bytes_eq(hd.tid, hd.len_tid, req->target_entity_id)) continue;
    if (req->n_event_names > 0) {
      bool any = false;
      for (const auto& nh : name_hashes) {
        if (bytes_eq(hd.event, hd.len_event, nh.second)) { any = true; break; }
      }
      if (!any) continue;
    }
    hits.push_back(i);
  }

  auto key_less = [&](uint64_t a, uint64_t b) {
    const RecMeta& ma = log->recs[a];
    const RecMeta& mb = log->recs[b];
    if (ma.time_us != mb.time_us) return ma.time_us < mb.time_us;
    if (ma.ctime_us != mb.ctime_us) return ma.ctime_us < mb.ctime_us;
    return a < b;
  };
  if (req->reversed)
    std::sort(hits.begin(), hits.end(), [&](uint64_t a, uint64_t b) { return key_less(b, a); });
  else
    std::sort(hits.begin(), hits.end(), key_less);
  if (req->limit >= 0 && hits.size() > static_cast<uint64_t>(req->limit))
    hits.resize(req->limit);

  uint64_t total = 0;
  for (uint64_t i : hits) total += 4 + log->recs[i].len;
  uint8_t* buf = total ? static_cast<uint8_t*>(malloc(total)) : nullptr;
  if (total && !buf) return -1;
  uint64_t w = 0;
  for (uint64_t i : hits) {
    const RecMeta& m = log->recs[i];
    memcpy(buf + w, log->map + m.offset, 4 + m.len);
    w += 4 + m.len;
  }
  *out = buf;
  *out_bytes = total;
  return static_cast<int64_t>(hits.size());
}

}  // extern "C"
