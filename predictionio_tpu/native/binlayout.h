// binlayout.h — shared host-side core of the zero-copy columnar->binned
// pipeline: layout planning + single-pass compressed fill.
//
// This header is the ONE implementation of the segmented-layout math
// (a bit-identical port of ops/ragged.build_segmented_groups +
// ops/als.compress_side) consumed by BOTH native libraries:
//   - raggedbin.cpp exports rb_bin_compressed (COO codes -> compressed
//     SideOut) for callers that already hold host COO arrays;
//   - eventlog.cpp exports el_bin_columnar (mmap'd log -> both sides'
//     compressed SideOut + vocabularies) — the fused ingest->bin lane.
//
// Why a header: the two .so files are compiled independently (see
// native/__init__.py build_library), so shared logic must be inlined
// into each; duplicating the layout math would let the two lanes drift
// apart, which the pinned equivalence tests exist to prevent.
//
// Output contract (must stay bit-identical to the Python reference):
//   idx_lo  [R, L] uint16   low 16 bits of the opposing-row index
//   idx_hi  [R, L] uint8    bits 16..23 (nullptr when max index < 2^16)
//   val     [R, L] uint8    affine value codes (code 255 = padded slot)
//           -- or --
//   val_f32 [R, L] float32  raw values + mask [R, L] uint8 when the
//                           distinct value set is not an affine ladder
//   seg     [R]    int32    group id local to the shard (pad rows carry
//                           the shard's last local id)
//   counts  [G]    int32    post-cap group sizes (padded group axis)
//
// All buffers are 64-byte-aligned allocations (posix_memalign) so
// numpy views over them can feed jax.device_put with no host-side
// realignment copy; free with free()/el_free()/rb_free().
//
// KNOWN (documented) divergence from the Python reference: the Python
// compress_side probes the first 2^18 slots of the PADDED value array
// before computing the full distinct set. At EXACTLY 255 distinct
// rating values with 0.0 not among them and a padded slot inside the
// probe window, the probe may count 256 and skip coding even though
// the full set is codable. This port reproduces that outcome from the
// plan (pad_in_probe_window below) except in the sub-case where not
// every distinct value appears inside the window — there it stays
// conservatively UNCOMPRESSED (semantically identical, different
// layout). Real rating scales have ~10 distinct values; the pinned
// equivalence fixtures sit nowhere near the 255 edge.

#ifndef PIO_NATIVE_BINLAYOUT_H_
#define PIO_NATIVE_BINLAYOUT_H_

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <unordered_map>
#include <vector>

namespace binlayout {

constexpr uint8_t kPadCode = 255;        // ops/als.PAD_CODE
constexpr int64_t kIdxWireLimit = 1 << 24;  // _split_idx 24-bit wire cap
constexpr int64_t kProbeWindow = 1 << 18;   // compress_side probe slots

inline int64_t pad_to_multiple(int64_t n, int64_t multiple) {
  return multiple > 1 ? ((n + multiple - 1) / multiple) * multiple : n;
}

// exact port of ops/ragged.auto_seg_len: evaluate the row count for
// every candidate L from the group-size histogram; first strict
// minimum wins (matching Python's `cost < best_cost`)
inline int64_t auto_seg_len(const int64_t* counts, int64_t n_groups,
                            double row_cost_slots, int64_t lo = 16,
                            int64_t hi = 512) {
  bool any = false;
  for (int64_t g = 0; g < n_groups; ++g) {
    if (counts[g] > 0) { any = true; break; }
  }
  if (!any) return lo;
  int64_t best_L = lo;
  double best_cost = -1.0;
  for (int64_t L = lo; L <= hi; L += 16) {
    int64_t rows = 0;
    for (int64_t g = 0; g < n_groups; ++g) {
      if (counts[g] > 0) rows += (counts[g] + L - 1) / L;
    }
    double cost = static_cast<double>(rows)
                  * (static_cast<double>(L) + row_cost_slots);
    if (best_cost < 0.0 || cost < best_cost) {
      best_L = L;
      best_cost = cost;
    }
  }
  return best_L;
}

struct SidePlan {
  int64_t L = 0;              // slots per virtual row
  int64_t g_per_shard = 0;
  int64_t G = 0;              // padded group axis (g_per_shard * n_shards)
  int64_t R_s = 0;            // rows per shard (padded)
  int64_t R_total = 0;        // n_shards * R_s
  int64_t row_block = 0;
  int64_t group_block = 0;
  int64_t n_shards = 1;
  int64_t n_groups = 0;       // true group count
  int64_t max_len = -1;       // -1 = uncapped
  std::vector<int64_t> counts_true;      // [n_groups]
  std::vector<int64_t> kept;             // [G] post-cap counts
  std::vector<int64_t> group_row_start;  // [G]
};

// exact port of the layout math in build_segmented_groups (counts ->
// blocks/padding/row starts); counts_true must hold the TRUE group
// sizes (pre-cap)
inline void plan_segmented(std::vector<int64_t>&& counts_true,
                           int64_t n_groups, int64_t seg_len,
                           int64_t max_len, int64_t n_shards,
                           int64_t block_size, double row_cost_slots,
                           SidePlan* plan) {
  plan->n_groups = n_groups;
  plan->n_shards = n_shards;
  plan->max_len = max_len;
  plan->counts_true = std::move(counts_true);
  const std::vector<int64_t>& ct = plan->counts_true;

  if (seg_len < 0) {  // "auto"
    if (max_len < 0) {
      seg_len = auto_seg_len(ct.data(), n_groups, row_cost_slots);
    } else {
      std::vector<int64_t> capped(n_groups);
      for (int64_t g = 0; g < n_groups; ++g)
        capped[g] = std::min(ct[g], max_len);
      seg_len = auto_seg_len(capped.data(), n_groups, row_cost_slots);
    }
  }
  const int64_t L = std::max<int64_t>(pad_to_multiple(seg_len, 8), 8);
  const int64_t g_raw = pad_to_multiple(
      std::max<int64_t>(1, (n_groups + n_shards - 1) / n_shards), 8);
  const int64_t group_block = std::min(block_size, g_raw);
  const int64_t g_per_shard = pad_to_multiple(g_raw, group_block);
  const int64_t G = g_per_shard * n_shards;

  plan->kept.assign(G, 0);
  for (int64_t g = 0; g < n_groups; ++g)
    plan->kept[g] = max_len < 0 ? ct[g] : std::min(ct[g], max_len);

  std::vector<int64_t> rows_by_shard(n_shards, 0);
  for (int64_t g = 0; g < G; ++g)
    rows_by_shard[g / g_per_shard] += (plan->kept[g] + L - 1) / L;
  int64_t rows_max = 1;
  for (int64_t s = 0; s < n_shards; ++s)
    rows_max = std::max(rows_max, rows_by_shard[s]);
  const int64_t row_block =
      std::min(block_size, pad_to_multiple(rows_max, 8));
  const int64_t R_s = pad_to_multiple(rows_max, row_block);

  plan->group_row_start.assign(G, 0);
  for (int64_t s = 0; s < n_shards; ++s) {
    int64_t acc = 0;
    for (int64_t j = 0; j < g_per_shard; ++j) {
      int64_t g = s * g_per_shard + j;
      plan->group_row_start[g] = acc + s * R_s;
      acc += (plan->kept[g] + L - 1) / L;
    }
  }
  plan->L = L;
  plan->g_per_shard = g_per_shard;
  plan->G = G;
  plan->R_s = R_s;
  plan->R_total = n_shards * R_s;
  plan->row_block = row_block;
  plan->group_block = group_block;
}

// does the first kProbeWindow slots of the row-major padded value
// array contain a padded slot? (the Python probe would then see the
// 0.0 pad filler as an extra distinct value). Derivable from the plan:
// row r's filled slots are exactly its first fill(r) positions.
inline bool pad_in_probe_window(const SidePlan& plan) {
  const int64_t L = plan.L;
  const int64_t window = std::min(kProbeWindow, plan.R_total * L);
  std::vector<int64_t> fill(plan.R_total, 0);
  for (int64_t g = 0; g < plan.G; ++g) {
    int64_t kept = plan.kept[g];
    if (kept == 0) continue;
    int64_t r0 = plan.group_row_start[g];
    int64_t rows = (kept + L - 1) / L;
    for (int64_t j = 0; j < rows; ++j)
      fill[r0 + j] = (j < rows - 1) ? L : kept - (rows - 1) * L;
  }
  for (int64_t r = 0; r * L < window; ++r) {
    // first pad slot of row r sits at global position r*L + fill[r] —
    // but a COMPLETELY full row (fill == L) has no pad of its own
    // (that position is row r+1's first slot, which may be filled)
    if (fill[r] < L && r * L + fill[r] < window) return true;
  }
  return false;
}

struct SideOut {
  uint16_t* idx_lo = nullptr;  // [R, L]
  uint8_t* idx_hi = nullptr;   // [R, L] or nullptr when max idx < 2^16
  uint8_t* val_u8 = nullptr;   // [R, L] affine codes (255 = pad) ...
  float* val_f32 = nullptr;    // ... or raw float32 values
  uint8_t* mask = nullptr;     // [R, L] 1/0, only with val_f32
  int32_t* seg = nullptr;      // [R]
  int32_t* counts = nullptr;   // [G]
  int64_t affine = 0;          // 1 = val_u8 carries codes
  double affine_a = 0.0;
  double affine_b = 0.0;
  int64_t kept_entries = 0;    // sum of post-cap counts
  double kept_value_sum = 0.0; // f64 sum of kept (binned) float32 values

  void free_all() {
    free(idx_lo); free(idx_hi); free(val_u8); free(val_f32);
    free(mask); free(seg); free(counts);
    *this = SideOut{};
  }
};

inline void* alloc_aligned(size_t nbytes) {
  void* p = nullptr;
  if (posix_memalign(&p, 64, nbytes ? nbytes : 64) != 0) return nullptr;
  return p;
}

// Fill one side's compressed layout from COO triples. Returns 0 ok,
// -1 group/item index out of range, -2 allocation failure, -3 item
// index exceeds the 24-bit wire format. ``values`` must already be
// the float32 the Python path would bin (value resolution — NaN->0,
// per-event-name overrides — happens in the caller).
template <typename IdxT>
inline int fill_compressed(const IdxT* group_idx, const IdxT* item_idx,
                           const float* values, int64_t nnz,
                           const SidePlan& plan, SideOut* out) {
  const int64_t L = plan.L;
  const int64_t n_groups = plan.n_groups;
  const int64_t max_len = plan.max_len;

  // pass 1: distinct KEPT values (what compress_side's np.unique over
  // the masked array sees — truncation-dropped entries must not count)
  // + the max kept item index (decides the idx_hi stream). Without a
  // cap every entry is kept, so no cursor walk is needed.
  std::unordered_map<uint32_t, uint8_t> value_codes;
  value_codes.reserve(512);
  bool too_many = false;
  bool has_nan = false;
  int64_t max_idx = 0;
  bool have_last = false;
  uint32_t last_bits = 0;
  auto note_value = [&](float v) {
    if (v != v) {  // NaN (any encoding): never codable — np.unique
      has_nan = true;  // would keep it and the ladder check fails, so
      return;          // the reference stays uncoded; keeping NaN out
    }                  // of the set also keeps std::sort well-defined
    if (v == 0.0f) v = 0.0f;  // collapse -0.0 onto 0.0 like np.unique
    uint32_t bits;
    memcpy(&bits, &v, 4);
    if (have_last && bits == last_bits) return;
    have_last = true;
    last_bits = bits;
    if (too_many) return;
    value_codes.emplace(bits, 0);
    if (value_codes.size() > 256) too_many = true;
  };
  std::vector<int64_t> cursor(n_groups, 0);
  for (int64_t k = 0; k < nnz; ++k) {
    int64_t g = static_cast<int64_t>(group_idx[k]);
    int64_t it = static_cast<int64_t>(item_idx[k]);
    if (g < 0 || g >= n_groups || it < 0) return -1;
    if (it >= kIdxWireLimit) return -3;
    if (max_len >= 0) {
      int64_t pos = cursor[g]++;
      int64_t drop = plan.counts_true[g] - max_len;
      if (drop > 0 && pos < drop) continue;  // truncated away: not kept
    }
    if (it > max_idx) max_idx = it;
    note_value(values[k]);
  }

  // coding decision — exact port of compress_side (plus the documented
  // probe edge at exactly 255 distinct values)
  int64_t n_vals = static_cast<int64_t>(value_codes.size());
  bool coded = false;
  double a = 0.0, b = 0.0;
  std::vector<float> uniq;
  if (!too_many && !has_nan && n_vals <= 255) {
    uniq.reserve(n_vals);
    for (const auto& kv : value_codes) {
      float v;
      uint32_t bits = kv.first;
      memcpy(&v, &bits, 4);
      uniq.push_back(v);
    }
    std::sort(uniq.begin(), uniq.end());
    if (n_vals == 1) {
      coded = true;
      a = static_cast<double>(uniq[0]);
      b = 0.0;
    } else if (n_vals >= 2) {
      float bf = uniq[1] - uniq[0];  // f32 subtraction, like numpy
      if (bf != 0.0f) {
        bool ladder = true;
        for (int64_t k = 0; k < n_vals; ++k) {
          float expect = uniq[0] + bf * static_cast<float>(k);
          if (uniq[k] != expect) { ladder = false; break; }
        }
        if (ladder) {
          coded = true;
          a = static_cast<double>(uniq[0]);
          b = static_cast<double>(bf);
        }
      }
    }
    if (coded && n_vals == 255) {
      // the Python probe window includes pad slots valued 0.0: at 255
      // distinct non-zero values + a pad inside the window it counts
      // 256 and skips coding — reproduce that outcome
      bool zero_in_vals =
          std::binary_search(uniq.begin(), uniq.end(), 0.0f);
      if (!zero_in_vals && pad_in_probe_window(plan)) coded = false;
    }
    if (coded) {
      for (int64_t k = 0; k < n_vals; ++k) {
        uint32_t bits;
        memcpy(&bits, &uniq[k], 4);
        value_codes[bits] = static_cast<uint8_t>(k);
      }
    }
  }

  const size_t slots = static_cast<size_t>(plan.R_total) * L;
  out->idx_lo = static_cast<uint16_t*>(alloc_aligned(slots * 2));
  out->idx_hi = max_idx >= (1 << 16)
                    ? static_cast<uint8_t*>(alloc_aligned(slots))
                    : nullptr;
  if (coded) {
    out->val_u8 = static_cast<uint8_t*>(alloc_aligned(slots));
  } else {
    out->val_f32 = static_cast<float*>(alloc_aligned(slots * 4));
    out->mask = static_cast<uint8_t*>(alloc_aligned(slots));
  }
  out->seg = static_cast<int32_t*>(alloc_aligned(plan.R_total * 4));
  out->counts = static_cast<int32_t*>(alloc_aligned(plan.G * 4));
  bool alloc_ok = out->idx_lo && out->seg && out->counts &&
                  (max_idx < (1 << 16) || out->idx_hi) &&
                  (coded ? out->val_u8 != nullptr
                         : out->val_f32 && out->mask);
  if (!alloc_ok) {
    out->free_all();
    return -2;
  }
  memset(out->idx_lo, 0, slots * 2);
  if (out->idx_hi) memset(out->idx_hi, 0, slots);
  if (coded) {
    memset(out->val_u8, kPadCode, slots);       // pads decode to 255
  } else {
    memset(out->val_f32, 0, slots * 4);
    memset(out->mask, 0, slots);
  }
  // pad rows point at the shard's LAST local group (nondecreasing seg)
  for (int64_t r = 0; r < plan.R_total; ++r)
    out->seg[r] = static_cast<int32_t>(plan.g_per_shard - 1);
  int64_t kept_total = 0;
  for (int64_t g = 0; g < plan.G; ++g) {
    out->counts[g] = static_cast<int32_t>(plan.kept[g]);
    kept_total += plan.kept[g];
  }
  out->affine = coded ? 1 : 0;
  out->affine_a = a;
  out->affine_b = b;
  out->kept_entries = kept_total;

  // pass 2: the cursor-walk fill (rb_fill_segmented's walk, writing
  // the wire-compressed streams directly — no intermediate f32
  // val/mask arrays, no post-hoc searchsorted/split passes)
  std::fill(cursor.begin(), cursor.end(), 0);
  have_last = false;  // coded values are never NaN (has_nan forces the
  last_bits = 0;      // f32 path), so the bits cache is collision-free
  uint8_t last_code = 0;
  double vsum = 0.0;
  for (int64_t k = 0; k < nnz; ++k) {
    int64_t g = static_cast<int64_t>(group_idx[k]);
    int64_t pos = cursor[g]++;
    if (max_len >= 0) {
      int64_t drop = plan.counts_true[g] - max_len;
      if (drop > 0) {
        if (pos < drop) continue;  // keep only the latest max_len
        pos -= drop;
      }
    }
    int64_t row = plan.group_row_start[g] + pos / L;
    int64_t slot = pos % L;
    int64_t at = row * L + slot;
    int32_t it = static_cast<int32_t>(item_idx[k]);
    out->idx_lo[at] = static_cast<uint16_t>(it & 0xFFFF);
    if (out->idx_hi) out->idx_hi[at] = static_cast<uint8_t>(it >> 16);
    float v = values[k];
    vsum += static_cast<double>(v);
    if (coded) {
      if (v == 0.0f) v = 0.0f;  // -0.0 folded like pass 1
      uint32_t bits;
      memcpy(&bits, &v, 4);
      if (!have_last || bits != last_bits) {
        have_last = true;
        last_bits = bits;
        last_code = value_codes[bits];
      }
      out->val_u8[at] = last_code;
    } else {
      out->val_f32[at] = v;
      out->mask[at] = 1;
    }
    out->seg[row] = static_cast<int32_t>(g % plan.g_per_shard);
  }
  out->kept_value_sum = vsum;
  return 0;
}

// C-ABI view of one side's layout (mirrored field-for-field by the
// ctypes Structure in the Python bindings; every field is 8 bytes so
// the layout is padding-free and identical across compilers)
struct CSide {
  uint16_t* idx_lo;
  uint8_t* idx_hi;
  uint8_t* val_u8;
  float* val_f32;
  uint8_t* mask;
  int32_t* seg;
  int32_t* counts;
  int64_t rows;          // R_total
  int64_t L;
  int64_t g_per_shard;
  int64_t n_shards;
  int64_t row_block;
  int64_t group_block;
  int64_t n_groups;      // true group count (pre-padding)
  int64_t affine;        // 1 = val_u8 carries codes
  double affine_a;
  double affine_b;
  int64_t kept_entries;
  double kept_value_sum;
};

inline void export_side(const SidePlan& plan, SideOut* out, CSide* c) {
  c->idx_lo = out->idx_lo;
  c->idx_hi = out->idx_hi;
  c->val_u8 = out->val_u8;
  c->val_f32 = out->val_f32;
  c->mask = out->mask;
  c->seg = out->seg;
  c->counts = out->counts;
  c->rows = plan.R_total;
  c->L = plan.L;
  c->g_per_shard = plan.g_per_shard;
  c->n_shards = plan.n_shards;
  c->row_block = plan.row_block;
  c->group_block = plan.group_block;
  c->n_groups = plan.n_groups;
  c->affine = out->affine;
  c->affine_a = out->affine_a;
  c->affine_b = out->affine_b;
  c->kept_entries = out->kept_entries;
  c->kept_value_sum = out->kept_value_sum;
  *out = SideOut{};  // ownership moved to the C view
}

}  // namespace binlayout

#endif  // PIO_NATIVE_BINLAYOUT_H_
