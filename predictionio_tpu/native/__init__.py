"""Native (C++) runtime components.

The reference delegates its heavy lifting to external JVM systems
(SURVEY.md §0: Spark, HBase, ES); this package holds the single-binary
native equivalents: the event-log storage engine (eventlog.cpp) and the
host-side ragged-data binning used by the training input pipeline.

Libraries are compiled on first use with the system toolchain and cached
under ``_build/``; loading is via ctypes (no pybind11 dependency).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

log = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.environ.get("PIO_NATIVE_BUILD_DIR", os.path.join(_HERE, "_build"))
_CXX = os.environ.get("PIO_CXX", "g++")

_lock = threading.Lock()
_cache: dict = {}


class NativeBuildError(RuntimeError):
    pass


def build_library(name: str, extra_flags: Optional[list] = None) -> str:
    """Compile ``<name>.cpp`` to ``_build/_<name>.so`` (mtime-cached).

    Returns the .so path; raises NativeBuildError if the toolchain is
    missing or compilation fails (callers degrade gracefully).
    """
    src = os.path.join(_HERE, f"{name}.cpp")
    out = os.path.join(_BUILD_DIR, f"_{name}.so")
    # shared headers (binlayout.h) are inlined into every .so: a stale
    # .so must rebuild when the header changed, not only the .cpp
    dep_mtime = max(
        [os.path.getmtime(src)]
        + [os.path.getmtime(os.path.join(_HERE, f))
           for f in os.listdir(_HERE) if f.endswith(".h")]
    )
    with _lock:
        if os.path.exists(out) and os.path.getmtime(out) >= dep_mtime:
            return out
        os.makedirs(_BUILD_DIR, exist_ok=True)
        cmd = [
            _CXX, "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
            *(extra_flags or []), src, "-o", out + ".tmp",
        ]
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
        except FileNotFoundError:
            raise NativeBuildError(f"C++ compiler {_CXX!r} not found") from None
        except subprocess.TimeoutExpired:
            raise NativeBuildError(f"compiling {name} timed out") from None
        if proc.returncode != 0:
            raise NativeBuildError(
                f"compiling {name} failed:\n{proc.stderr[-2000:]}"
            )
        os.replace(out + ".tmp", out)
        return out


def load_library(name: str, extra_flags: Optional[list] = None) -> ctypes.CDLL:
    """Build (if needed) and dlopen a native library; process-cached."""
    with _lock:
        if name in _cache:
            return _cache[name]
    path = build_library(name, extra_flags)
    lib = ctypes.CDLL(path)
    with _lock:
        # re-validate under the lock: a concurrent first caller may have
        # cached its own handle while this thread was building — converge
        # on ONE canonical CDLL so per-handle state (restype/argtypes set
        # once by callers) is never split across two live handles
        return _cache.setdefault(name, lib)


def native_available(name: str) -> bool:
    try:
        load_library(name)
        return True
    except NativeBuildError as exc:
        log.debug("native %s unavailable: %s", name, exc)
        return False


class CSide(ctypes.Structure):
    """Mirror of binlayout::CSide (native/binlayout.h) — one side of a
    transfer-compressed binned layout. Every field is 8 bytes, so the
    Python and C layouts are padding-free and identical. Shared by the
    eventlog backend (el_bin_columnar) and ops/ragged
    (rb_bin_compressed)."""

    _fields_ = [
        ("idx_lo", ctypes.c_void_p),
        ("idx_hi", ctypes.c_void_p),
        ("val_u8", ctypes.c_void_p),
        ("val_f32", ctypes.c_void_p),
        ("mask", ctypes.c_void_p),
        ("seg", ctypes.c_void_p),
        ("counts", ctypes.c_void_p),
        ("rows", ctypes.c_int64),
        ("L", ctypes.c_int64),
        ("g_per_shard", ctypes.c_int64),
        ("n_shards", ctypes.c_int64),
        ("row_block", ctypes.c_int64),
        ("group_block", ctypes.c_int64),
        ("n_groups", ctypes.c_int64),
        ("affine", ctypes.c_int64),
        ("affine_a", ctypes.c_double),
        ("affine_b", ctypes.c_double),
        ("kept_entries", ctypes.c_int64),
        ("kept_value_sum", ctypes.c_double),
    ]


def unpack_cside(c: "CSide", owner: "NativeOwner") -> dict:
    """CSide -> kwargs for data.storage.BinnedSide: zero-copy numpy
    views over the native buffers, lifetime-anchored to ``owner`` (the
    side's pointers are also registered on the owner here)."""
    import numpy as np

    slots = c.rows * c.L
    for p in (c.idx_lo, c.idx_hi, c.val_u8, c.val_f32, c.mask,
              c.seg, c.counts):
        owner.add(p)
    coded = bool(c.affine)
    G = c.g_per_shard * c.n_shards
    return dict(
        idx_lo=as_ndarray(c.idx_lo, slots * 2, "uint16", (c.rows, c.L),
                          owner),
        idx_hi=as_ndarray(c.idx_hi, slots, "uint8", (c.rows, c.L), owner),
        val=(as_ndarray(c.val_u8, slots, "uint8", (c.rows, c.L), owner)
             if coded else
             as_ndarray(c.val_f32, slots * 4, "float32", (c.rows, c.L),
                        owner)),
        mask=(None if coded
              else as_ndarray(c.mask, slots, "uint8", (c.rows, c.L),
                              owner)),
        seg=as_ndarray(c.seg, c.rows * 4, "int32", (c.rows,), owner),
        counts=as_ndarray(c.counts, G * 4, "int32", (G,), owner),
        affine=((c.affine_a, c.affine_b) if coded else None),
        row_block=int(c.row_block),
        group_block=int(c.group_block),
        groups_per_shard=int(c.g_per_shard),
        n_shards=int(c.n_shards),
        n_groups=int(c.n_groups),
        kept_entries=int(c.kept_entries),
        kept_value_sum=float(c.kept_value_sum),
    )


class NativeOwner:
    """Frees a set of native buffers when garbage-collected — the
    lifetime anchor of every zero-copy numpy view over native memory
    (``as_ndarray`` ties each view's buffer to its owner, so a view
    kept alive keeps the allocation alive)."""

    def __init__(self, free_fn, ptrs):
        self._free = free_fn
        self._ptrs = [int(p) for p in ptrs if p]

    def add(self, ptr) -> None:
        if ptr:
            self._ptrs.append(int(ptr))

    def __del__(self):
        free = getattr(self, "_free", None)
        for p in getattr(self, "_ptrs", ()):
            try:
                free(p)
            except Exception:  # noqa: BLE001 — interpreter teardown
                pass
        self._ptrs = []


def as_ndarray(ptr, nbytes: int, dtype, shape, owner: NativeOwner):
    """Zero-copy numpy view over a native allocation.

    The returned array's buffer object holds a reference to ``owner``,
    so the memory outlives any view derived from it (slices, reshapes)
    regardless of what happens to the enclosing result object — the
    hand-to-jax contract of the zero-copy data path: ``device_put``
    reads the host bytes with no intermediate copy."""
    import numpy as np

    if not ptr:
        return None
    buf = (ctypes.c_char * nbytes).from_address(int(ptr))
    buf._owner = owner  # lifetime anchor (ctypes instances take attrs)
    return np.frombuffer(buf, dtype=dtype).reshape(shape)
