"""Native (C++) runtime components.

The reference delegates its heavy lifting to external JVM systems
(SURVEY.md §0: Spark, HBase, ES); this package holds the single-binary
native equivalents: the event-log storage engine (eventlog.cpp) and the
host-side ragged-data binning used by the training input pipeline.

Libraries are compiled on first use with the system toolchain and cached
under ``_build/``; loading is via ctypes (no pybind11 dependency).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

log = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.environ.get("PIO_NATIVE_BUILD_DIR", os.path.join(_HERE, "_build"))
_CXX = os.environ.get("PIO_CXX", "g++")

_lock = threading.Lock()
_cache: dict = {}


class NativeBuildError(RuntimeError):
    pass


def build_library(name: str, extra_flags: Optional[list] = None) -> str:
    """Compile ``<name>.cpp`` to ``_build/_<name>.so`` (mtime-cached).

    Returns the .so path; raises NativeBuildError if the toolchain is
    missing or compilation fails (callers degrade gracefully).
    """
    src = os.path.join(_HERE, f"{name}.cpp")
    out = os.path.join(_BUILD_DIR, f"_{name}.so")
    with _lock:
        if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
            return out
        os.makedirs(_BUILD_DIR, exist_ok=True)
        cmd = [
            _CXX, "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
            *(extra_flags or []), src, "-o", out + ".tmp",
        ]
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
        except FileNotFoundError:
            raise NativeBuildError(f"C++ compiler {_CXX!r} not found") from None
        except subprocess.TimeoutExpired:
            raise NativeBuildError(f"compiling {name} timed out") from None
        if proc.returncode != 0:
            raise NativeBuildError(
                f"compiling {name} failed:\n{proc.stderr[-2000:]}"
            )
        os.replace(out + ".tmp", out)
        return out


def load_library(name: str, extra_flags: Optional[list] = None) -> ctypes.CDLL:
    """Build (if needed) and dlopen a native library; process-cached."""
    with _lock:
        if name in _cache:
            return _cache[name]
    path = build_library(name, extra_flags)
    lib = ctypes.CDLL(path)
    with _lock:
        _cache[name] = lib
    return lib


def native_available(name: str) -> bool:
    try:
        load_library(name)
        return True
    except NativeBuildError as exc:
        log.debug("native %s unavailable: %s", name, exc)
        return False
