"""ALS matrix factorization as a DASE Algorithm.

Behavior contract from the reference's recommendation template
(examples/scala-parallel-recommendation/custom-serving/src/main/scala/
ALSAlgorithm.scala:56 — `ALS.train(ratings, rank, iterations, lambda)`
on indexed ratings, model = user/item factor matrices, predict =
top-``num`` item scores for a user). The compute core is
predictionio_tpu.ops.als (mesh-sharded batched normal equations)
instead of MLlib's shuffle-blocked ALS.

Query / result are JSON-shaped dicts, matching the REST contract of the
deployed engine (`POST /queries.json {"user": "1", "num": 4}` ->
`{"itemScores": [{"item": ..., "score": ...}]}`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.core import Algorithm, SanityCheck
from predictionio_tpu.core.params import Params
from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.ops.als import ALSConfig, ALSFactors, als_train
from predictionio_tpu.ops.topk import TopKScorer
from predictionio_tpu.parallel.mesh import MeshContext


@dataclass
class PreparedRatings(SanityCheck):
    """PD for factorization algorithms: indexed COO ratings — or, on
    the zero-copy lane, a DEFERRED ``binned_request`` (the DataSource
    cannot bin at read time because the layout depends on algorithm
    knobs; the fit stage performs the one fused native scan+bin call
    with its own config, and no COO ever materializes)."""

    user_ids: Optional[BiMap] = None   # user id str -> row
    item_ids: Optional[BiMap] = None   # item id str -> row
    user_idx: Optional[np.ndarray] = None    # [nnz] int
    item_idx: Optional[np.ndarray] = None    # [nnz] int
    ratings: Optional[np.ndarray] = None     # [nnz] float32
    #: data+derivation fingerprint from the DataSource (None when the
    #: backend has no cheap one) — keys the binned-layout cache
    fingerprint: Optional[str] = None
    #: deferred zero-copy read (templates.recommendation
    #: .BinnedReadRequest); when set, the COO fields above are None
    binned_request: Optional[Any] = None

    @property
    def n_users(self) -> int:
        return len(self.user_ids)

    @property
    def n_items(self) -> int:
        return len(self.item_ids)

    def sanity_check(self) -> None:
        if self.binned_request is not None:
            return  # emptiness is checked by the fit-stage native read
        if self.user_idx is None or len(self.user_idx) == 0:
            raise ValueError("PreparedRatings is empty — no rating events found")
        if len(self.user_idx) != len(self.item_idx) or len(self.user_idx) != len(self.ratings):
            raise ValueError("COO arrays length mismatch")


@dataclass
class ALSParams(Params):
    rank: int = 32
    num_iterations: int = 10
    lambda_: float = 0.1
    implicit_prefs: bool = False
    alpha: float = 1.0
    block_size: int = 4096
    seed: int = 3
    seg_len: object = "auto"          # virtual-row length (int), or
                                      # "auto": sized from the group-
                                      # size histogram (ops.ragged)
    solver: str = "cg"               # "cg" | "direct"
    cg_iters: int = 6   # warm-started + Jacobi-preconditioned CG needs
                        # far fewer steps than a cold solve (measured
                        # sweep: ops.als.ALSConfig.cg_iters)
    cg_unroll: bool = True           # straight-line CG recurrence
                                     # (False restores the lax.scan form)
    cg_precond: str = "jacobi"       # "jacobi" | "none"; with "none",
                                     # raise cg_iters to >= 8 (see sweep)
    cg_dtype: str = "bfloat16"       # CG matvec dtype ("float32" to opt out)
    compute_dtype: str = "bfloat16"  # Gramian input dtype (f32 accumulate)
    # optional hard caps (None = keep every rating; the segmented layout
    # makes caps unnecessary except as an outlier guard)
    max_ratings_per_user: Optional[int] = None
    max_ratings_per_item: Optional[int] = None
    # retrieval-index knobs (predictionio_tpu/index): backend
    # "auto"/"exact"/"ivf" (PIO_INDEX_BACKEND overrides), and the exact
    # backend's Pallas dot+top-k kernel flag "auto"/"on"/"off"
    # (PIO_INDEX_KERNEL overrides — selection exactly like
    # flash_ce_kernel)
    index_backend: str = "auto"
    index_kernel: str = "auto"


class ALSModel:
    """Factor matrices + id maps; scorer compiled lazily and kept on device."""

    #: ledger attribution label (obs/memacct.py); TwoTowerModel
    #: overrides — the same per-model key perfacct's MFU gauges use
    memacct_model = "als"

    def __init__(self, factors: ALSFactors, user_ids: BiMap, item_ids: BiMap,
                 index_backend: str = "auto", index_kernel: str = "auto"):
        self.user_factors = factors.user_factors
        self.item_factors = factors.item_factors
        self.user_ids = user_ids
        self.item_ids = item_ids
        self._scorer: Optional[TopKScorer] = None
        # retrieval index (predictionio_tpu/index): built lazily /
        # by deploy warm-up, patched in place by the streaming lane
        self._index = None
        self.index_backend = index_backend
        self.index_kernel = index_kernel
        # picklable record that sharded serving was enabled (the mesh
        # itself never pickles); load_persistent_model re-enables it
        self.sharded_axis: Optional[str] = None
        self._register_memory()

    def __getstate__(self):
        d = dict(self.__dict__)
        d["_scorer"] = None  # device buffers never pickle
        d["_index"] = None   # rebuilt at deploy warm-up
        return d

    def __setstate__(self, d):
        d.setdefault("sharded_axis", None)  # models pickled pre-field
        d.setdefault("_index", None)
        d.setdefault("index_backend", "auto")
        d.setdefault("index_kernel", "auto")
        self.__dict__.update(d)
        # model LOAD seam (prepare_deploy unpickle): this instance's
        # residency lands in the device-memory ledger; the hot-swap /
        # replica-stop paths release it (obs/memacct.py)
        self._register_memory()

    def _register_memory(self) -> None:
        """(Re-)price this model's footprints in the device-memory
        ledger: the factor tables and (estimated) id maps. Called at
        construction, load (unpickle) and after every fold-in patch —
        a grown table re-prices itself under the same owner key."""
        from predictionio_tpu.obs import memacct

        memacct.LEDGER.register(
            self, self.memacct_model, "factors",
            int(self.user_factors.nbytes + self.item_factors.nbytes))
        # id maps: a cheap structural estimate (dict slot + interned
        # key + inverse list per entry) — attribution, not malloc truth
        memacct.LEDGER.register(
            self, self.memacct_model, "id_maps",
            (len(self.user_ids) + len(self.item_ids)) * 24)

    def scorer(self) -> TopKScorer:
        if self._scorer is None:
            self._scorer = TopKScorer(self.item_factors)
        return self._scorer

    def retrieval_index(self):
        """The model's ANN candidate-generation index over the item
        factor table (predictionio_tpu/index): built lazily (the engine
        server's warm-up builds it at model load), kept fresh by
        ``upsert_rows`` — the streaming ``/model/patch`` lane reaches
        retrieval, not just scoring."""
        if self._index is None:
            from predictionio_tpu.index import make_index

            index = make_index(backend=self.index_backend,
                               kernel=self.index_kernel)
            # ledger attribution BEFORE the build registers bytes, so
            # the index's footprints land under this model's label
            index.mem_model = self.memacct_model
            index.build(np.asarray(self.item_factors, np.float32))
            self._index = index
        return self._index

    def retrieval_stats(self) -> Optional[dict]:
        """Stats of the BUILT index, or None (status pages must never
        trigger a build)."""
        return self._index.stats() if self._index is not None else None

    def enable_sharded_serving(self, mesh, axis: str = "data") -> None:
        """Swap in a ShardedTopKScorer: item factors row-sharded over
        ``mesh[axis]``, per-shard top-k merged over ICI — serving for
        catalogs larger than one chip's HBM (ops.topk.make_sharded_topk).
        Same results as the single-device scorer."""
        from predictionio_tpu.ops.topk import ShardedTopKScorer

        self._scorer = ShardedTopKScorer(self.item_factors, mesh, axis=axis)
        self.sharded_axis = axis

    def upsert_rows(
        self,
        user_rows: Sequence[Tuple[str, "np.ndarray"]] = (),
        item_rows: Sequence[Tuple[str, "np.ndarray"]] = (),
    ) -> Tuple[int, int]:
        """Apply a streaming fold-in patch: overwrite (or append) the
        named factor rows. COPY-ON-WRITE — new arrays are built and the
        attribute references swapped last, so a concurrent ``predict``
        reading ``self.user_factors`` once sees either the old or the
        new table, never a torn row. Any item change invalidates the
        cached scorer (it holds a device copy of the item table); a
        same-shape re-put hits the compile cache, only NEW items change
        shapes. Returns (n_new_users, n_new_items)."""
        rank = self.user_factors.shape[1] if self.user_factors.size else (
            self.item_factors.shape[1])
        if item_rows and self.sharded_axis is not None:
            # the sharded scorer's row placement can't be patched from
            # here (no mesh at hand) — silently downgrading to the
            # single-device scorer would change serving capacity; the
            # rolling /reload lane is the supported swap for these
            raise ValueError(
                "item-row patches are not supported on a sharded-serving "
                "model; use the rolling /reload fallback")
        new_users = new_items = 0
        if user_rows:
            ids, factors = self.user_ids, self.user_factors
            fresh = [uid for uid, _ in user_rows if uid not in ids]
            if fresh:
                vocab = list(ids.keys()) + fresh
                ids = BiMap.from_vocab(vocab)
                factors = np.vstack(
                    [factors, np.zeros((len(fresh), rank), np.float32)])
                new_users = len(fresh)
            else:
                factors = factors.copy()
            for uid, vec in user_rows:
                vec = np.asarray(vec, np.float32)
                if vec.shape != (rank,):
                    raise ValueError(
                        f"user row {uid!r}: expected a length-{rank} "
                        f"vector, got shape {vec.shape}")
                factors[ids[uid]] = vec
            # factors FIRST: a reader holding the new id map but the old
            # (shorter) table would index past its end on a fresh user
            self.user_factors = factors  # graftlint: disable=JT18 — copy-on-write commit: store is atomic, readers take one local ref (old-or-new, never torn)
            self.user_ids = ids  # graftlint: disable=JT18 — paired with the factors swap; ordering documented above
        if item_rows:
            ids, factors = self.item_ids, self.item_factors
            fresh = [iid for iid, _ in item_rows if iid not in ids]
            if fresh:
                vocab = list(ids.keys()) + fresh
                ids = BiMap.from_vocab(vocab)
                factors = np.vstack(
                    [factors, np.zeros((len(fresh), rank), np.float32)])
                new_items = len(fresh)
            else:
                factors = factors.copy()
            for iid, vec in item_rows:
                vec = np.asarray(vec, np.float32)
                if vec.shape != (rank,):
                    raise ValueError(
                        f"item row {iid!r}: expected a length-{rank} "
                        f"vector, got shape {vec.shape}")
                factors[ids[iid]] = vec
            self.item_factors = factors  # graftlint: disable=JT18 — copy-on-write commit: store is atomic, readers take one local ref (old-or-new, never torn)
            self.item_ids = ids  # graftlint: disable=JT18 — paired with the factors swap; same ordering rule
            # the scorer holds a DEVICE copy of the old item table
            self._scorer = None
            # the retrieval index takes the SAME rows as an in-place
            # upsert (no rebuild): streamed items become retrievable
            # without a /reload
            if self._index is not None:
                touched = np.fromiter(
                    (ids[iid] for iid, _ in item_rows), np.int64,
                    count=len(item_rows))
                self._index.upsert(touched, factors[touched])
        if new_users or new_items or user_rows or item_rows:
            # grown/overwritten tables re-price their ledger footprints
            self._register_memory()
        return new_users, new_items

    def recommend(
        self,
        user_id: str,
        num: int,
        exclude_items: Sequence[str] = (),
        candidate_items: Optional[Sequence[str]] = None,
    ) -> List[Tuple[str, float]]:
        row = self.user_ids.get(user_id)
        if row is None:
            return []
        exclude = {self.item_ids[i] for i in exclude_items if i in self.item_ids}
        if candidate_items is not None:
            cand = np.array(
                sorted(
                    {self.item_ids[i] for i in candidate_items if i in self.item_ids}
                    - exclude
                ),
                dtype=np.int64,
            )
            if len(cand) == 0:
                return []
            scores = self.item_factors[cand] @ self.user_factors[row]
            # partial sort: the whitelist can be the whole catalog
            # (JT14 — argsort(...)[:k] full-sorts it per query)
            top_s, top_j = TopKScorer._host_topk(scores[None, :], num)
            inv = self.item_ids.inverse()
            return [(inv[int(cand[j])], float(s))
                    for s, j in zip(top_s[0], top_j[0])]
        excl = np.fromiter(exclude, dtype=np.int32) if exclude else None
        if self.sharded_axis is not None:
            # sharded serving keeps the mesh scorer (a model-axis
            # sharded index is the ROADMAP item A follow-up)
            scores, idx = self.scorer().score(
                self.user_factors[row], num, excl)
        else:
            scores, idx = self.retrieval_index().search(
                self.user_factors[row], num, excl)
        inv = self.item_ids.inverse()
        return [
            (inv[int(i)], float(s))
            for s, i in zip(scores[0], idx[0])
            if s > -1e29 and int(i) >= 0
        ]

    def similar_items(
        self,
        item_id: str,
        num: int,
        exclude_items: Sequence[str] = (),
    ) -> List[Tuple[str, float]]:
        """item -> top-``num`` similar items through the retrieval
        index: top-k by dot product of the item's factor against the
        item table, the query item excluded. Cosine similarity when the
        table is row-normalized (two-tower towers are; raw ALS factors
        score dot-similarity, popularity-weighted)."""
        row = self.item_ids.get(item_id)
        if row is None:
            return []
        exclude = {self.item_ids[i] for i in exclude_items
                   if i in self.item_ids} - {row}
        # self-exclusion goes LAST: the exact backend caps exclusion
        # lists at max_exclude keeping the NEWEST (rightmost) entries,
        # so an oversize blacklist may drop itself but never the query
        # item — and the result filter below backstops even that
        excl = np.fromiter(
            list(exclude) + [row], dtype=np.int32,
            count=len(exclude) + 1)
        if self.sharded_axis is not None:
            # sharded serving keeps the mesh scorer (same stance as
            # recommend: no single-device index over a sharded catalog)
            scores, idx = self.scorer().score(
                self.item_factors[row], num, excl)
        else:
            scores, idx = self.retrieval_index().search(
                self.item_factors[row], num, excl)
        inv = self.item_ids.inverse()
        return [
            (inv[int(i)], float(s))
            for s, i in zip(scores[0], idx[0])
            if s > -1e29 and int(i) >= 0 and int(i) != row
        ]


def apply_rows_patch(model: ALSModel, patch: dict) -> bool:
    """The one factor-row patch decoder every factor-backed algorithm
    shares (ALS and two-tower models both serve from ALSModel factor
    tables): ``patch`` carries ``userRows`` / ``itemRows`` as
    ``[[id, [floats...]], ...]`` and lands via
    :meth:`ALSModel.upsert_rows` (copy-on-write, scorer invalidation).
    Malformed rows raise ValueError — the engine server maps that to
    400 with nothing partially applied for the failing side."""

    def rows(key):
        out = []
        for entry in patch.get(key) or ():
            if (not isinstance(entry, (list, tuple)) or len(entry) != 2
                    or not isinstance(entry[0], str)):
                raise ValueError(
                    f"{key}: each row must be [id, [floats...]]")
            out.append((entry[0], np.asarray(entry[1], np.float32)))
        return out

    model.upsert_rows(user_rows=rows("userRows"),
                      item_rows=rows("itemRows"))
    return True


class ALSAlgorithm(Algorithm):
    """DASE wrapper over ops.als (ref template: ALSAlgorithm.scala)."""

    def __init__(self, params: ALSParams):
        super().__init__(params)

    def apply_patch(self, model: ALSModel, patch: dict) -> bool:
        """Streaming fold-in rows land in the live factor tables
        (workflow/stream.py's model-patch lane)."""
        return apply_rows_patch(model, patch)

    def train(self, ctx: MeshContext, pd: PreparedRatings) -> ALSModel:
        p: ALSParams = self.params
        cfg = ALSConfig(
            rank=p.rank,
            iterations=p.num_iterations,
            reg=p.lambda_,
            implicit=p.implicit_prefs,
            alpha=p.alpha,
            block_size=p.block_size,
            seed=p.seed,
            seg_len=p.seg_len,
            solver=p.solver,
            cg_iters=p.cg_iters,
            cg_unroll=p.cg_unroll,
            cg_precond=p.cg_precond,
            cg_dtype=p.cg_dtype,
            compute_dtype=p.compute_dtype,
        )
        if pd.binned_request is not None:
            return self._train_binned(ctx, pd, cfg)
        factors = als_train(
            (pd.user_idx, pd.item_idx, pd.ratings),
            pd.n_users,
            pd.n_items,
            cfg,
            mesh=ctx.mesh,
            max_ratings_per_user=p.max_ratings_per_user,
            max_ratings_per_item=p.max_ratings_per_item,
            # retrain-on-unchanged-events skips re-binning (ops.bincache)
            cache_key=pd.fingerprint,
        )
        return ALSModel(factors, pd.user_ids, pd.item_ids,
                        index_backend=p.index_backend,
                        index_kernel=p.index_kernel)

    def _train_binned(self, ctx: MeshContext, pd: PreparedRatings,
                      cfg: ALSConfig) -> ALSModel:
        """The zero-copy lane: warm starts load the compressed layout
        (+ vocabularies) from the bin cache as mmap views; cold starts
        make ONE fused native scan+bin call (store.bin_columnar — no
        COO, no Event objects, no Python row loop) and persist the
        layout WITH the vocabularies so the next warm start skips the
        read entirely. Either way the sides go to
        ``ALSTrainer.from_sides`` and the chunked H2D pipeline."""
        from predictionio_tpu.data.storage import pack_vocab, unpack_vocab
        from predictionio_tpu.obs import perfacct
        from predictionio_tpu.ops import bincache
        from predictionio_tpu.ops.als import (ALSTrainer, SideLayout,
                                              als_row_cost_slots,
                                              layout_cache_key,
                                              side_layout_from_binned)

        p: ALSParams = self.params
        n_shards = ctx.mesh.shape["data"] if ctx.mesh is not None else 1
        # SAME key derivation as ALSTrainer's internal COO-path cache:
        # the layouts are bit-identical, so either lane's entry serves
        # the other
        key = None
        cached = None
        if pd.fingerprint:
            key = layout_cache_key(pd.fingerprint, cfg, n_shards,
                                   p.max_ratings_per_user,
                                   p.max_ratings_per_item)
            cached = bincache.load(key)
        if cached is not None:
            arrays, meta = cached
            if "u_vocab_bytes" in arrays:
                user_vocab = unpack_vocab(arrays["u_vocab_bytes"],
                                          arrays["u_vocab_offs"])
                item_vocab = unpack_vocab(arrays["i_vocab_bytes"],
                                          arrays["i_vocab_offs"])
                trainer = ALSTrainer.from_sides(
                    SideLayout.from_arrays(arrays, "u_", meta),
                    SideLayout.from_arrays(arrays, "i_", meta),
                    int(meta["n_users"]), int(meta["n_items"]),
                    int(meta["total_entries"]), cfg, mesh=ctx.mesh)
                trainer.cache_hit = True
                return ALSModel(trainer.run(),
                                BiMap.from_vocab(user_vocab),
                                BiMap.from_vocab(item_vocab),
                                index_backend=p.index_backend,
                                index_kernel=p.index_kernel)
            # entry saved by the COO lane (no vocab): rebuild below and
            # overwrite it with a vocab-carrying entry

        req = pd.binned_request
        binned = req.bin(
            seg_len=cfg.seg_len,
            max_len_user=p.max_ratings_per_user,
            max_len_item=p.max_ratings_per_item,
            n_shards=n_shards, block_size=cfg.block_size,
            row_cost_slots=als_row_cost_slots(cfg.rank))
        if binned.n_rows == 0:
            raise ValueError(
                "PreparedRatings is empty — no rating events found")
        # ledger sub-stages: the native call's own scan/bin split (the
        # engine's coarse read/prepare stages were ~0 on this lane)
        perfacct.LEDGER.note_stage("read", binned.scan_sec)
        perfacct.LEDGER.note_stage("bin", binned.bin_sec)
        user_side = side_layout_from_binned(binned.user_side)
        item_side = side_layout_from_binned(binned.item_side)
        n_users = len(binned.entity_vocab)
        n_items = len(binned.target_vocab)
        trainer = ALSTrainer.from_sides(
            user_side, item_side, n_users, n_items, binned.n_rows, cfg,
            mesh=ctx.mesh)
        if key is not None:
            import numpy as _np

            uv_b, uv_o = pack_vocab(binned.entity_vocab)
            iv_b, iv_o = pack_vocab(binned.target_vocab)
            arrays = {
                **user_side.to_arrays("u_"), **item_side.to_arrays("i_"),
                "u_vocab_bytes": _np.frombuffer(uv_b, _np.uint8),
                "u_vocab_offs": uv_o,
                "i_vocab_bytes": _np.frombuffer(iv_b, _np.uint8),
                "i_vocab_offs": iv_o,
            }
            bincache.save(key, arrays, {
                "n_users": n_users, "n_items": n_items,
                "n_shards": n_shards, "total_entries": binned.n_rows,
                **user_side.meta("u_"), **item_side.meta("i_"),
            })
        return ALSModel(trainer.run(),
                        BiMap.from_vocab(binned.entity_vocab),
                        BiMap.from_vocab(binned.target_vocab),
                        index_backend=p.index_backend,
                        index_kernel=p.index_kernel)

    @classmethod
    def grid_train(
        cls,
        ctx: MeshContext,
        pd: PreparedRatings,
        params_list: Sequence["ALSParams"],
    ) -> Optional[List[ALSModel]]:
        """Train EVERY candidate in ONE compiled dispatch when the
        candidates differ only in SHAPE-STABLE scalars — lambda_,
        alpha, num_iterations, cg_iters (VERDICT r4 item 6; iteration
        counts ride as per-candidate step budgets: the program runs to
        the max and freezes finished candidates bit-identically to
        their sequential runs). The vmapped tuning path
        (ops.als.als_grid_train) behind MetricEvaluator (reference
        role: MetricEvaluator over engineParamsList,
        controller/MetricEvaluator.scala:177, which trains G times).

        Returns one model per candidate, or None when the grid shape
        does not apply (params differing beyond those scalars, or a
        multi-device mesh — the grid axis occupies the batch dimension,
        so sharded data training keeps the sequential path)."""
        if len(params_list) < 2:
            return None
        if pd.binned_request is not None:
            # the vmapped grid needs host COO; the zero-copy lane has
            # none — sequential per-candidate trains share the binned
            # layout via the cache instead (same key across candidates
            # differing only in the grid scalars)
            return None
        base = params_list[0]
        _GRID_SCALARS = ("lambda_", "alpha", "num_iterations", "cg_iters")
        for p in params_list:
            if not isinstance(p, ALSParams):
                return None
            a, b = dict(vars(p)), dict(vars(base))
            for k in _GRID_SCALARS:
                a.pop(k), b.pop(k)
            if a != b:
                return None
        if (base.max_ratings_per_user is not None
                or base.max_ratings_per_item is not None):
            # als_grid_train builds its sides uncapped; silently
            # training different data than the sequential path would is
            # exactly the kind of divergence grid tuning must not have
            # (code-review regression) — sequential path instead
            return None
        if ctx.mesh is not None and np.prod(
                [ctx.mesh.shape[a] for a in ctx.mesh.axis_names]) > 1:
            return None
        from predictionio_tpu.ops.als import als_grid_train

        cfg = ALSConfig(
            rank=base.rank, iterations=base.num_iterations,
            implicit=base.implicit_prefs, alpha=base.alpha,
            block_size=base.block_size, seed=base.seed,
            seg_len=base.seg_len, solver=base.solver,
            cg_iters=base.cg_iters, cg_unroll=base.cg_unroll,
            cg_precond=base.cg_precond, cg_dtype=base.cg_dtype,
            compute_dtype=base.compute_dtype,
        )
        factors_list = als_grid_train(
            (pd.user_idx, pd.item_idx, pd.ratings),
            pd.n_users, pd.n_items, cfg,
            regs=[p.lambda_ for p in params_list],
            alphas=[p.alpha for p in params_list],
            iterations=[p.num_iterations for p in params_list],
            cg_iters=[p.cg_iters for p in params_list],
        )
        return [ALSModel(f, pd.user_ids, pd.item_ids,
                         index_backend=base.index_backend,
                         index_kernel=base.index_kernel)
                for f in factors_list]

    def load_persistent_model(self, persisted: ALSModel, ctx: MeshContext) -> ALSModel:
        """Re-enable sharded serving after unpickle when the model was
        trained with it (the mesh never pickles; rebuild from ctx)."""
        axis = getattr(persisted, "sharded_axis", None)
        if axis is not None:
            mesh = ctx.require_mesh()
            if axis in mesh.axis_names and mesh.shape[axis] > 1:
                persisted.enable_sharded_serving(mesh, axis=axis)
            else:
                persisted.sharded_axis = None  # single-device deploy
        return persisted

    def warmup(self, model: ALSModel, ctx: MeshContext) -> None:
        """Pre-warm the serve path so the first queries after
        deploy/reload answer at steady-state latency (SURVEY.md §7.5
        hard part #2): k buckets 8 and 16 at B=1, then the BATCH-size
        buckets the micro-batched server dispatches under load (8/32)
        — covering first-touch costs on both scorer routes (XLA
        compiles on the device route, BLAS/thread-pool init on the
        host route) before live traffic pays them."""
        if len(model.user_ids) == 0 or len(model.item_ids) == 0:
            return
        # every (B, k) bucket the server can dispatch (B buckets up to
        # the default micro-batch cap of 64, k buckets 8 and 16): on
        # the device route each distinct bucket is an XLA compile that
        # would otherwise block a LIVE batch (code-review regression);
        # on the host route these are millisecond no-ops. Deploy/reload
        # warm BEFORE the swap, so this cost never blocks traffic.
        for b in (1, 2, 4, 8, 16, 32, 64):
            # batch size is bounded by CONCURRENT QUERIES (max_batch),
            # not distinct users — duplicate-user queries coalesce into
            # big batches, so small catalogs still need every bucket
            # warm (tile rows instead of capping at the user count)
            rows = model.user_factors[np.arange(b) % len(model.user_ids)]
            for k in (5, 10):
                model.scorer().score(rows, k)
        if model.sharded_axis is not None:
            # sharded serving never consults the single-device index —
            # building one would device-put the FULL item table onto
            # one chip, the exact thing the sharded catalog can't hold
            return
        # retrieval index: BUILD at model load (pio_index_build_seconds
        # prices it here, never on a live query) and warm the search
        # buckets both retrieval query shapes dispatch — user -> top-k
        # (no exclusions) and item -> similar (one self-exclusion)
        index = model.retrieval_index()
        for b in (1, 8):
            rows = model.user_factors[np.arange(b) % len(model.user_ids)]
            for k in (5, 10):
                index.search(rows, k)
        index.search(model.item_factors[:1],
                     min(10, len(model.item_ids)),
                     exclude=np.array([[0]], np.int32))

    def predict(self, model: ALSModel, query: Dict[str, Any]) -> Dict[str, Any]:
        num = int(query.get("num", 10))
        if "user" not in query and "item" in query:
            # item -> top-num similar items: candidate generation
            # through the retrieval index (the similarproduct-style
            # query surface on the factor templates)
            sims = model.similar_items(
                str(query["item"]), num,
                exclude_items=query.get("blacklist") or ())
            return {"itemScores": [{"item": i, "score": s}
                                   for i, s in sims]}
        recs = model.recommend(
            str(query["user"]),
            num,
            exclude_items=query.get("blacklist") or (),
            candidate_items=query.get("whitelist"),
        )
        return {"itemScores": [{"item": i, "score": s} for i, s in recs]}

    def batch_predict(self, model: ALSModel, queries):
        """Vector-scored evaluation path (ref: batchPredict for eval).

        Queries for known users are scored as one batched matmul+top-k;
        unknown users fall back to empty results.
        """
        known = [(i, q) for i, q in queries if str(q["user"]) in model.user_ids]
        unknown = [(i, q) for i, q in queries if str(q["user"]) not in model.user_ids]
        out = [(i, {"itemScores": []}) for i, q in unknown]
        if known:
            rows = np.array(
                [model.user_ids[str(q["user"])] for _, q in known], dtype=np.int64
            )
            num = max(int(q.get("num", 10)) for _, q in known)
            scores, idx = model.scorer().score(model.user_factors[rows], num)
            inv = model.item_ids.inverse()
            for (qi, q), s_row, i_row in zip(known, scores, idx):
                n = int(q.get("num", 10))
                out.append(
                    (
                        qi,
                        {
                            "itemScores": [
                                {"item": inv[int(i)], "score": float(s)}
                                for s, i in zip(s_row[:n], i_row[:n])
                            ]
                        },
                    )
                )
        return out
