"""Top-N Markov chain transition model (ref: e2/.../engine/MarkovChain.scala:25).

Behavior contract from the reference:

  - ``train`` takes a tally of state transitions (a sparse coordinate
    matrix), normalizes each row by its *full* row total, keeps the
    top-N entries per row (MarkovChain.scala:32-55).
  - ``predict`` multiplies a current-state probability vector through
    the kept transitions: next[j] = sum_i current[i] * P[i, j]
    (MarkovChain.scala:72-90).

TPU-first design: the ragged per-row top-N lists become fixed-shape
padded arrays ``indices[S, N]`` / ``probs[S, N]`` (pad prob = 0, so
padding is a no-op in the sum), and predict is one jitted
broadcast-multiply + scatter-add instead of the reference's
collect-and-loop over sparse vectors.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _predict(indices: jax.Array, probs: jax.Array, current: jax.Array) -> jax.Array:
    # weighted[i, n] = P(i -> indices[i, n]) * current[i]
    weighted = probs * current[:, None]
    out = jnp.zeros(current.shape[0], dtype=probs.dtype)
    return out.at[indices.reshape(-1)].add(weighted.reshape(-1))


@dataclass
class MarkovChainModel:
    """Padded top-N transition table; predict runs on-device."""

    indices: np.ndarray   # [n_states, top_n] int32 destination states
    probs: np.ndarray     # [n_states, top_n] float32 (0 = padding)
    top_n: int

    @property
    def n_states(self) -> int:
        return self.indices.shape[0]

    def predict(self, current_state: Sequence[float]) -> List[float]:
        """Next-state probabilities (ref: MarkovChainModel.predict :72)."""
        current = jnp.asarray(current_state, dtype=jnp.float32)
        if current.shape[0] != self.n_states:
            raise ValueError(
                f"current_state has {current.shape[0]} entries, "
                f"model has {self.n_states} states"
            )
        out = _predict(jnp.asarray(self.indices), jnp.asarray(self.probs), current)
        return [float(x) for x in np.asarray(out)]

    def transition_row(self, state: int) -> List[Tuple[int, float]]:
        """Kept (destination, probability) pairs of one row, by destination."""
        pairs = [
            (int(j), float(p))
            for j, p in zip(self.indices[state], self.probs[state])
            if p > 0.0
        ]
        return sorted(pairs)


def train(
    entries: Tuple[np.ndarray, np.ndarray, np.ndarray],
    n_states: int,
    top_n: int,
) -> MarkovChainModel:
    """Build the model from COO transition tallies (ref: MarkovChain.train :32).

    ``entries`` is (row, col, value) arrays of the tally matrix. Each
    row is normalized by its full total; only the ``top_n`` largest
    entries per row are kept (reference semantics — dropped mass is
    discarded, not renormalized).
    """
    rows = np.asarray(entries[0], dtype=np.int64)
    cols = np.asarray(entries[1], dtype=np.int64)
    vals = np.asarray(entries[2], dtype=np.float64)
    if top_n < 1:
        raise ValueError("top_n must be >= 1")
    if len(rows) and (rows.min() < 0 or rows.max() >= n_states
                      or cols.min() < 0 or cols.max() >= n_states):
        raise ValueError("COO entries reference states outside [0, n_states)")

    indices = np.zeros((n_states, top_n), dtype=np.int32)
    probs = np.zeros((n_states, top_n), dtype=np.float32)
    if not len(rows):
        return MarkovChainModel(indices=indices, probs=probs, top_n=top_n)

    # combine duplicate (row, col) tallies (streaming callers emit one
    # entry per observed transition)
    flat = rows * n_states + cols
    uniq, inverse = np.unique(flat, return_inverse=True)
    summed = np.zeros(len(uniq), dtype=np.float64)
    np.add.at(summed, inverse, vals)
    rows_u, cols_u = uniq // n_states, uniq % n_states

    totals = np.zeros(n_states, dtype=np.float64)
    np.add.at(totals, rows_u, summed)

    # vectorized per-row top-N: sort by (row asc, value desc), keep the
    # first top_n of each row, then re-sort kept entries by (row, col)
    # (reference stores kept entries column-sorted, MarkovChain.scala:45)
    order = np.lexsort((-summed, rows_u))
    rows_s, cols_s, vals_s = rows_u[order], cols_u[order], summed[order]
    row_starts = np.searchsorted(rows_s, rows_s)       # start offset of own row
    rank = np.arange(len(rows_s)) - row_starts
    keep = rank < top_n
    rows_k, cols_k, vals_k = rows_s[keep], cols_s[keep], vals_s[keep]

    order2 = np.lexsort((cols_k, rows_k))
    rows_k, cols_k, vals_k = rows_k[order2], cols_k[order2], vals_k[order2]
    slot = np.arange(len(rows_k)) - np.searchsorted(rows_k, rows_k)
    indices[rows_k, slot] = cols_k
    probs[rows_k, slot] = (vals_k / totals[rows_k]).astype(np.float32)

    return MarkovChainModel(indices=indices, probs=probs, top_n=top_n)
