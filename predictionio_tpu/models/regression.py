"""Linear regression algorithms over numeric feature vectors.

Behavior contracts from the reference regression examples
(examples/experimental/scala-parallel-regression/Run.scala:56-70,
examples/experimental/scala-local-regression/Run.scala):

  - ``SGDRegressionAlgorithm`` mirrors MLlib's
    ``LinearRegressionWithSGD.train(data, numIterations, stepSize)``:
    full-batch gradient descent on squared error with the MLlib
    step-size decay ``stepSize / sqrt(t)`` and no intercept (MLlib's
    default ``addIntercept = false``). The epoch loop is a single
    ``lax.scan`` under ``jit`` — the whole training run is one XLA
    program, gradients are one [N,D]x[D] matmul per step on the MXU.
  - ``RidgeRegressionAlgorithm`` is the TPU-first upgrade the Spark
    version never shipped: closed-form normal equations
    (X^T X + reg*I) w = X^T y — one Gramian matmul plus a D x D solve,
    exact in one pass instead of 200 SGD epochs.

Both predict a float from ``{"features": [...]}`` queries, so
``AverageServing`` can average multi-algorithm fan-outs exactly as
``LAverageServing`` does in the reference example's three-stepSize run
(Run.scala:88-92).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.core import Algorithm, SanityCheck
from predictionio_tpu.core.params import Params
from predictionio_tpu.parallel.mesh import MeshContext


@dataclass
class RegressionData(SanityCheck):
    """PD: dense feature matrix + float targets (ref: RDD[LabeledPoint],
    scala-parallel-regression/Run.scala:40-44)."""

    features: np.ndarray  # [N, D] float32
    targets: np.ndarray   # [N] float32

    def sanity_check(self) -> None:
        if len(self.features) == 0:
            raise ValueError("no labeled points found")
        if len(self.features) != len(self.targets):
            raise ValueError("features/targets length mismatch")


@dataclass
class LinearModel:
    weights: np.ndarray    # [D]
    intercept: float

    def predict(self, features: Sequence[float]) -> float:
        return float(np.dot(self.weights, np.asarray(features, dtype=np.float32))
                     + self.intercept)

    def predict_batch(self, features: np.ndarray) -> np.ndarray:
        return features @ self.weights + self.intercept


@dataclass
class SGDRegressionParams(Params):
    """ref: AlgorithmParams(numIterations=200, stepSize=0.1) Run.scala:54."""

    iterations: int = 200
    step_size: float = 0.1
    intercept: bool = False  # MLlib LinearRegressionWithSGD default


@partial(jax.jit, static_argnames=("iterations",))
def _sgd_fit(x, y, step_size, iterations):
    n = x.shape[0]

    def epoch(w, t):
        grad = x.T @ (x @ w - y) / n
        # MLlib GradientDescent: thisIterStepSize = stepSize / sqrt(t)
        return w - step_size / jnp.sqrt(t) * grad, None

    w0 = jnp.zeros((x.shape[1],), dtype=x.dtype)
    w, _ = jax.lax.scan(epoch, w0, jnp.arange(1, iterations + 1, dtype=x.dtype))
    return w


def train_sgd_regression(pd: RegressionData, p: SGDRegressionParams) -> LinearModel:
    x = np.asarray(pd.features, dtype=np.float32)
    y = np.asarray(pd.targets, dtype=np.float32)
    if p.intercept:
        x = np.concatenate([x, np.ones((len(x), 1), dtype=np.float32)], axis=1)
    w = np.asarray(_sgd_fit(jnp.asarray(x), jnp.asarray(y),
                            jnp.float32(p.step_size), p.iterations))
    if p.intercept:
        return LinearModel(weights=w[:-1], intercept=float(w[-1]))
    return LinearModel(weights=w, intercept=0.0)


@dataclass
class RidgeRegressionParams(Params):
    reg: float = 1e-6
    intercept: bool = True


@jax.jit
def _ridge_gram(x, y):
    return x.T @ x, x.T @ y


def train_ridge_regression(pd: RegressionData, p: RidgeRegressionParams) -> LinearModel:
    x = np.asarray(pd.features, dtype=np.float32)
    y = np.asarray(pd.targets, dtype=np.float32)
    if p.intercept:
        x = np.concatenate([x, np.ones((len(x), 1), dtype=np.float32)], axis=1)
    # Gramian (the O(N*D^2) matmul) on device; the D x D solve on host in
    # float64 via lstsq — collinear feature columns give the min-norm
    # solution instead of silent float32 NaNs
    gram, xty = _ridge_gram(jnp.asarray(x), jnp.asarray(y))
    d = x.shape[1]
    penalty = np.eye(d)
    if p.intercept:
        penalty[-1, -1] = 0.0  # standard ridge never shrinks the intercept
    a = np.asarray(gram, dtype=np.float64) + p.reg * penalty
    w = np.linalg.lstsq(a, np.asarray(xty, dtype=np.float64), rcond=None)[0]
    w = w.astype(np.float32)
    if p.intercept:
        return LinearModel(weights=w[:-1], intercept=float(w[-1]))
    return LinearModel(weights=w, intercept=0.0)


class _RegressionAlgorithmBase(Algorithm):
    def predict(self, model: LinearModel, query: Dict[str, Any]) -> float:
        return model.predict([float(v) for v in query["features"]])

    def batch_predict(self, model, queries):
        from predictionio_tpu.models import batch_predict_dense

        return batch_predict_dense(model, queries)


class SGDRegressionAlgorithm(_RegressionAlgorithmBase):
    """ref: ParallelSGDAlgorithm (scala-parallel-regression/Run.scala:56)."""

    def __init__(self, params: SGDRegressionParams):
        super().__init__(params)

    def train(self, ctx: MeshContext, pd: RegressionData) -> LinearModel:
        return train_sgd_regression(pd, self.params)


class RidgeRegressionAlgorithm(_RegressionAlgorithmBase):
    """Closed-form slot (see module docstring)."""

    def __init__(self, params: RidgeRegressionParams):
        super().__init__(params)

    def train(self, ctx: MeshContext, pd: RegressionData) -> LinearModel:
        return train_ridge_regression(pd, self.params)
