"""Algorithm library (ref: e2/ engines + examples/ template algorithms).

Each module pairs a JAX/TPU compute core from predictionio_tpu.ops with
a DASE Algorithm wrapper:

  als            — matrix factorization (ref: MLlib ALS templates)
  naive_bayes    — categorical NB (ref: e2/.../CategoricalNaiveBayes.scala)
  logistic       — logistic regression via optax (ref: classification template)
  similarproduct — item-cosine similarity (ref: scala-parallel-similarproduct)
  ecommerce      — ALS + business-rule serving filters
                   (ref: scala-parallel-ecommercerecommendation)
  markov         — top-N transition chains (ref: e2/.../MarkovChain.scala)
  two_tower      — flax neural recommender (stretch config in BASELINE.json)
"""
