"""Algorithm library (ref: e2/ engines + examples/ template algorithms).

Each module pairs a JAX/TPU compute core from predictionio_tpu.ops with
a DASE Algorithm wrapper:

  als            — matrix factorization (ref: MLlib ALS templates)
  naive_bayes    — categorical NB (ref: e2/.../CategoricalNaiveBayes.scala)
  logistic       — logistic regression via optax (ref: classification template)
  similarproduct — item-cosine similarity (ref: scala-parallel-similarproduct)
  ecommerce      — ALS + business-rule serving filters
                   (ref: scala-parallel-ecommercerecommendation)
  markov         — top-N transition chains (ref: e2/.../MarkovChain.scala)
  two_tower      — neural retrieval recommender (stretch config in BASELINE.json)
"""

from typing import Any, Callable, List, Sequence, Tuple

import numpy as np

from predictionio_tpu.obs import jaxmon


def batch_predict_dense(
    model: Any,
    queries: Sequence[Tuple[int, Any]],
    wrap: Callable[[float], Any] = float,
) -> List[Tuple[int, Any]]:
    """Shared glue for algorithms over dense ``{"features": [...]}``
    queries: stack the batch into one [B, D] matrix, score it with the
    model's vectorized ``predict_batch``, and wrap each output. Handles
    the empty fold ``engine.eval`` can produce (dataset rows < eval_k)."""
    if not queries:
        return []
    feats = np.array([q["features"] for _, q in queries], dtype=np.float32)
    jaxmon.record_transfer(feats.nbytes, "h2d")
    preds = model.predict_batch(feats)
    jaxmon.record_transfer(getattr(preds, "nbytes", None), "d2h")
    return [(i, wrap(p)) for (i, _q), p in zip(queries, preds)]
