"""Classification algorithms over numeric feature vectors.

Behavior contracts:

  - ``NaiveBayesAlgorithm`` mirrors the reference classification
    template (examples/scala-parallel-classification/add-algorithm/
    src/main/scala/NaiveBayesAlgorithm.scala:16-28), which delegates to
    MLlib's multinomial NaiveBayes with additive smoothing ``lambda``:
      pi(c)     = log((count_c + lambda) / (N + numLabels * lambda))
      theta(c,j)= log((sum_{i in c} x_ij + lambda)
                      / (sum_j sum_{i in c} x_ij + numFeatures * lambda))
      predict(x) = argmax_c pi(c) + theta(c) . x
    Labels are floats, as in MLlib.
  - ``LogisticRegressionAlgorithm`` is the second-algorithm slot the
    reference fills with MLlib RandomForest (RandomForestAlgorithm.scala
    in the same template). Tree ensembles do not map onto the MXU, so
    the TPU build's second algorithm is softmax regression trained with
    optax — same engine-level contract (numeric features in, float
    label out), compute that is all matmuls.

Training is segment-sum counting / full-batch gradient steps under
``jit``; prediction is one matmul + argmax.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.core import Algorithm, SanityCheck
from predictionio_tpu.core.params import Params
from predictionio_tpu.parallel.mesh import MeshContext


@dataclass
class LabeledVectors(SanityCheck):
    """PD: dense feature matrix + float labels (ref: TrainingData w/
    RDD[LabeledPoint], DataSource.scala:58)."""

    features: np.ndarray   # [N, D] float32
    labels: np.ndarray     # [N] float

    def sanity_check(self) -> None:
        if len(self.features) == 0:
            raise ValueError("no labeled points found")
        if len(self.features) != len(self.labels):
            raise ValueError("features/labels length mismatch")


# -- multinomial naive Bayes -------------------------------------------------

@partial(jax.jit, static_argnames=("n_classes",))
def _nb_counts(features: jax.Array, label_idx: jax.Array, n_classes: int):
    one_hot = jax.nn.one_hot(label_idx, n_classes, dtype=features.dtype)  # [N, C]
    class_counts = one_hot.sum(axis=0)                 # [C]
    feature_sums = one_hot.T @ features                # [C, D] MXU
    return class_counts, feature_sums


@dataclass
class NaiveBayesModel:
    class_labels: np.ndarray   # [C] float — MLlib label values
    pi: np.ndarray             # [C] log priors
    theta: np.ndarray          # [C, D] log feature likelihoods

    def _scores(self, x: np.ndarray) -> np.ndarray:
        x = jnp.atleast_2d(jnp.asarray(x, dtype=jnp.float32))
        return np.asarray(
            jnp.asarray(self.pi)[None, :] + x @ jnp.asarray(self.theta).T
        )

    def predict(self, features: Sequence[float]) -> float:
        return float(self.class_labels[int(np.argmax(self._scores(np.asarray(features))))])

    def predict_batch(self, features: np.ndarray) -> np.ndarray:
        return self.class_labels[np.argmax(self._scores(features), axis=1)]


def train_naive_bayes(pd: LabeledVectors, lambda_: float = 1.0) -> NaiveBayesModel:
    class_labels, label_idx = np.unique(pd.labels, return_inverse=True)
    n_classes = len(class_labels)
    class_counts, feature_sums = _nb_counts(
        jnp.asarray(pd.features, dtype=jnp.float32),
        jnp.asarray(label_idx),
        n_classes,
    )
    class_counts = np.asarray(class_counts, dtype=np.float64)
    feature_sums = np.asarray(feature_sums, dtype=np.float64)
    n, d = len(pd.labels), pd.features.shape[1]
    pi = np.log(class_counts + lambda_) - np.log(n + n_classes * lambda_)
    theta = np.log(feature_sums + lambda_) - np.log(
        feature_sums.sum(axis=1, keepdims=True) + d * lambda_
    )
    return NaiveBayesModel(
        class_labels=class_labels,
        pi=pi.astype(np.float32),
        theta=theta.astype(np.float32),
    )


@dataclass
class NaiveBayesParams(Params):
    lambda_: float = 1.0


class NaiveBayesAlgorithm(Algorithm):
    """ref: NaiveBayesAlgorithm.scala:16."""

    def __init__(self, params: NaiveBayesParams):
        super().__init__(params)

    def train(self, ctx: MeshContext, pd: LabeledVectors) -> NaiveBayesModel:
        return train_naive_bayes(pd, self.params.lambda_)

    def predict(self, model: NaiveBayesModel, query: Dict[str, Any]) -> Dict[str, Any]:
        return {"label": model.predict([float(v) for v in query["features"]])}

    def batch_predict(self, model, queries):
        from predictionio_tpu.models import batch_predict_dense

        return batch_predict_dense(model, queries, lambda l: {"label": float(l)})


# -- softmax regression (optax) ----------------------------------------------

@dataclass
class LogisticRegressionModel:
    class_labels: np.ndarray   # [C] float
    weights: np.ndarray        # [D, C]
    bias: np.ndarray           # [C]
    feature_mean: np.ndarray   # [D] standardization applied at train time
    feature_std: np.ndarray    # [D]

    def _scores(self, x: np.ndarray) -> np.ndarray:
        x = np.atleast_2d(np.asarray(x, dtype=np.float32))
        x = (x - self.feature_mean) / self.feature_std
        return x @ self.weights + self.bias

    def predict(self, features: Sequence[float]) -> float:
        return float(self.class_labels[int(np.argmax(self._scores(np.asarray(features))))])

    def predict_batch(self, features: np.ndarray) -> np.ndarray:
        return self.class_labels[np.argmax(self._scores(features), axis=1)]


@dataclass
class LogisticRegressionParams(Params):
    learning_rate: float = 0.1
    iterations: int = 200
    l2: float = 1e-4
    seed: int = 0


def train_logistic_regression(
    pd: LabeledVectors, p: LogisticRegressionParams
) -> LogisticRegressionModel:
    import optax

    class_labels, label_idx = np.unique(pd.labels, return_inverse=True)
    n_classes = len(class_labels)
    d = pd.features.shape[1]
    mean = pd.features.mean(axis=0)
    std = np.maximum(pd.features.std(axis=0), 1e-8)
    x = jnp.asarray((pd.features - mean) / std, dtype=jnp.float32)
    y = jnp.asarray(label_idx)

    tx = optax.adam(p.learning_rate)
    params = {
        "w": jnp.zeros((d, n_classes), dtype=jnp.float32),
        "b": jnp.zeros((n_classes,), dtype=jnp.float32),
    }

    def loss_fn(params):
        logits = x @ params["w"] + params["b"]
        nll = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
        return nll + p.l2 * (params["w"] ** 2).sum()

    # donate params/opt_state: the loop rebinds both every iteration,
    # so without donation the old and new copies coexist (JT07)
    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    opt_state = tx.init(params)
    for _ in range(p.iterations):
        params, opt_state, _loss = step(params, opt_state)

    return LogisticRegressionModel(
        class_labels=class_labels,
        weights=np.asarray(params["w"]),
        bias=np.asarray(params["b"]),
        feature_mean=mean.astype(np.float32),
        feature_std=std.astype(np.float32),
    )


class LogisticRegressionAlgorithm(Algorithm):
    """Second algorithm slot (ref: RandomForestAlgorithm.scala — see
    module docstring for the substitution rationale)."""

    def __init__(self, params: LogisticRegressionParams):
        super().__init__(params)

    def train(self, ctx: MeshContext, pd: LabeledVectors) -> LogisticRegressionModel:
        return train_logistic_regression(pd, self.params)

    def predict(self, model, query: Dict[str, Any]) -> Dict[str, Any]:
        return {"label": model.predict([float(v) for v in query["features"]])}

    def batch_predict(self, model, queries):
        from predictionio_tpu.models import batch_predict_dense

        return batch_predict_dense(model, queries, lambda l: {"label": float(l)})
