"""Similar-product algorithms: item-to-item similarity over ALS factors.

Behavior contract from the reference similarproduct template
(examples/scala-parallel-similarproduct/multi/src/main/scala/
ALSAlgorithm.scala + LikeAlgorithm.scala):

  - ``ALSAlgorithm.train`` indexes users/items, aggregates duplicate
    (user, item) view events into counts, trains *implicit* ALS, keeps
    the item ("product") factors + item metadata (:74-144).
  - ``LikeAlgorithm.train`` does the same over like/dislike events with
    rating +1 / -1 (LikeAlgorithm.scala:27-99).
  - ``predict``: look up the query items' factor vectors, score every
    item by the SUM of cosine similarities to the query vectors, drop
    the query items themselves, apply whiteList/blackList/categories
    candidate predicates, return top-``num`` (:146-207, 239-263).

TPU-first design: sum-of-cosines factorizes — with row-normalized
factors F, sum_q cos(f_q, f_i) = (sum_q F[q]) . F[i] — so the whole
scoring pass is one query-vector sum plus one masked [1,K]x[K,I] matmul
+ top_k on device (ops.topk.score_masked); the candidate predicate is a
vectorized host-side bool mask, not a per-item filter loop.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from predictionio_tpu.core import Algorithm, SanityCheck
from predictionio_tpu.core.params import Params
from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.ops.als import ALSConfig, als_train
from predictionio_tpu.ops.topk import NEG_INF, TopKScorer, cosine_normalize
from predictionio_tpu.parallel.mesh import MeshContext


@dataclass
class SimilarProductData(SanityCheck):
    """TD/PD: users, items (with optional categories), and interactions."""

    users: List[str] = field(default_factory=list)
    items: List[str] = field(default_factory=list)
    item_categories: Dict[str, List[str]] = field(default_factory=dict)
    # (user, item) view pairs
    view_events: List[Tuple[str, str]] = field(default_factory=list)
    # (user, item, like?) pairs
    like_events: List[Tuple[str, str, bool]] = field(default_factory=list)

    def sanity_check(self) -> None:
        if not self.users:
            raise ValueError("users cannot be empty")
        if not self.items:
            raise ValueError("items cannot be empty")
        if not self.view_events and not self.like_events:
            raise ValueError("no view/like events found")


@dataclass
class SimilarProductParams(Params):
    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    seed: int = 3
    block_size: int = 4096


class SimilarProductModel:
    """Row-normalized item factors resident on device + item metadata."""

    def __init__(
        self,
        item_factors: np.ndarray,      # [I, K] raw ALS factors
        item_ids: BiMap,
        item_categories: Dict[str, List[str]],
    ):
        self.item_factors = np.asarray(item_factors, dtype=np.float32)
        self.item_ids = item_ids
        self.item_categories = item_categories
        self._normalized = cosine_normalize(self.item_factors)
        self._scorer: Optional[TopKScorer] = None
        self._index = None
        self._category_index: Optional[Dict[str, np.ndarray]] = None

    def __getstate__(self):
        d = dict(self.__dict__)
        d["_scorer"] = None
        d["_category_index"] = None
        d["_index"] = None
        return d

    def __setstate__(self, d):
        d.setdefault("_index", None)
        self.__dict__.update(d)

    def scorer(self) -> TopKScorer:
        if self._scorer is None:
            self._scorer = TopKScorer(self._normalized)
        return self._scorer

    def retrieval_index(self):
        """ANN candidate generation over the ROW-NORMALIZED item table
        (predictionio_tpu/index — dot == cosine here): the
        exclusion-only query shape goes through it; whitelist/category
        predicates keep the masked scorer (masks are not an AnnIndex
        surface)."""
        if self._index is None:
            from predictionio_tpu.index import make_index

            self._index = make_index(self._normalized)
        return self._index

    def retrieval_stats(self) -> Optional[dict]:
        return self._index.stats() if self._index is not None else None

    def _category_mask(self, categories: Set[str]) -> np.ndarray:
        """[I] bool — items sharing >=1 category with the query.

        Items without categories are discarded when a category filter is
        given (ref: isCandidateItem .getOrElse(false))."""
        if self._category_index is None:
            idx: Dict[str, np.ndarray] = {}
            per_cat: Dict[str, List[int]] = {}
            for item, cats in self.item_categories.items():
                row = self.item_ids.get(item)
                if row is None:
                    continue
                for c in cats:
                    per_cat.setdefault(c, []).append(row)
            n = len(self.item_ids)
            for c, rows in per_cat.items():
                m = np.zeros(n, dtype=bool)
                m[rows] = True
                idx[c] = m
            self._category_index = idx
        mask = np.zeros(len(self.item_ids), dtype=bool)
        for c in categories:
            m = self._category_index.get(c)
            if m is not None:
                mask |= m
        return mask

    def similar(
        self,
        items: Sequence[str],
        num: int,
        categories: Optional[Set[str]] = None,
        white_list: Optional[Set[str]] = None,
        black_list: Optional[Set[str]] = None,
    ) -> List[Tuple[str, float]]:
        """Top-num items by summed cosine similarity to ``items``."""
        query_rows = [self.item_ids[i] for i in items if i in self.item_ids]
        if not query_rows:
            return []
        qvec = self._normalized[query_rows].sum(axis=0)

        n = len(self.item_ids)
        # exclusion-only queries (no whitelist/category predicate) are
        # CANDIDATE GENERATION — route them through the retrieval
        # index; predicate queries keep the masked scorer (a bool mask
        # is not an AnnIndex surface)
        if white_list is None and not categories:
            excl_rows = set(query_rows)
            if black_list:
                excl_rows |= {self.item_ids[i] for i in black_list
                              if i in self.item_ids}
            index = self.retrieval_index()
            max_excl = getattr(index, "max_exclude", 64)
            if len(excl_rows) <= max_excl:
                scores, idx = index.search(
                    qvec, num,
                    np.fromiter(excl_rows, np.int32, count=len(excl_rows)))
                inv = self.item_ids.inverse()
                return [
                    (inv[int(i)], float(s))
                    for s, i in zip(scores[0], idx[0])
                    if s > 0.0 and int(i) >= 0  # ref keeps score > 0 (:174)
                ]

        mask = np.ones(n, dtype=bool)
        mask[query_rows] = False                     # discard query items
        if white_list is not None:
            wl = np.zeros(n, dtype=bool)
            wl[[self.item_ids[i] for i in white_list if i in self.item_ids]] = True
            mask &= wl
        if black_list:
            mask[[self.item_ids[i] for i in black_list if i in self.item_ids]] = False
        if categories:
            mask &= self._category_mask(set(categories))
        if not mask.any():
            return []

        scores, idx = self.scorer().score_masked(qvec, num, mask)
        inv = self.item_ids.inverse()
        return [
            (inv[int(i)], float(s))
            for s, i in zip(scores[0], idx[0])
            if s > 0.0  # ref keeps score > 0 only (:174)
        ]


def _train_als_item_factors(
    pairs: List[Tuple[int, int, float]],
    n_users: int,
    n_items: int,
    p: SimilarProductParams,
    ctx: MeshContext,
) -> np.ndarray:
    u, i, r = (
        np.array([x[0] for x in pairs], dtype=np.int64),
        np.array([x[1] for x in pairs], dtype=np.int64),
        np.array([x[2] for x in pairs], dtype=np.float32),
    )
    cfg = ALSConfig(
        rank=p.rank,
        iterations=p.num_iterations,
        reg=p.lambda_,
        implicit=True,
        alpha=1.0,
        block_size=p.block_size,
        seed=p.seed,
    )
    factors = als_train((u, i, r), n_users, n_items, cfg, mesh=ctx.mesh)
    return np.asarray(factors.item_factors)


class SimilarProductAlgorithm(Algorithm):
    """Implicit ALS over view counts (ref: ALSAlgorithm.scala:69)."""

    def __init__(self, params: SimilarProductParams):
        super().__init__(params)

    def _interactions(self, pd: SimilarProductData) -> Dict[Tuple[str, str], float]:
        counts: Dict[Tuple[str, str], float] = {}
        for user, item in pd.view_events:
            counts[(user, item)] = counts.get((user, item), 0.0) + 1.0
        return counts

    def train(self, ctx: MeshContext, pd: SimilarProductData) -> SimilarProductModel:
        user_ids = BiMap.string_int(pd.users)
        item_ids = BiMap.string_int(pd.items)
        pairs = [
            (user_ids[u], item_ids[i], r)
            for (u, i), r in self._interactions(pd).items()
            if u in user_ids and i in item_ids
        ]
        if not pairs:
            raise ValueError(
                "ratings cannot be empty — check that events contain valid "
                "user and item IDs"
            )
        item_factors = _train_als_item_factors(
            pairs, len(user_ids), len(item_ids), self.params, ctx
        )
        return SimilarProductModel(item_factors, item_ids, pd.item_categories)

    def warmup(self, model: SimilarProductModel, ctx: MeshContext) -> None:
        """Pre-compile the serve buckets (B=1, k buckets 8 and 16)
        through the real query path — the exclusion-only call builds
        the retrieval index at model load, the category call warms the
        masked-scorer route."""
        first = next(iter(model.item_ids.keys()), None)
        if first is None:
            return
        for num in (5, 10):
            model.similar([first], num)
        cats = next(iter(model.item_categories.values()), None)
        if cats:
            model.similar([first], 10, categories=set(cats[:1]))

    def predict(self, model: SimilarProductModel, query: Dict[str, Any]) -> Dict[str, Any]:
        recs = model.similar(
            [str(i) for i in query["items"]],
            int(query.get("num", 10)),
            categories=set(query["categories"]) if query.get("categories") else None,
            white_list=set(query["whiteList"]) if query.get("whiteList") else None,
            black_list=set(query["blackList"]) if query.get("blackList") else None,
        )
        return {"itemScores": [{"item": i, "score": s} for i, s in recs]}

    def batch_predict(self, model, queries):
        return [(i, self.predict(model, q)) for i, q in queries]


class LikeAlgorithm(SimilarProductAlgorithm):
    """Same ALS over like/dislike = +1/-1 (ref: LikeAlgorithm.scala:27);
    duplicate (user, item) pairs keep the LATEST event's polarity."""

    def _interactions(self, pd: SimilarProductData) -> Dict[Tuple[str, str], float]:
        latest: Dict[Tuple[str, str], float] = {}
        for user, item, like in pd.like_events:  # events arrive time-ordered
            latest[(user, item)] = 1.0 if like else -1.0
        return latest
