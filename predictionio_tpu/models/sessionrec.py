"""Sequential (next-item) recommendation as a DASE Algorithm.

The long-context model family of the rebuild — no reference counterpart
exists (SURVEY.md §5.7: PredictionIO has no sequence dimension), so the
behavior contract is the recommendation template's query surface
(top-``num`` itemScores, ref: examples/scala-parallel-recommendation
Serving.scala) applied to *ordered* histories: the model answers "what
comes next for this user", not "what does this user like overall".
Compute core: ops.sessionrec (causal transformer; blockwise or ring
attention for histories past one device's HBM).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from predictionio_tpu.core import Algorithm, SanityCheck
from predictionio_tpu.core.params import Params
from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.ops.sessionrec import (
    SessionRecConfig,
    SessionRecModelState,
    SessionRecTrainer,
    SessionScorer,
)
from predictionio_tpu.parallel.mesh import MeshContext


@dataclass
class PreparedSequences(SanityCheck):
    """PD for sequence models: indexed, timestamped interaction triples."""

    user_ids: BiMap
    item_ids: BiMap
    user_idx: np.ndarray     # [n] int
    item_idx: np.ndarray     # [n] int
    times: np.ndarray        # [n] float64 (epoch seconds)

    @property
    def n_users(self) -> int:
        return len(self.user_ids)

    @property
    def n_items(self) -> int:
        return len(self.item_ids)

    def sanity_check(self) -> None:
        if len(self.user_idx) == 0:
            raise ValueError("PreparedSequences is empty — no events found")
        if not (len(self.user_idx) == len(self.item_idx) == len(self.times)):
            raise ValueError("sequence arrays length mismatch")


@dataclass
class SessionRecParams(Params):
    dim: int = 64
    heads: int = 2
    layers: int = 2
    ffn_mult: int = 4
    max_len: int = 64
    dropout: float = 0.1
    learning_rate: float = 1e-3
    weight_decay: float = 1e-6
    epochs: int = 5
    batch_size: int = 256
    seed: int = 13
    attn_block: int = 0              # >0: flash-style blockwise attention
    seq_axis: Optional[str] = None   # mesh axis for ring attention (SP)
    checkpoint_dir: Optional[str] = None   # mid-training checkpoint/resume
    checkpoint_every: int = 1


class SessionRecModel:
    """Params + per-user histories + id maps; scorer compiled lazily."""

    def __init__(self, state: SessionRecModelState, user_ids: BiMap, item_ids: BiMap):
        self.state = state
        self.user_ids = user_ids
        self.item_ids = item_ids
        self._scorer: Optional[SessionScorer] = None

    def __getstate__(self):
        d = dict(self.__dict__)
        d["_scorer"] = None          # device buffers never pickle
        return d

    def scorer(self) -> SessionScorer:
        if self._scorer is None:
            self._scorer = SessionScorer(self.state)
        return self._scorer

    def _sequence_for(self, query: Dict[str, Any]) -> Optional[np.ndarray]:
        """Resolve the history to encode: an explicit ``items`` list in
        the query (session-based, works for anonymous users) wins over
        the stored training history."""
        max_len = self.state.cfg.max_len
        items = query.get("items")
        if items is not None:
            idx = [self.item_ids[i] + 1 for i in map(str, items) if i in self.item_ids]
            if not idx:
                return None
            row = np.zeros(max_len, np.int32)
            tail = idx[-max_len:]
            row[: len(tail)] = tail
            return row
        row_id = self.user_ids.get(str(query.get("user", "")))
        if row_id is None:
            return None
        row = self.state.sequences[row_id]
        return row if (row > 0).any() else None

    def recommend(self, query: Dict[str, Any]) -> List[Tuple[str, float]]:
        seq = self._sequence_for(query)
        if seq is None:
            return []
        num = int(query.get("num", 10))
        scores, idx = self.scorer().top_k(
            seq[None, :], num, exclude_seen=bool(query.get("excludeSeen", False))
        )
        inv = self.item_ids.inverse()
        return [
            (inv[int(i)], float(s))
            for s, i in zip(scores[0], idx[0])
            if i >= 0 and np.isfinite(s)
        ]


class SessionRecAlgorithm(Algorithm):
    """DASE wrapper over ops.sessionrec."""

    def __init__(self, params: SessionRecParams):
        super().__init__(params)

    def train(self, ctx: MeshContext, pd: PreparedSequences) -> SessionRecModel:
        p: SessionRecParams = self.params
        cfg = SessionRecConfig(
            dim=p.dim, heads=p.heads, layers=p.layers, ffn_mult=p.ffn_mult,
            max_len=p.max_len, dropout=p.dropout,
            learning_rate=p.learning_rate, weight_decay=p.weight_decay,
            epochs=p.epochs, batch_size=p.batch_size, seed=p.seed,
            attn_block=p.attn_block, seq_axis=p.seq_axis,
            checkpoint_dir=p.checkpoint_dir,
            checkpoint_every=p.checkpoint_every,
        )
        # ring attention needs a mesh even when the caller didn't build
        # one (same contract as ALSAlgorithm: require on demand)
        mesh = ctx.require_mesh() if p.seq_axis else ctx.mesh
        trainer = SessionRecTrainer(
            (pd.user_idx, pd.item_idx, pd.times),
            pd.n_users, pd.n_items, cfg, mesh=mesh,
        )
        losses = trainer.run()
        state = trainer.state(losses)
        return SessionRecModel(state, pd.user_ids, pd.item_ids)

    def warmup(self, model: SessionRecModel, ctx: MeshContext) -> None:
        """Pre-compile the B=1 encoder + top-k for both excludeSeen
        variants (the flag is jit-static) so the first live session
        query answers at warm latency."""
        if len(model.item_ids) == 0:
            return
        seq = np.zeros((1, model.state.cfg.max_len), np.int32)
        seq[0, 0] = 1  # one real (1-shifted) item position
        for exclude_seen in (False, True):
            model.scorer().top_k(seq, 10, exclude_seen=exclude_seen)

    def predict(self, model: SessionRecModel, query: Dict[str, Any]) -> Dict[str, Any]:
        recs = model.recommend(query)
        return {"itemScores": [{"item": i, "score": s} for i, s in recs]}

    def batch_predict(self, model: SessionRecModel, queries):
        """Batched evaluation: resolve every query's history, encode and
        score them as one fixed-shape device batch per excludeSeen value
        (the flag is jit-static, so mixed batches split in two)."""
        groups: Dict[bool, list] = {False: [], True: []}
        out = []
        for qi, q in queries:
            seq = model._sequence_for(q)
            if seq is None:
                out.append((qi, {"itemScores": []}))
            else:
                groups[bool(q.get("excludeSeen", False))].append((qi, q, seq))
        inv = model.item_ids.inverse()
        for exclude_seen, resolved in groups.items():
            if not resolved:
                continue
            batch = np.stack([seq for _, _, seq in resolved])
            num = max(int(q.get("num", 10)) for _, q, _ in resolved)
            scores, idx = model.scorer().top_k(
                batch, num, exclude_seen=exclude_seen
            )
            for (qi, q, _), s_row, i_row in zip(resolved, scores, idx):
                n = int(q.get("num", 10))
                out.append((qi, {
                    "itemScores": [
                        {"item": inv[int(i)], "score": float(s)}
                        for s, i in zip(s_row[:n], i_row[:n])
                        if i >= 0 and np.isfinite(s)
                    ]
                }))
        return out
