"""Categorical naive Bayes (ref: e2/.../engine/CategoricalNaiveBayes.scala:23).

Behavior contract from the reference:

  - ``train`` counts, per label, the occurrences of each categorical
    value in each feature slot (CategoricalNaiveBayes.scala:29-77):
    log prior = log(labelCount / totalCount), log likelihood =
    log(valueCount / labelCount).
  - ``log_score`` returns ``None`` for an unknown label, else
    prior + sum over slots of the value's log likelihood; a value never
    seen with that (label, slot) falls back to a pluggable
    ``default_likelihood`` function of the other likelihoods in that
    slot (CategoricalNaiveBayes.scala:103-141, default -inf).
  - ``predict`` returns the argmax label (CategoricalNaiveBayes.scala:143).

TPU-first design: the reference scores with nested string-keyed hash
maps per query. Here training bakes the model into dense arrays — a
likelihood table ``L[n_labels, n_slots, vocab+1]`` whose unseen /
unknown entries are pre-filled from ``default_likelihood`` — so scoring
is a pure gather + reduce that XLA fuses, and ``batch_predict`` scores
a whole query batch against all labels in one jitted call instead of a
per-query Python loop.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.data.bimap import BiMap

DefaultLikelihood = Callable[[Sequence[float]], float]


def _neg_inf_default(_likelihoods: Sequence[float]) -> float:
    """Reference default: unseen feature value scores -inf."""
    return float("-inf")


@dataclass(frozen=True)
class LabeledPoint:
    """A label and its categorical feature values (ref: LabeledPoint, :158)."""

    label: str
    features: Tuple[str, ...]

    def __init__(self, label: str, features: Sequence[str]):
        object.__setattr__(self, "label", label)
        object.__setattr__(self, "features", tuple(features))


@partial(jax.jit, static_argnames=())
def _score_batch(
    feature_ids: jax.Array,    # [B, n_slots] int32, vocab index or UNK slot
    priors: jax.Array,         # [n_labels]
    likelihoods: jax.Array,    # [n_labels, n_slots, vocab+1]
) -> jax.Array:                # [B, n_labels]
    # Gather per-slot likelihoods for every label at once:
    # L[l, s, feature_ids[b, s]] -> [B, n_labels, n_slots], then reduce slots.
    gathered = jnp.take_along_axis(
        likelihoods[None, :, :, :],                              # [1, L, S, V]
        feature_ids[:, None, :, None].astype(jnp.int32),         # [B, 1, S, 1]
        axis=3,
    )[..., 0]                                                    # [B, L, S]
    return priors[None, :] + gathered.sum(axis=2)


class CategoricalNaiveBayesModel:
    """Dense NB model; all score paths run on-device.

    ``priors``/``likelihoods`` expose the reference model's map shape
    (label -> log prior, label -> slot -> {value: log likelihood}) for
    parity checks, while the compute path uses the dense tables.
    """

    def __init__(
        self,
        labels: BiMap,                     # label -> 0..L-1
        vocabs: List[BiMap],               # per slot: value -> 0..V_s-1
        priors_arr: np.ndarray,            # [L]
        likelihoods_arr: np.ndarray,       # [L, S, maxV+1]; [..., -1] = default
        seen: np.ndarray,                  # [L, S, maxV+1] bool
    ):
        self.labels = labels
        self.vocabs = vocabs
        self.n_slots = len(vocabs)
        self._priors = jnp.asarray(priors_arr, dtype=jnp.float32)
        self._likelihoods = jnp.asarray(likelihoods_arr, dtype=jnp.float32)
        self._seen = seen
        self._unk = likelihoods_arr.shape[-1] - 1  # sentinel column
        # long-lived device residency -> the memory ledger (JT16):
        # these dense tables serve every query until the model retires
        from predictionio_tpu.obs import memacct

        memacct.LEDGER.register(
            self, "naive_bayes", "params",
            int(self._priors.nbytes + self._likelihoods.nbytes))

    # -- reference-shaped views ----------------------------------------------
    @property
    def priors(self) -> Dict[str, float]:
        arr = np.asarray(self._priors)
        return {lbl: float(arr[i]) for lbl, i in self.labels.items()}

    @property
    def likelihoods(self) -> Dict[str, List[Dict[str, float]]]:
        arr = np.asarray(self._likelihoods)
        out: Dict[str, List[Dict[str, float]]] = {}
        for lbl, li in self.labels.items():
            out[lbl] = [
                {
                    v: float(arr[li, s, vi])
                    for v, vi in self.vocabs[s].items()
                    if self._seen[li, s, vi]
                }
                for s in range(self.n_slots)
            ]
        return out

    # -- encoding -------------------------------------------------------------
    def encode_features(self, batch: Sequence[Sequence[str]]) -> np.ndarray:
        """String features -> [B, n_slots] vocab indices (UNK sentinel)."""
        ids = np.full((len(batch), self.n_slots), self._unk, dtype=np.int32)
        for b, features in enumerate(batch):
            if len(features) != self.n_slots:
                raise ValueError(
                    f"expected {self.n_slots} features, got {len(features)}"
                )
            for s, v in enumerate(features):
                ids[b, s] = self.vocabs[s].get(v, self._unk)
        return ids

    # -- scoring (ref: logScore :103) -----------------------------------------
    def log_score(
        self,
        point: LabeledPoint,
        default_likelihood: Optional[DefaultLikelihood] = None,
    ) -> Optional[float]:
        """Log score of (features, label); None if the label is unknown."""
        if point.label not in self.labels:
            return None
        li = self.labels[point.label]
        if default_likelihood is None:
            score = _score_batch(
                jnp.asarray(self.encode_features([point.features])),
                self._priors,
                self._likelihoods,
            )[0, li]
            return float(score)
        # Custom default fn: recompute the fallback entries host-side
        # (the baked table holds the train-time default).
        arr = np.asarray(self._likelihoods)
        total = float(self._priors[li])
        for s, v in enumerate(point.features):
            vi = self.vocabs[s].get(v)
            if vi is not None and self._seen[li, s, vi]:
                total += float(arr[li, s, vi])
            else:
                others = [
                    float(arr[li, s, oi])
                    for oi in range(arr.shape[-1] - 1)
                    if self._seen[li, s, oi]
                ]
                total += default_likelihood(others)
        return total

    def score_batch(self, batch: Sequence[Sequence[str]]) -> np.ndarray:
        """[B, n_labels] log scores, one jitted gather+reduce."""
        ids = jnp.asarray(self.encode_features(batch))
        return np.asarray(_score_batch(ids, self._priors, self._likelihoods))

    # -- prediction (ref: predict :143) ---------------------------------------
    def predict(self, features: Sequence[str]) -> str:
        return self.predict_batch([features])[0]

    def predict_batch(self, batch: Sequence[Sequence[str]]) -> List[str]:
        scores = self.score_batch(batch)
        inv = self.labels.inverse()
        return [inv[int(i)] for i in np.argmax(scores, axis=1)]


def train(
    points: Sequence[LabeledPoint],
    default_likelihood: DefaultLikelihood = _neg_inf_default,
) -> CategoricalNaiveBayesModel:
    """Count-based training (ref: CategoricalNaiveBayes.train :29).

    ``default_likelihood`` is evaluated per (label, slot) over that
    slot's seen likelihoods and baked into the dense table's unseen and
    unknown-value entries, keeping scoring a pure gather.
    """
    if not points:
        raise ValueError("no training points")
    n_slots = len(points[0].features)
    for p in points:
        if len(p.features) != n_slots:
            raise ValueError("inconsistent feature arity in training points")

    labels = BiMap.string_int(p.label for p in points)
    vocabs = [BiMap.string_int(p.features[s] for p in points) for s in range(n_slots)]
    n_labels = len(labels)
    max_v = max((len(v) for v in vocabs), default=0)

    counts = np.zeros((n_labels, n_slots, max_v + 1), dtype=np.int64)
    label_counts = np.zeros(n_labels, dtype=np.int64)
    li_arr = np.fromiter((labels[p.label] for p in points), dtype=np.int64,
                         count=len(points))
    np.add.at(label_counts, li_arr, 1)
    for s in range(n_slots):
        vi_arr = np.fromiter((vocabs[s][p.features[s]] for p in points),
                             dtype=np.int64, count=len(points))
        np.add.at(counts[:, s, :], (li_arr, vi_arr), 1)

    seen = counts > 0
    with np.errstate(divide="ignore"):
        lik = np.where(
            seen,
            np.log(counts / np.maximum(label_counts[:, None, None], 1)),
            0.0,
        )
    # Bake default_likelihood into unseen + UNK entries per (label, slot).
    for l in range(n_labels):
        for s in range(n_slots):
            seen_vals = lik[l, s, : len(vocabs[s])][seen[l, s, : len(vocabs[s])]]
            d = default_likelihood([float(x) for x in seen_vals])
            lik[l, s, ~seen[l, s]] = d
            lik[l, s, -1] = d

    priors = np.log(label_counts / float(len(points)))
    return CategoricalNaiveBayesModel(labels, vocabs, priors, lik, seen)
