"""Two-tower neural retrieval as a DASE Algorithm.

The deep-model counterpart of models.als: same PD (PreparedRatings),
same model container / query surface (top-``num`` itemScores), so the
recommendation engine can swap `"als"` for `"twotower"` — or run both
and let Serving combine them, the reference's distinctive
multi-algorithm contract (SURVEY.md §7 hard part (d), CreateServer
serving combine :472–475). Compute core: ops.twotower (row-sparse towers +
in-batch softmax under jit on the mesh).

Scores are cosine similarities (towers L2-normalize), so multi-algo
averaging with ALS dot-products needs score-scale awareness — the same
caveat the reference leaves to user Serving code.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import numpy as np

from predictionio_tpu.core import Algorithm
from predictionio_tpu.core.params import Params
from predictionio_tpu.models.als import ALSAlgorithm, ALSModel, PreparedRatings
from predictionio_tpu.ops.als import ALSFactors
from predictionio_tpu.ops.twotower import TwoTowerConfig, TwoTowerTrainer
from predictionio_tpu.parallel.mesh import MeshContext


@dataclass
class TwoTowerParams(Params):
    dim: int = 64
    embed_dim: Optional[int] = None   # id-embedding width (default: dim)
    hidden: Tuple[int, ...] = ()
    temperature: float = 0.07
    learning_rate: float = 3e-3
    weight_decay: float = 1e-6
    epochs: int = 5
    batch_size: int = 1024
    seed: int = 11
    min_rating: float = 0.0       # keep events with rating >= this as positives
    weight_by_rating: bool = False
    shard_embeddings: bool = False
    checkpoint_dir: Optional[str] = None   # mid-training checkpoint/resume
    checkpoint_every: int = 1
    flash_ce_kernel: str = "auto"          # ops/pallas flash-CE kernel:
    embed_update_kernel: str = "off"       # "auto" | "on" | "off" (see
                                           # TwoTowerConfig; env overrides
                                           # PIO_TT_FLASH_CE /
                                           # PIO_TT_EMBED_UPDATE)
    index_backend: str = "auto"            # retrieval index backend
                                           # (PIO_INDEX_BACKEND overrides)
    index_kernel: str = "auto"             # Pallas dot+top-k flag
                                           # (PIO_INDEX_KERNEL overrides)


class TwoTowerModel(ALSModel):
    """Same container as ALSModel: (user_vecs, item_vecs, id maps) +
    TopKScorer serve path + the shared retrieval index; vectors here
    are L2-normalized so scores — including the index's item -> similar
    answers — are cosine similarities."""

    #: device-memory ledger attribution (obs/memacct.py)
    memacct_model = "twotower"


class TwoTowerAlgorithm(Algorithm):
    """DASE wrapper over ops.twotower."""

    def __init__(self, params: TwoTowerParams):
        super().__init__(params)

    def train(self, ctx: MeshContext, pd: PreparedRatings) -> TwoTowerModel:
        p: TwoTowerParams = self.params
        if pd.binned_request is not None:
            # the zero-copy lane's deferred read is ALS-layout-shaped;
            # this trainer consumes host COO — materialize it through
            # the columnar fallback (same rows/codes/value resolution)
            pd = pd.binned_request.read_prepared(pd.fingerprint)
        keep = pd.ratings >= p.min_rating
        u, i, r = pd.user_idx[keep], pd.item_idx[keep], pd.ratings[keep]
        if len(u) == 0:
            raise ValueError(
                f"no events with rating >= {p.min_rating} — nothing to train on"
            )
        cfg = TwoTowerConfig(
            dim=p.dim,
            embed_dim=p.embed_dim,
            hidden=tuple(p.hidden),
            temperature=p.temperature,
            learning_rate=p.learning_rate,
            weight_decay=p.weight_decay,
            epochs=p.epochs,
            batch_size=p.batch_size,
            seed=p.seed,
            shard_embeddings=p.shard_embeddings,
            checkpoint_dir=p.checkpoint_dir,
            checkpoint_every=p.checkpoint_every,
            flash_ce_kernel=p.flash_ce_kernel,
            embed_update_kernel=p.embed_update_kernel,
        )
        trainer = TwoTowerTrainer(
            (u, i, r if p.weight_by_rating else None),
            pd.n_users,
            pd.n_items,
            cfg,
            mesh=ctx.mesh,
        )
        losses = trainer.run()
        emb = trainer.embeddings(losses)
        factors = ALSFactors(user_factors=emb.user_vecs, item_factors=emb.item_vecs)
        model = TwoTowerModel(factors, pd.user_ids, pd.item_ids,
                              index_backend=p.index_backend,
                              index_kernel=p.index_kernel)
        model.train_losses = emb.losses
        return model

    # identical model/query surface -> share ALS's serve and batched
    # (matmul + top-k) evaluation paths, its deploy-time warmup, and
    # the streaming model-patch lane (same factor-table container)
    predict = ALSAlgorithm.predict
    batch_predict = ALSAlgorithm.batch_predict
    warmup = ALSAlgorithm.warmup
    apply_patch = ALSAlgorithm.apply_patch
