"""E-commerce recommendation: explicit ALS + serve-time business-rule filters.

Behavior contract from the reference template
(examples/scala-parallel-ecommercerecommendation/train-with-rate-event/
src/main/scala/ALSAlgorithm.scala):

  - ``train`` (:63-146): index users/items, dedupe (user, item) rate
    events keeping the LATEST rating, explicit ALS, model keeps BOTH
    user and item ("product") factors plus item metadata.
  - ``predict`` (:148-277): build a final blacklist from the query's
    blackList + the user's "seen" events (live event-store lookup when
    ``unseen_only``) + the latest ``$set`` of the special
    ``constraint/unavailableItems`` entity; known users score
    user_vec . item_vec; users unseen at train time fall back to summed
    cosine similarity against their recently viewed items' factors
    (predictNewUser :286-363); apply category/whiteList candidate
    predicates; keep score > 0; top-``num``.

TPU-first design: factors stay device-resident; both the known-user
path (dot products) and the new-user path (sum-of-cosines, which
factorizes to one matvec over normalized factors) are a single masked
matmul + top_k (ops.topk.score_masked); candidate predicates become
vectorized bool masks.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from predictionio_tpu.core import Algorithm, SanityCheck
from predictionio_tpu.core.params import Params
from predictionio_tpu.data import store
from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.storage import StorageError
from predictionio_tpu.ops.als import ALSConfig, als_train
from predictionio_tpu.ops.topk import TopKScorer, cosine_normalize
from predictionio_tpu.parallel.mesh import MeshContext


@dataclass
class ECommTrainingData(SanityCheck):
    users: List[str] = field(default_factory=list)
    items: List[str] = field(default_factory=list)
    item_categories: Dict[str, List[str]] = field(default_factory=dict)
    # (user, item, rating) — events in time order
    rate_events: List[Tuple[str, str, float]] = field(default_factory=list)

    def sanity_check(self) -> None:
        if not self.rate_events:
            raise ValueError("rateEvents cannot be empty")
        if not self.users:
            raise ValueError("users cannot be empty")
        if not self.items:
            raise ValueError("items cannot be empty")


@dataclass
class ECommAlgorithmParams(Params):
    app_name: str = ""
    unseen_only: bool = False
    seen_events: List[str] = field(default_factory=lambda: ["buy", "view"])
    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    seed: int = 3
    block_size: int = 4096
    # serve-time lookup caching (divergence from the reference, which
    # scans the event store inside EVERY request, :148-251 — that scan
    # is a disk read inside the latency budget on file backends). TTL
    # bounds staleness; 0 disables caching (reference behavior).
    lookup_ttl_sec: float = 3.0
    seen_cache_size: int = 10_000


class ECommModel:
    """User + item factors, id maps, item metadata (ref: ALSModel :29)."""

    def __init__(
        self,
        user_factors: np.ndarray,
        item_factors: np.ndarray,
        user_ids: BiMap,
        item_ids: BiMap,
        item_categories: Dict[str, List[str]],
        rated_users: Optional[np.ndarray] = None,
        rated_items: Optional[np.ndarray] = None,
    ):
        self.user_factors = np.asarray(user_factors, dtype=np.float32)
        self.item_factors = np.asarray(item_factors, dtype=np.float32)
        self.user_ids = user_ids
        self.item_ids = item_ids
        self.item_categories = item_categories
        # MLlib's factor maps only cover entities present in the ratings
        # (userFeatures.get -> None drives the new-user path, :225-231;
        # productFeatures feature.isDefined gates candidates, :235).
        # Dense factor matrices cover every indexed id, so track which
        # rows were actually trained.
        self.rated_users = (
            rated_users if rated_users is not None
            else np.ones(len(user_ids), dtype=bool)
        )
        self.rated_items = (
            rated_items if rated_items is not None
            else np.ones(len(item_ids), dtype=bool)
        )
        self._scorer: Optional[TopKScorer] = None
        self._cos_scorer: Optional[TopKScorer] = None
        self._normalized: Optional[np.ndarray] = None

    def __getstate__(self):
        d = dict(self.__dict__)
        d["_scorer"] = d["_cos_scorer"] = d["_normalized"] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)

    def scorer(self) -> TopKScorer:
        if self._scorer is None:
            self._scorer = TopKScorer(self.item_factors)
        return self._scorer

    def cos_scorer(self) -> TopKScorer:
        if self._cos_scorer is None:
            self._normalized = cosine_normalize(self.item_factors)
            self._cos_scorer = TopKScorer(self._normalized)
        return self._cos_scorer

    def candidate_mask(
        self,
        categories: Optional[Set[str]],
        white_list: Optional[Set[str]],
        black_list: Set[str],
    ) -> np.ndarray:
        """Vectorized isCandidateItem + feature.isDefined (ref: :380-398, :235)."""
        n = len(self.item_ids)
        mask = self.rated_items.copy()
        if white_list is not None:
            wl = np.zeros(n, dtype=bool)
            wl[[self.item_ids[i] for i in white_list if i in self.item_ids]] = True
            mask &= wl
        if black_list:
            mask[[self.item_ids[i] for i in black_list if i in self.item_ids]] = False
        if categories:
            cat_mask = np.zeros(n, dtype=bool)
            for item, cats in self.item_categories.items():
                row = self.item_ids.get(item)
                if row is not None and set(cats) & categories:
                    cat_mask[row] = True
            mask &= cat_mask  # items without categories are discarded
        return mask


class ECommAlgorithm(Algorithm):
    """ref: ALSAlgorithm (train-with-rate-event variant)."""

    def __init__(self, params: ECommAlgorithmParams):
        super().__init__(params)
        import collections
        import threading

        # bounded TTL caches for the per-request event-store lookups
        self._cache_lock = threading.Lock()
        self._seen_cache: "collections.OrderedDict[str, Tuple[Set[str], float]]" = (
            collections.OrderedDict()
        )
        self._recent_cache: "collections.OrderedDict[str, Tuple[List[str], float]]" = (
            collections.OrderedDict()
        )
        self._unavail_cache: Optional[Tuple[Set[str], float]] = None

    def _cached(self, cache_get, cache_put, compute):
        import time

        ttl = getattr(self.params, "lookup_ttl_sec", 0.0)
        if ttl <= 0:
            return compute()
        now = time.monotonic()
        with self._cache_lock:
            hit = cache_get()
            if hit is not None and hit[1] > now:
                return hit[0]
        value = compute()
        with self._cache_lock:
            cache_put((value, now + ttl))
        return value

    def train(self, ctx: MeshContext, pd: ECommTrainingData) -> ECommModel:
        p: ECommAlgorithmParams = self.params
        user_ids = BiMap.string_int(pd.users)
        item_ids = BiMap.string_int(pd.items)
        # dedupe (user, item), keeping the latest rating (ref: :96-107)
        latest: Dict[Tuple[int, int], float] = {}
        for user, item, rating in pd.rate_events:
            u, i = user_ids.get(user), item_ids.get(item)
            if u is None or i is None:
                continue  # ref logs and drops nonexistent ids
            latest[(u, i)] = float(rating)
        if not latest:
            raise ValueError(
                "ratings cannot be empty — check that events contain valid "
                "user and item IDs"
            )
        keys = np.array(list(latest.keys()), dtype=np.int64)
        vals = np.array(list(latest.values()), dtype=np.float32)
        cfg = ALSConfig(
            rank=p.rank,
            iterations=p.num_iterations,
            reg=p.lambda_,
            implicit=False,
            block_size=p.block_size,
            seed=p.seed,
        )
        factors = als_train(
            (keys[:, 0], keys[:, 1], vals),
            len(user_ids),
            len(item_ids),
            cfg,
            mesh=ctx.mesh,
        )
        rated_users = np.zeros(len(user_ids), dtype=bool)
        rated_items = np.zeros(len(item_ids), dtype=bool)
        rated_users[keys[:, 0]] = True
        rated_items[keys[:, 1]] = True
        return ECommModel(
            np.asarray(factors.user_factors),
            np.asarray(factors.item_factors),
            user_ids,
            item_ids,
            pd.item_categories,
            rated_users=rated_users,
            rated_items=rated_items,
        )

    # -- serve-time event lookups (ref: lEventsDb.findSingleEntity calls;
    # cached with a bounded TTL here, see ECommAlgorithmParams) ------------
    def _seen_items(self, user: str) -> Set[str]:
        p: ECommAlgorithmParams = self.params
        if not p.unseen_only:
            return set()

        def compute() -> Set[str]:
            try:
                events = store.find_by_entity(
                    p.app_name, "user", user,
                    event_names=list(p.seen_events),
                    target_entity_type="item",
                )
            except StorageError:
                return set()
            return {e.target_entity_id for e in events if e.target_entity_id}

        def put(entry):
            self._seen_cache[user] = entry
            self._seen_cache.move_to_end(user)
            while len(self._seen_cache) > p.seen_cache_size:
                self._seen_cache.popitem(last=False)

        return self._cached(
            lambda: self._seen_cache.get(user), put, compute
        )

    def _unavailable_items(self) -> Set[str]:
        """Latest constraint/unavailableItems $set (ref: :195-215)."""
        p: ECommAlgorithmParams = self.params

        def compute() -> Set[str]:
            try:
                events = store.find_by_entity(
                    p.app_name, "constraint", "unavailableItems",
                    event_names=["$set"], limit=1, latest=True,
                )
            except StorageError:
                return set()
            if not events:
                return set()
            items = events[0].properties.get_opt("items")
            return set(items) if items else set()

        def put(entry):
            self._unavail_cache = entry

        return self._cached(lambda: self._unavail_cache, put, compute)

    def _recent_items(self, user: str) -> List[str]:
        """Latest 10 viewed items (ref: predictNewUser :293-322); TTL
        cached like the other lookups — the new-user path must not keep
        a per-request storage scan either."""
        p: ECommAlgorithmParams = self.params

        def compute() -> List[str]:
            try:
                events = store.find_by_entity(
                    p.app_name, "user", user,
                    event_names=["view"],
                    target_entity_type="item",
                    limit=10, latest=True,
                )
            except StorageError:
                return []
            return [e.target_entity_id for e in events if e.target_entity_id]

        def put(entry):
            self._recent_cache[user] = entry
            self._recent_cache.move_to_end(user)
            while len(self._recent_cache) > p.seen_cache_size:
                self._recent_cache.popitem(last=False)

        return self._cached(
            lambda: self._recent_cache.get(user), put, compute
        )

    def warmup(self, model: ECommModel, ctx: MeshContext) -> None:
        """Pre-compile both masked scorers' default buckets (B=1, k
        buckets 8 and 16) — no storage lookups, no side effects."""
        if len(model.item_ids) == 0 or len(model.user_ids) == 0:
            return
        mask = np.ones(len(model.item_ids), dtype=bool)
        model.cos_scorer()  # builds _normalized
        for k in (5, 10):
            model.scorer().score_masked(model.user_factors[0], k, mask)
            model.cos_scorer().score_masked(model._normalized[0], k, mask)

    def predict(self, model: ECommModel, query: Dict[str, Any]) -> Dict[str, Any]:
        p: ECommAlgorithmParams = self.params
        user = str(query["user"])
        num = int(query.get("num", 10))
        categories = set(query["categories"]) if query.get("categories") else None
        white_list = set(query["whiteList"]) if query.get("whiteList") else None
        black_list = set(query.get("blackList") or ())

        final_black = black_list | self._seen_items(user) | self._unavailable_items()
        mask = model.candidate_mask(categories, white_list, final_black)

        row = model.user_ids.get(user)
        if row is not None and not model.rated_users[row]:
            row = None  # indexed but never rated -> new-user path (ref: :225)
        if row is not None:
            if not mask.any():
                return {"itemScores": []}
            scores, idx = model.scorer().score_masked(
                model.user_factors[row], num, mask
            )
        else:
            # new user: summed cosine vs recently viewed items (ref: :286)
            recent_rows = [
                model.item_ids[i]
                for i in self._recent_items(user)
                if i in model.item_ids
            ]
            if not recent_rows or not mask.any():
                return {"itemScores": []}
            model.cos_scorer()  # ensures _normalized
            qvec = model._normalized[recent_rows].sum(axis=0)
            scores, idx = model.cos_scorer().score_masked(qvec, num, mask)

        inv = model.item_ids.inverse()
        return {
            "itemScores": [
                {"item": inv[int(i)], "score": float(s)}
                for s, i in zip(scores[0], idx[0])
                if s > 0.0  # ref keeps score > 0 only (:252)
            ]
        }

    def batch_predict(self, model, queries):
        return [(i, self.predict(model, q)) for i, q in queries]
