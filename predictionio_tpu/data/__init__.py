"""Event, metadata and storage layer (ref: data/src/main/scala/io/prediction/data/)."""

from predictionio_tpu.data.event import Event, EventValidationError, validate_event
from predictionio_tpu.data.datamap import DataMap, PropertyMap
from predictionio_tpu.data.aggregation import aggregate_properties_from_events
from predictionio_tpu.data.bimap import BiMap, EntityIdIxMap, EntityMap

__all__ = [
    "Event",
    "EventValidationError",
    "validate_event",
    "DataMap",
    "PropertyMap",
    "aggregate_properties_from_events",
    "BiMap",
    "EntityIdIxMap",
    "EntityMap",
]
