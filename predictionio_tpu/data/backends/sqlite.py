"""SQLite storage backend — the durable single-node tier.

Fills the role of the reference's HBase (EVENTDATA) + Elasticsearch
(METADATA) pair for deployments that want real transactional
persistence and multi-process safety without external services:

  - events   -> one indexed ``events`` table; (app_id, channel_id)
                "tables" are rows gated by an ``event_tables`` registry
                so init/remove keep the reference's create/drop-table
                semantics (ref: hbase/HBEventsUtil.scala:51, the
                ``events_<appId>[_<channelId>]`` table naming)
  - metadata -> JSON documents with key columns
                (ref: elasticsearch/ES* DAOs — JSON docs per index)
  - models   -> blobs (ref: localfs/LocalFSModels.scala:29)

Concurrency: WAL journal mode; every connection is per-process, every
mutation is one transaction — unlike the localfs backend's
flock-and-snapshot dance, concurrent CLI + server processes get real
ACID behavior.

Config (ref: env-var contract, conf/pio-env.sh.template:36-56):
  PIO_STORAGE_SOURCES_<N>_TYPE=sqlite
  PIO_STORAGE_SOURCES_<N>_PATH=/path/to/dir-or-file.db
"""

from __future__ import annotations

import datetime as _dt
import json
import os
import sqlite3
import threading
from typing import Any, Dict, List, Optional

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.metadata import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EngineManifest,
    EvaluationInstance,
    Model,
    dict_to_record,
    record_to_dict,
)
from predictionio_tpu.data import storage as S

UTC = _dt.timezone.utc

_SCHEMA = """
CREATE TABLE IF NOT EXISTS event_tables (
    app_id INTEGER NOT NULL,
    channel_id INTEGER NOT NULL,
    PRIMARY KEY (app_id, channel_id)
);
CREATE TABLE IF NOT EXISTS events (
    event_id TEXT NOT NULL,
    app_id INTEGER NOT NULL,
    channel_id INTEGER NOT NULL,
    event TEXT NOT NULL,
    entity_type TEXT NOT NULL,
    entity_id TEXT NOT NULL,
    target_entity_type TEXT,
    target_entity_id TEXT,
    event_time_us INTEGER NOT NULL,
    creation_time_us INTEGER NOT NULL,
    payload TEXT NOT NULL,
    PRIMARY KEY (app_id, channel_id, event_id)
);
CREATE INDEX IF NOT EXISTS idx_events_scan
    ON events (app_id, channel_id, event_time_us);
CREATE INDEX IF NOT EXISTS idx_events_entity
    ON events (app_id, channel_id, entity_type, entity_id, event_time_us);
CREATE TABLE IF NOT EXISTS apps (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    name TEXT NOT NULL UNIQUE,
    payload TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS access_keys (
    key TEXT PRIMARY KEY,
    appid INTEGER NOT NULL,
    payload TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS channels (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    appid INTEGER NOT NULL,
    name TEXT NOT NULL,
    payload TEXT NOT NULL,
    UNIQUE (appid, name)
);
CREATE TABLE IF NOT EXISTS engine_manifests (
    id TEXT NOT NULL,
    version TEXT NOT NULL,
    payload TEXT NOT NULL,
    PRIMARY KEY (id, version)
);
CREATE TABLE IF NOT EXISTS engine_instances (
    id TEXT PRIMARY KEY,
    status TEXT NOT NULL,
    engine_id TEXT NOT NULL,
    engine_version TEXT NOT NULL,
    engine_variant TEXT NOT NULL,
    start_time TEXT NOT NULL,
    payload TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS evaluation_instances (
    id TEXT PRIMARY KEY,
    status TEXT NOT NULL,
    start_time TEXT NOT NULL,
    payload TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS models (
    id TEXT PRIMARY KEY,
    blob BLOB NOT NULL
);
"""

_NO_CHANNEL = -1  # SQL PKs cannot contain NULL; -1 encodes "default channel"


def _us(t: _dt.datetime) -> int:
    if t.tzinfo is None:
        t = t.replace(tzinfo=UTC)
    return int(t.timestamp() * 1_000_000)


def _chan(channel_id: Optional[int]) -> int:
    return _NO_CHANNEL if channel_id is None else int(channel_id)


class _Db:
    """One connection per process, serialized by a lock (sqlite handles
    cross-process locking itself)."""

    def __init__(self, path: str):
        self._lock = threading.RLock()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.row_factory = sqlite3.Row
        with self._lock, self._conn:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.executescript(_SCHEMA)

    def execute(self, sql: str, params=()) -> sqlite3.Cursor:
        with self._lock, self._conn:
            return self._conn.execute(sql, params)

    def transaction(self):
        """Context manager: lock + one BEGIN..COMMIT for multi-statement
        atomicity; yields the connection."""
        import contextlib

        @contextlib.contextmanager
        def _tx():
            with self._lock, self._conn:
                yield self._conn

        return _tx()

    def query(self, sql: str, params=()) -> List[sqlite3.Row]:
        with self._lock:
            return self._conn.execute(sql, params).fetchall()

    def close(self) -> None:
        with self._lock:
            self._conn.close()


class SqliteEventStore(S.EventStore):
    def __init__(self, db: _Db):
        self._db = db

    def _check_table(self, app_id: int, channel_id: Optional[int]) -> None:
        rows = self._db.query(
            "SELECT 1 FROM event_tables WHERE app_id=? AND channel_id=?",
            (int(app_id), _chan(channel_id)),
        )
        if not rows:
            raise S.StorageError(
                f"event table for app {app_id} channel {channel_id} not initialized"
            )

    def init(self, app_id, channel_id=None):
        self._db.execute(
            "INSERT OR IGNORE INTO event_tables (app_id, channel_id) VALUES (?, ?)",
            (int(app_id), _chan(channel_id)),
        )

    def remove(self, app_id, channel_id=None):
        self._db.execute(
            "DELETE FROM events WHERE app_id=? AND channel_id=?",
            (int(app_id), _chan(channel_id)),
        )
        self._db.execute(
            "DELETE FROM event_tables WHERE app_id=? AND channel_id=?",
            (int(app_id), _chan(channel_id)),
        )

    def insert(self, event: Event, app_id, channel_id=None) -> str:
        self._check_table(app_id, channel_id)
        e = event if event.event_id else event.with_id()
        self._db.execute(
            "INSERT OR REPLACE INTO events (event_id, app_id, channel_id, event,"
            " entity_type, entity_id, target_entity_type, target_entity_id,"
            " event_time_us, creation_time_us, payload)"
            " VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?)",
            (
                e.event_id,
                int(app_id),
                _chan(channel_id),
                e.event,
                e.entity_type,
                e.entity_id,
                e.target_entity_type,
                e.target_entity_id,
                _us(e.event_time),
                _us(e.creation_time),
                json.dumps(e.to_dict(api_format=True)),
            ),
        )
        return e.event_id

    def insert_batch(self, events, app_id, channel_id=None):
        """One transaction for the whole batch (ref: PEvents.write:124)."""
        self._check_table(app_id, channel_id)
        stamped = [e if e.event_id else e.with_id() for e in events]
        with self._db.transaction() as conn:
            conn.executemany(
                "INSERT OR REPLACE INTO events (event_id, app_id, channel_id, event,"
                " entity_type, entity_id, target_entity_type, target_entity_id,"
                " event_time_us, creation_time_us, payload)"
                " VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?)",
                [
                    (
                        e.event_id, int(app_id), _chan(channel_id), e.event,
                        e.entity_type, e.entity_id, e.target_entity_type,
                        e.target_entity_id, _us(e.event_time),
                        _us(e.creation_time),
                        json.dumps(e.to_dict(api_format=True)),
                    )
                    for e in stamped
                ],
            )
        if stamped:
            # freshness clock (obs/perfacct.py): like every other bulk
            # storage writer, once per committed batch
            from predictionio_tpu.obs import dataobs, perfacct

            perfacct.note_ingest()
            dataobs.DATAOBS.observe_events(app_id, stamped)
        return [e.event_id for e in stamped]

    def _row_to_event(self, row: sqlite3.Row) -> Event:
        return Event.from_dict(json.loads(row["payload"]))

    def get(self, event_id, app_id, channel_id=None):
        self._check_table(app_id, channel_id)
        rows = self._db.query(
            "SELECT payload FROM events WHERE app_id=? AND channel_id=? AND event_id=?",
            (int(app_id), _chan(channel_id), event_id),
        )
        return self._row_to_event(rows[0]) if rows else None

    def delete(self, event_id, app_id, channel_id=None) -> bool:
        self._check_table(app_id, channel_id)
        cur = self._db.execute(
            "DELETE FROM events WHERE app_id=? AND channel_id=? AND event_id=?",
            (int(app_id), _chan(channel_id), event_id),
        )
        return cur.rowcount > 0

    def find(
        self,
        app_id,
        channel_id=None,
        start_time=None,
        until_time=None,
        entity_type=None,
        entity_id=None,
        event_names=None,
        target_entity_type=S.UNSET,
        target_entity_id=S.UNSET,
        limit=None,
        reversed=False,
    ) -> List[Event]:
        self._check_table(app_id, channel_id)
        sql = "SELECT payload FROM events WHERE app_id=? AND channel_id=?"
        params: List[Any] = [int(app_id), _chan(channel_id)]
        if start_time is not None:  # half-open [start, until)
            sql += " AND event_time_us >= ?"
            params.append(_us(start_time))
        if until_time is not None:
            sql += " AND event_time_us < ?"
            params.append(_us(until_time))
        if entity_type is not None:
            sql += " AND entity_type = ?"
            params.append(entity_type)
        if entity_id is not None:
            sql += " AND entity_id = ?"
            params.append(entity_id)
        if event_names is not None:
            sql += f" AND event IN ({','.join('?' * len(event_names))})"
            params.extend(event_names)
        if target_entity_type is not S.UNSET:
            if target_entity_type is None:
                sql += " AND target_entity_type IS NULL"
            else:
                sql += " AND target_entity_type = ?"
                params.append(target_entity_type)
        if target_entity_id is not S.UNSET:
            if target_entity_id is None:
                sql += " AND target_entity_id IS NULL"
            else:
                sql += " AND target_entity_id = ?"
                params.append(target_entity_id)
        direction = "DESC" if reversed else "ASC"
        sql += f" ORDER BY event_time_us {direction}, creation_time_us {direction}"
        if limit is not None and limit >= 0:
            sql += " LIMIT ?"
            params.append(limit)
        return [self._row_to_event(r) for r in self._db.query(sql, params)]


class SqliteAppsRepo(S.AppsRepo):
    def __init__(self, db: _Db):
        self._db = db

    def insert(self, name, description=None) -> App:
        try:
            with self._db.transaction() as conn:
                cur = conn.execute(
                    "INSERT INTO apps (name, payload) VALUES (?, ?)", (name, "{}")
                )
                app = App(id=cur.lastrowid, name=name, description=description)
                conn.execute(
                    "UPDATE apps SET payload=? WHERE id=?",
                    (json.dumps(record_to_dict(app)), app.id),
                )
        except sqlite3.IntegrityError:
            raise S.StorageError(f"app name {name!r} already exists")
        return app

    def _row(self, row) -> App:
        return dict_to_record(App, json.loads(row["payload"]))

    def get(self, app_id):
        rows = self._db.query("SELECT payload FROM apps WHERE id=?", (int(app_id),))
        return self._row(rows[0]) if rows else None

    def get_by_name(self, name):
        rows = self._db.query("SELECT payload FROM apps WHERE name=?", (name,))
        return self._row(rows[0]) if rows else None

    def get_all(self):
        return [self._row(r) for r in self._db.query("SELECT payload FROM apps ORDER BY id")]

    def update(self, app):
        self._db.execute(
            "UPDATE apps SET name=?, payload=? WHERE id=?",
            (app.name, json.dumps(record_to_dict(app)), app.id),
        )

    def put(self, app):
        # replication upsert with the owner-assigned id (update above is
        # UPDATE-only and would silently no-op on a replica missing the
        # row — S.AppsRepo.put contract)
        self._db.execute(
            "INSERT OR REPLACE INTO apps (id, name, payload) VALUES (?, ?, ?)",
            (int(app.id), app.name, json.dumps(record_to_dict(app))),
        )

    def delete(self, app_id):
        self._db.execute("DELETE FROM apps WHERE id=?", (int(app_id),))


class SqliteAccessKeysRepo(S.AccessKeysRepo):
    def __init__(self, db: _Db):
        self._db = db

    def insert(self, access_key: AccessKey) -> str:
        self._db.execute(
            "INSERT OR REPLACE INTO access_keys (key, appid, payload) VALUES (?, ?, ?)",
            (access_key.key, access_key.appid,
             json.dumps(record_to_dict(access_key))),
        )
        return access_key.key

    def _row(self, row) -> AccessKey:
        return dict_to_record(AccessKey, json.loads(row["payload"]))

    def get(self, key):
        rows = self._db.query("SELECT payload FROM access_keys WHERE key=?", (key,))
        return self._row(rows[0]) if rows else None

    def get_all(self):
        return [self._row(r) for r in self._db.query("SELECT payload FROM access_keys")]

    def get_by_app_id(self, app_id):
        return [
            self._row(r)
            for r in self._db.query(
                "SELECT payload FROM access_keys WHERE appid=?", (int(app_id),)
            )
        ]

    def update(self, access_key):
        self.insert(access_key)

    def delete(self, key):
        self._db.execute("DELETE FROM access_keys WHERE key=?", (key,))


class SqliteChannelsRepo(S.ChannelsRepo):
    def __init__(self, db: _Db):
        self._db = db

    def insert(self, name, app_id) -> Channel:
        if not Channel.is_valid_name(name):
            raise S.StorageError(
                f"invalid channel name {name!r} (must match [a-zA-Z0-9-]{{1,16}})"
            )
        try:
            with self._db.transaction() as conn:
                cur = conn.execute(
                    "INSERT INTO channels (appid, name, payload) VALUES (?, ?, ?)",
                    (int(app_id), name, "{}"),
                )
                ch = Channel(id=cur.lastrowid, name=name, appid=int(app_id))
                conn.execute(
                    "UPDATE channels SET payload=? WHERE id=?",
                    (json.dumps(record_to_dict(ch)), ch.id),
                )
        except sqlite3.IntegrityError:
            raise S.StorageError(f"channel {name!r} already exists for app {app_id}")
        return ch

    def _row(self, row) -> Channel:
        return dict_to_record(Channel, json.loads(row["payload"]))

    def get(self, channel_id):
        rows = self._db.query("SELECT payload FROM channels WHERE id=?", (int(channel_id),))
        return self._row(rows[0]) if rows else None

    def get_by_app_id(self, app_id):
        return [
            self._row(r)
            for r in self._db.query(
                "SELECT payload FROM channels WHERE appid=? ORDER BY id", (int(app_id),)
            )
        ]

    def delete(self, channel_id):
        self._db.execute("DELETE FROM channels WHERE id=?", (int(channel_id),))

    def put(self, channel):
        # replication upsert with the owner-assigned id (S.ChannelsRepo.put)
        self._db.execute(
            "INSERT OR REPLACE INTO channels (id, appid, name, payload)"
            " VALUES (?, ?, ?, ?)",
            (int(channel.id), int(channel.appid), channel.name,
             json.dumps(record_to_dict(channel))),
        )


class SqliteEngineManifestsRepo(S.EngineManifestsRepo):
    def __init__(self, db: _Db):
        self._db = db

    def insert(self, manifest: EngineManifest) -> None:
        self._db.execute(
            "INSERT OR REPLACE INTO engine_manifests (id, version, payload) VALUES (?, ?, ?)",
            (manifest.id, manifest.version, json.dumps(record_to_dict(manifest))),
        )

    def _row(self, row) -> EngineManifest:
        return dict_to_record(EngineManifest, json.loads(row["payload"]))

    def get(self, id, version):
        rows = self._db.query(
            "SELECT payload FROM engine_manifests WHERE id=? AND version=?",
            (id, version),
        )
        return self._row(rows[0]) if rows else None

    def get_all(self):
        return [self._row(r) for r in self._db.query("SELECT payload FROM engine_manifests")]

    def update(self, manifest):
        self.insert(manifest)

    def delete(self, id, version):
        self._db.execute(
            "DELETE FROM engine_manifests WHERE id=? AND version=?", (id, version)
        )


class SqliteEngineInstancesRepo(S.EngineInstancesRepo):
    def __init__(self, db: _Db):
        self._db = db

    def insert(self, instance: EngineInstance) -> str:
        self._db.execute(
            "INSERT OR REPLACE INTO engine_instances"
            " (id, status, engine_id, engine_version, engine_variant, start_time, payload)"
            " VALUES (?, ?, ?, ?, ?, ?, ?)",
            (
                instance.id, instance.status, instance.engine_id,
                instance.engine_version, instance.engine_variant,
                instance.start_time.astimezone(UTC).isoformat(),
                json.dumps(record_to_dict(instance)),
            ),
        )
        return instance.id

    def _row(self, row) -> EngineInstance:
        return dict_to_record(EngineInstance, json.loads(row["payload"]))

    def get(self, id):
        rows = self._db.query("SELECT payload FROM engine_instances WHERE id=?", (id,))
        return self._row(rows[0]) if rows else None

    def get_all(self):
        return [self._row(r) for r in self._db.query("SELECT payload FROM engine_instances")]

    def get_completed(self, engine_id, engine_version, engine_variant):
        rows = self._db.query(
            "SELECT payload FROM engine_instances WHERE status='COMPLETED'"
            " AND engine_id=? AND engine_version=? AND engine_variant=?"
            " ORDER BY start_time DESC",
            (engine_id, engine_version, engine_variant),
        )
        return [self._row(r) for r in rows]

    def get_latest_completed(self, engine_id, engine_version, engine_variant):
        completed = self.get_completed(engine_id, engine_version, engine_variant)
        return completed[0] if completed else None

    def update(self, instance):
        self.insert(instance)

    def delete(self, id):
        self._db.execute("DELETE FROM engine_instances WHERE id=?", (id,))


class SqliteEvaluationInstancesRepo(S.EvaluationInstancesRepo):
    def __init__(self, db: _Db):
        self._db = db

    def insert(self, instance: EvaluationInstance) -> str:
        self._db.execute(
            "INSERT OR REPLACE INTO evaluation_instances (id, status, start_time, payload)"
            " VALUES (?, ?, ?, ?)",
            (
                instance.id, instance.status,
                instance.start_time.astimezone(UTC).isoformat(),
                json.dumps(record_to_dict(instance)),
            ),
        )
        return instance.id

    def _row(self, row) -> EvaluationInstance:
        return dict_to_record(EvaluationInstance, json.loads(row["payload"]))

    def get(self, id):
        rows = self._db.query("SELECT payload FROM evaluation_instances WHERE id=?", (id,))
        return self._row(rows[0]) if rows else None

    def get_all(self):
        return [
            self._row(r) for r in self._db.query("SELECT payload FROM evaluation_instances")
        ]

    def get_completed(self):
        rows = self._db.query(
            "SELECT payload FROM evaluation_instances WHERE status='EVALCOMPLETED'"
            " ORDER BY start_time DESC"
        )
        return [self._row(r) for r in rows]

    def update(self, instance):
        self.insert(instance)

    def delete(self, id):
        self._db.execute("DELETE FROM evaluation_instances WHERE id=?", (id,))


class SqliteModelsRepo(S.ModelsRepo):
    def __init__(self, db: _Db):
        self._db = db

    def insert(self, model: Model) -> None:
        self._db.execute(
            "INSERT OR REPLACE INTO models (id, blob) VALUES (?, ?)",
            (model.id, model.models),
        )

    def get(self, id) -> Optional[Model]:
        rows = self._db.query("SELECT id, blob FROM models WHERE id=?", (id,))
        if not rows:
            return None
        return Model(id=rows[0]["id"], models=rows[0]["blob"])

    def size(self, id) -> Optional[int]:
        # length() in SQL — the blob never crosses into Python (the
        # OOM preflight's cheap question)
        rows = self._db.query(
            "SELECT length(blob) AS n FROM models WHERE id=?", (id,))
        return None if not rows else int(rows[0]["n"])

    def delete(self, id):
        self._db.execute("DELETE FROM models WHERE id=?", (id,))

    def list(self):
        import hashlib

        return [
            {"id": r["id"], "bytes": len(r["blob"]),
             "sha256": hashlib.sha256(r["blob"]).hexdigest()}
            for r in self._db.query("SELECT id, blob FROM models ORDER BY id")
        ]


class SqliteStorageClient(S.StorageClient):
    """ref: the per-backend StorageClient contract (Storage.scala:151-166)."""

    def __init__(self, config: Dict[str, str]):
        path = config.get("PATH", "pio.db")
        if not path.endswith(".db") and (os.path.isdir(path) or "." not in os.path.basename(path)):
            os.makedirs(path, exist_ok=True)
            path = os.path.join(path, "pio.db")
        else:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
        self._db = _Db(path)
        self._events = SqliteEventStore(self._db)
        self._apps = SqliteAppsRepo(self._db)
        self._access_keys = SqliteAccessKeysRepo(self._db)
        self._channels = SqliteChannelsRepo(self._db)
        self._manifests = SqliteEngineManifestsRepo(self._db)
        self._engine_instances = SqliteEngineInstancesRepo(self._db)
        self._evaluation_instances = SqliteEvaluationInstancesRepo(self._db)
        self._models = SqliteModelsRepo(self._db)

    def events(self) -> S.EventStore:
        return self._events

    def apps(self) -> S.AppsRepo:
        return self._apps

    def access_keys(self) -> S.AccessKeysRepo:
        return self._access_keys

    def channels(self) -> S.ChannelsRepo:
        return self._channels

    def engine_manifests(self) -> S.EngineManifestsRepo:
        return self._manifests

    def engine_instances(self) -> S.EngineInstancesRepo:
        return self._engine_instances

    def evaluation_instances(self) -> S.EvaluationInstancesRepo:
        return self._evaluation_instances

    def models(self) -> S.ModelsRepo:
        return self._models

    def health_check(self) -> bool:
        """A real round-trip, not the base class's constant True: a
        closed/corrupted database file must turn /readyz and `pio
        status` red, and only a live query notices."""
        self._db.query("SELECT 1")
        return True

    def close(self) -> None:
        self._db.close()


S.register_backend("sqlite", SqliteStorageClient)
