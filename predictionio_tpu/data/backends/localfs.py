"""Local-filesystem storage backend — the single-host default.

Maps the reference's three default backends onto one directory tree:

  - events   -> append-only JSONL logs ``events/events_<app>[_<ch>].jsonl``
                (ref: hbase tables ``events_<appId>[_<channelId>]``,
                 hbase/HBEventsUtil.scala:51)
  - metadata -> one JSON document ``metadata.json``
                (ref: elasticsearch indices, data/.../storage/elasticsearch/)
  - models   -> blob files ``models/pio_<id>``
                (ref: localfs/LocalFSModels.scala:29)

Writes go through the in-memory DAOs and are persisted with
atomic-rename JSON snapshots (metadata) or appends (events), so a
process restart replays to the same state.

Multi-process coordination (CLI + servers sharing one basedir): every
metadata mutation re-syncs from disk under an exclusive flock before
applying, and read accessors reload when the file mtime changes. A
mutation lost to the residual window between reload and save would
require two processes mutating metadata in the same few microseconds —
acceptable for the single-host tier this backend targets (scale-out
backends own that problem properly).
"""

from __future__ import annotations

import contextlib
import fcntl
import json
import logging
import os
import threading
from typing import Dict, Optional, Tuple

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.metadata import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EngineManifest,
    EvaluationInstance,
    Model,
    dict_to_record,
    record_to_dict,
)
from predictionio_tpu.data import storage as S
from predictionio_tpu.data.backends import memory as M

log = logging.getLogger(__name__)


def _atomic_write(path: str, data: str) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:  # graftlint: disable=JT21 — the store lock exists to serialize this very file: concurrent writers would race the tmp+replace pair; localfs is the single-process dev backend, not a serving hot path
        f.write(data)
    os.replace(tmp, path)


class LocalFSEventStore(M.MemoryEventStore):
    """JSONL event log with an in-memory replay cache."""

    def __init__(self, basedir: str):
        super().__init__()
        self._dir = os.path.join(basedir, "events")
        os.makedirs(self._dir, exist_ok=True)
        self._loaded: set = set()

    def _path(self, app_id: int, channel_id: Optional[int]) -> str:
        name = f"events_{int(app_id)}"
        if channel_id is not None:
            name += f"_{int(channel_id)}"
        return os.path.join(self._dir, name + ".jsonl")

    def _ensure_loaded(self, app_id: int, channel_id: Optional[int]) -> None:
        key = M._table_key(app_id, channel_id)
        if key in self._loaded:
            return
        path = self._path(app_id, channel_id)
        if not os.path.exists(path):
            return
        tbl: Dict[str, Event] = {}
        with open(path) as f:  # graftlint: disable=JT21 — replay must be atomic with the table publish it guards: a writer appending mid-replay would be lost; one cold read per table lifetime
            lines = f.readlines()
        for lineno, line in enumerate(lines):
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                # a torn final line (crash mid-append) is recoverable;
                # corruption earlier in the log is not
                if lineno == len(lines) - 1:
                    log.warning("%s: dropping torn final line", path)
                    continue
                raise S.StorageError(f"{path}:{lineno + 1}: corrupt event log line")
            if "__tombstone__" in d:
                tbl.pop(d["__tombstone__"], None)
            else:
                e = Event.from_dict(d)
                tbl[e.event_id] = e
        # publish only after a full successful replay
        self._tables[key] = tbl
        self._loaded.add(key)

    def _append(self, app_id, channel_id, record: dict) -> None:
        with open(self._path(app_id, channel_id), "a") as f:  # graftlint: disable=JT21 — the event-store lock exists to serialize this log: the JSONL append must land in the same order as the in-memory table update it rides with
            f.write(json.dumps(record, sort_keys=True) + "\n")

    # -- overrides ----------------------------------------------------------
    def init(self, app_id, channel_id=None):
        with self._lock:
            self._ensure_loaded(app_id, channel_id)
            super().init(app_id, channel_id)
            self._loaded.add(M._table_key(app_id, channel_id))
            path = self._path(app_id, channel_id)
            if not os.path.exists(path):
                open(path, "a").close()  # graftlint: disable=JT21 — exists-check and create must be one transaction under the store lock; a one-time touch on the init path

    def remove(self, app_id, channel_id=None):
        with self._lock:
            super().remove(app_id, channel_id)
            self._loaded.discard(M._table_key(app_id, channel_id))
            try:
                os.remove(self._path(app_id, channel_id))
            except FileNotFoundError:
                pass

    def insert(self, event, app_id, channel_id=None) -> str:
        with self._lock:
            self._ensure_loaded(app_id, channel_id)
            event_id = super().insert(event, app_id, channel_id)
            stored = super().get(event_id, app_id, channel_id)
            self._append(app_id, channel_id, stored.to_dict(api_format=False))
            return event_id

    def get(self, event_id, app_id, channel_id=None):
        with self._lock:
            self._ensure_loaded(app_id, channel_id)
            return super().get(event_id, app_id, channel_id)

    def delete(self, event_id, app_id, channel_id=None) -> bool:
        with self._lock:
            self._ensure_loaded(app_id, channel_id)
            found = super().delete(event_id, app_id, channel_id)
            if found:
                self._append(app_id, channel_id, {"__tombstone__": event_id})
            return found

    def find(self, app_id, channel_id=None, **kwargs):
        with self._lock:
            self._ensure_loaded(app_id, channel_id)
        return super().find(app_id, channel_id=channel_id, **kwargs)


class LocalFSModelsRepo(S.ModelsRepo):
    """ref: localfs/LocalFSModels.scala:29 — blob per model id."""

    def __init__(self, basedir: str):
        self._dir = os.path.join(basedir, "models")
        os.makedirs(self._dir, exist_ok=True)

    def _path(self, id: str) -> str:
        return os.path.join(self._dir, f"pio_{id}")

    def insert(self, model: Model) -> None:
        with open(self._path(model.id), "wb") as f:
            f.write(model.models)

    def get(self, id: str) -> Optional[Model]:
        try:
            with open(self._path(id), "rb") as f:
                return Model(id=id, models=f.read())
        except FileNotFoundError:
            return None

    def size(self, id: str) -> Optional[int]:
        # one stat, no blob read (the OOM preflight's cheap question)
        try:
            return os.path.getsize(self._path(id))
        except OSError:
            return None

    def delete(self, id: str) -> None:
        try:
            os.remove(self._path(id))
        except FileNotFoundError:
            pass

    def list(self):
        import hashlib

        out = []
        for name in sorted(os.listdir(self._dir)):
            if not name.startswith("pio_"):
                continue
            try:
                with open(os.path.join(self._dir, name), "rb") as f:
                    blob = f.read()
            except FileNotFoundError:
                continue  # concurrently deleted between listdir and open
            out.append({"id": name[len("pio_"):], "bytes": len(blob),
                        "sha256": hashlib.sha256(blob).hexdigest()})
        return out


_META_RECORDS = {
    "apps": (App, lambda r: r.id),
    "access_keys": (AccessKey, lambda r: r.key),
    "channels": (Channel, lambda r: r.id),
    "engine_manifests": (EngineManifest, lambda r: (r.id, r.version)),
    "engine_instances": (EngineInstance, lambda r: r.id),
    "evaluation_instances": (EvaluationInstance, lambda r: r.id),
}


class LocalFSStorageClient(S.StorageClient):
    """Directory-rooted storage source; ``PATH`` config key sets the root."""

    def __init__(self, config: Dict[str, str]):
        super().__init__(config)
        basedir = os.path.expanduser(config.get("PATH") or "~/.pio_store")
        os.makedirs(basedir, exist_ok=True)
        self._basedir = basedir
        self._meta_path = os.path.join(basedir, "metadata.json")
        self._lock_path = os.path.join(basedir, ".metadata.lock")
        self._meta_mtime: Optional[int] = None
        self._lock = threading.RLock()
        self._sequences = M._Sequences()
        save, sync = self._save_metadata, self._sync_from_disk
        self._events = LocalFSEventStore(basedir)
        self._apps = M.MemoryAppsRepo(self._sequences, self._lock, save, sync)
        self._access_keys = M.MemoryAccessKeysRepo(self._lock, save, sync)
        self._channels = M.MemoryChannelsRepo(self._sequences, self._lock, save, sync)
        self._engine_manifests = M.MemoryEngineManifestsRepo(self._lock, save, sync)
        self._engine_instances = M.MemoryEngineInstancesRepo(self._lock, save, sync)
        self._evaluation_instances = M.MemoryEvaluationInstancesRepo(self._lock, save, sync)
        self._models = LocalFSModelsRepo(basedir)
        self._loading = False
        with self._flocked():
            self._load_metadata()

    # -- persistence --------------------------------------------------------
    @contextlib.contextmanager
    def _flocked(self):
        """Cross-process exclusive lock for metadata load/save."""
        with open(self._lock_path, "a+") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(lockf, fcntl.LOCK_UN)

    def _repos(self):
        return {
            "apps": self._apps,
            "access_keys": self._access_keys,
            "channels": self._channels,
            "engine_manifests": self._engine_manifests,
            "engine_instances": self._engine_instances,
            "evaluation_instances": self._evaluation_instances,
        }

    def _stat_mtime(self) -> Optional[int]:
        try:
            return os.stat(self._meta_path).st_mtime_ns
        except FileNotFoundError:
            return None

    def _save_metadata(self) -> None:
        if self._loading:
            return
        with self._lock, self._flocked():
            doc = {"sequences": self._sequences.state()}
            for name in _META_RECORDS:
                repo = self._repos()[name]
                doc[name] = [record_to_dict(r) for r in repo._records.values()]
            _atomic_write(self._meta_path, json.dumps(doc, indent=1, sort_keys=True))
            self._meta_mtime = self._stat_mtime()

    def _sync_from_disk(self) -> None:
        """pre_change hook: pick up other processes' writes before mutating."""
        if self._loading:
            return
        if self._stat_mtime() == self._meta_mtime:
            return
        with self._lock, self._flocked():
            self._load_metadata()

    def _load_metadata(self) -> None:
        mtime = self._stat_mtime()
        if mtime is None:
            return
        with open(self._meta_path) as f:
            doc = json.load(f)
        self._loading = True
        try:
            with self._lock:
                self._sequences.merge_max(doc.get("sequences", {}))
                for name, (cls, key) in _META_RECORDS.items():
                    repo = self._repos()[name]
                    repo._records.clear()
                    for rd in doc.get(name, []):
                        rec = dict_to_record(cls, rd)
                        repo._records[key(rec)] = rec
                self._meta_mtime = mtime
        finally:
            self._loading = False

    # -- accessors ----------------------------------------------------------
    def events(self): return self._events

    def apps(self):
        self._sync_from_disk()
        return self._apps

    def access_keys(self):
        self._sync_from_disk()
        return self._access_keys

    def channels(self):
        self._sync_from_disk()
        return self._channels

    def engine_manifests(self):
        self._sync_from_disk()
        return self._engine_manifests

    def engine_instances(self):
        self._sync_from_disk()
        return self._engine_instances

    def evaluation_instances(self):
        self._sync_from_disk()
        return self._evaluation_instances

    def models(self): return self._models


S.register_backend("localfs", LocalFSStorageClient)
