"""Local-filesystem storage backend — the single-host default.

Maps the reference's three default backends onto one directory tree:

  - events   -> append-only JSONL logs ``events/events_<app>[_<ch>].jsonl``
                (ref: hbase tables ``events_<appId>[_<channelId>]``,
                 hbase/HBEventsUtil.scala:51)
  - metadata -> one JSON document ``metadata.json``
                (ref: elasticsearch indices, data/.../storage/elasticsearch/)
  - models   -> blob files ``models/pio_<id>``
                (ref: localfs/LocalFSModels.scala:29)

Writes go through the in-memory DAOs and are persisted with
atomic-rename JSON snapshots (metadata) or appends (events), so a
process restart replays to the same state.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional, Tuple

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.metadata import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EngineManifest,
    EvaluationInstance,
    Model,
    dict_to_record,
    record_to_dict,
)
from predictionio_tpu.data import storage as S
from predictionio_tpu.data.backends import memory as M


def _atomic_write(path: str, data: str) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(data)
    os.replace(tmp, path)


class LocalFSEventStore(M.MemoryEventStore):
    """JSONL event log with an in-memory replay cache."""

    def __init__(self, basedir: str):
        super().__init__()
        self._dir = os.path.join(basedir, "events")
        os.makedirs(self._dir, exist_ok=True)
        self._loaded: set = set()

    def _path(self, app_id: int, channel_id: Optional[int]) -> str:
        name = f"events_{int(app_id)}"
        if channel_id is not None:
            name += f"_{int(channel_id)}"
        return os.path.join(self._dir, name + ".jsonl")

    def _ensure_loaded(self, app_id: int, channel_id: Optional[int]) -> None:
        key = (int(app_id), channel_id if channel_id is None else int(channel_id))
        if key in self._loaded:
            return
        self._loaded.add(key)
        path = self._path(app_id, channel_id)
        if not os.path.exists(path):
            return
        tbl = super()._table(app_id, channel_id, create=True)
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                d = json.loads(line)
                if "__tombstone__" in d:
                    tbl.pop(d["__tombstone__"], None)
                else:
                    e = Event.from_dict(d)
                    tbl[e.event_id] = e

    def _append(self, app_id, channel_id, record: dict) -> None:
        with open(self._path(app_id, channel_id), "a") as f:
            f.write(json.dumps(record, sort_keys=True) + "\n")

    # -- overrides ----------------------------------------------------------
    def init(self, app_id, channel_id=None):
        with self._lock:
            self._ensure_loaded(app_id, channel_id)
            super().init(app_id, channel_id)
            path = self._path(app_id, channel_id)
            if not os.path.exists(path):
                open(path, "a").close()

    def remove(self, app_id, channel_id=None):
        with self._lock:
            super().remove(app_id, channel_id)
            self._loaded.discard(
                (int(app_id), channel_id if channel_id is None else int(channel_id))
            )
            try:
                os.remove(self._path(app_id, channel_id))
            except FileNotFoundError:
                pass

    def insert(self, event, app_id, channel_id=None) -> str:
        with self._lock:
            self._ensure_loaded(app_id, channel_id)
            event_id = super().insert(event, app_id, channel_id)
            stored = super().get(event_id, app_id, channel_id)
            self._append(app_id, channel_id, stored.to_dict(api_format=False))
            return event_id

    def get(self, event_id, app_id, channel_id=None):
        with self._lock:
            self._ensure_loaded(app_id, channel_id)
            return super().get(event_id, app_id, channel_id)

    def delete(self, event_id, app_id, channel_id=None) -> bool:
        with self._lock:
            self._ensure_loaded(app_id, channel_id)
            found = super().delete(event_id, app_id, channel_id)
            if found:
                self._append(app_id, channel_id, {"__tombstone__": event_id})
            return found

    def find(self, app_id, channel_id=None, **kwargs):
        with self._lock:
            self._ensure_loaded(app_id, channel_id)
        return super().find(app_id, channel_id=channel_id, **kwargs)


class LocalFSModelsRepo(S.ModelsRepo):
    """ref: localfs/LocalFSModels.scala:29 — blob per model id."""

    def __init__(self, basedir: str):
        self._dir = os.path.join(basedir, "models")
        os.makedirs(self._dir, exist_ok=True)

    def _path(self, id: str) -> str:
        return os.path.join(self._dir, f"pio_{id}")

    def insert(self, model: Model) -> None:
        with open(self._path(model.id), "wb") as f:
            f.write(model.models)

    def get(self, id: str) -> Optional[Model]:
        try:
            with open(self._path(id), "rb") as f:
                return Model(id=id, models=f.read())
        except FileNotFoundError:
            return None

    def delete(self, id: str) -> None:
        try:
            os.remove(self._path(id))
        except FileNotFoundError:
            pass


_META_RECORDS = {
    "apps": (App, "_apps", lambda r: r.id),
    "access_keys": (AccessKey, "_keys", lambda r: r.key),
    "channels": (Channel, "_channels", lambda r: r.id),
    "engine_manifests": (EngineManifest, "_manifests", lambda r: (r.id, r.version)),
    "engine_instances": (EngineInstance, "_instances", lambda r: r.id),
    "evaluation_instances": (EvaluationInstance, "_instances", lambda r: r.id),
}


class LocalFSStorageClient(S.StorageClient):
    """Directory-rooted storage source; ``PATH`` config key sets the root."""

    def __init__(self, config: Dict[str, str]):
        super().__init__(config)
        basedir = os.path.expanduser(config.get("PATH") or "~/.pio_store")
        os.makedirs(basedir, exist_ok=True)
        self._basedir = basedir
        self._meta_path = os.path.join(basedir, "metadata.json")
        self._lock = threading.RLock()
        self._sequences = M._Sequences()
        save = self._save_metadata
        self._events = LocalFSEventStore(basedir)
        self._apps = M.MemoryAppsRepo(self._sequences, self._lock, save)
        self._access_keys = M.MemoryAccessKeysRepo(self._lock, save)
        self._channels = M.MemoryChannelsRepo(self._sequences, self._lock, save)
        self._engine_manifests = M.MemoryEngineManifestsRepo(self._lock, save)
        self._engine_instances = M.MemoryEngineInstancesRepo(self._lock, save)
        self._evaluation_instances = M.MemoryEvaluationInstancesRepo(self._lock, save)
        self._models = LocalFSModelsRepo(basedir)
        self._loading = False
        self._load_metadata()

    # -- persistence --------------------------------------------------------
    def _repos(self):
        return {
            "apps": self._apps,
            "access_keys": self._access_keys,
            "channels": self._channels,
            "engine_manifests": self._engine_manifests,
            "engine_instances": self._engine_instances,
            "evaluation_instances": self._evaluation_instances,
        }

    def _save_metadata(self) -> None:
        if self._loading:
            return
        with self._lock:
            doc = {"sequences": self._sequences.state()}
            for name, (cls, attr, _key) in _META_RECORDS.items():
                repo = self._repos()[name]
                records = list(getattr(repo, attr).values())
                doc[name] = [record_to_dict(r) for r in records]
            _atomic_write(self._meta_path, json.dumps(doc, indent=1, sort_keys=True))

    def _load_metadata(self) -> None:
        if not os.path.exists(self._meta_path):
            return
        with open(self._meta_path) as f:
            doc = json.load(f)
        self._loading = True
        try:
            with self._lock:
                self._sequences.restore(doc.get("sequences", {}))
                for name, (cls, attr, key) in _META_RECORDS.items():
                    repo = self._repos()[name]
                    store = getattr(repo, attr)
                    store.clear()
                    for rd in doc.get(name, []):
                        rec = dict_to_record(cls, rd)
                        store[key(rec)] = rec
        finally:
            self._loading = False

    # -- accessors ----------------------------------------------------------
    def events(self): return self._events
    def apps(self): return self._apps
    def access_keys(self): return self._access_keys
    def channels(self): return self._channels
    def engine_manifests(self): return self._engine_manifests
    def engine_instances(self): return self._engine_instances
    def evaluation_instances(self): return self._evaluation_instances
    def models(self): return self._models


S.register_backend("localfs", LocalFSStorageClient)
