"""Pluggable storage backends (ref: data/.../storage/{hbase,elasticsearch,localfs,hdfs}/).

The reference ships HBase (events), Elasticsearch (metadata) and
localfs/HDFS (model blobs). The TPU build ships:

  - ``memory``  — in-process, for tests and embedded use (the reference
                  has no such backend; its tests require live HBase)
  - ``localfs`` — JSONL event logs + JSON metadata + model-blob files,
                  the single-host default
  - ``sqlite``  — one WAL-mode SQLite database: indexed event scans,
                  ACID metadata, model blobs; the durable multi-process
                  single-node tier

Scale-out backends can be registered by third parties via
``predictionio_tpu.data.storage.register_backend``.
"""
