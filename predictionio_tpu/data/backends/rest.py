"""``rest`` storage backend: proxy DAOs talking to a Storage Server.

The reference reaches its scale-out tiers through network clients —
HBase RPC for events, the Elasticsearch transport client for metadata
(elasticsearch/StorageClient.scala:42), HDFS for model blobs
(hdfs/HDFSModels.scala:28). This backend is that client side for the
TPU build's own storage service (serving/storage_server.py): every DAO
call becomes an HTTP request, so any number of trainer/serving hosts
share one logical METADATA / EVENTDATA / MODELDATA over DCN.

Source config (reference env grammar, conf/pio-env.sh.template):

    PIO_STORAGE_SOURCES_CENTRAL_TYPE=rest
    PIO_STORAGE_SOURCES_CENTRAL_HOSTS=10.0.0.5
    PIO_STORAGE_SOURCES_CENTRAL_PORTS=7077
    PIO_STORAGE_SOURCES_CENTRAL_AUTH_KEY=...   # optional shared secret
    PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE=CENTRAL   # etc.
"""

from __future__ import annotations

import json
import logging
import time
import urllib.error
import urllib.request
from http.client import IncompleteRead
from typing import Any, Dict, List, Optional

from predictionio_tpu.data.event import Event
from predictionio_tpu.data import metadata as MD
from predictionio_tpu.resilience.policy import (
    CircuitOpenError,
    Policy,
    breaker_for,
)
from predictionio_tpu.data.metadata import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EngineManifest,
    EvaluationInstance,
    Model,
)
from predictionio_tpu.data import storage as S
from predictionio_tpu.obs import trace

log = logging.getLogger(__name__)


class StorageCircuitOpenError(S.StorageUnavailableError):
    """Unavailable because the endpoint's circuit is OPEN: retrying the
    SAME endpoint is guaranteed to fail fast again until the half-open
    window, so same-endpoint retry loops must give up immediately —
    that is the breaker's whole fail-fast contract. Replica failover
    (a DIFFERENT endpoint) still proceeds: this subclasses
    StorageUnavailableError, so `_first_live` advances past a
    circuit-broken replica like any other dead one."""


def _span_name(path: str) -> str:
    """Bounded span/metric name for a storage-server route:
    /storage/events/find -> storage.find, /storage/meta/apps/get ->
    storage.meta.apps.get, /storage/models/<id> -> storage.models."""
    parts = path.split("?", 1)[0].strip("/").split("/")
    if len(parts) >= 3 and parts[1] == "events":
        name = parts[2] if not parts[2].startswith("scan") else "scan"
        return f"storage.{name}"
    if len(parts) >= 4 and parts[1] == "meta":
        return f"storage.meta.{parts[2]}.{parts[3]}"
    if len(parts) >= 2 and parts[1] == "models":
        return "storage.models"
    return "storage.request"


class _Transport:
    """One storage-server endpoint + auth; shared by all proxy DAOs.

    Resilience (the role HBase's client plays with its connection pool
    and bounded retries, hbase/StorageClient.scala), now carried by the
    framework-wide resilience :class:`Policy`: connection-level
    failures — refused, reset, timed out — are classified as
    StorageUnavailableError and, for IDEMPOTENT operations, retried
    with capped exponential backoff + FULL jitter. Non-idempotent
    writes (event/metadata inserts) never auto-retry: their first
    attempt's outcome is unknown, and a blind replay could
    double-write. Every request also runs through this endpoint's
    circuit breaker: after enough consecutive connection failures the
    circuit opens and calls fail FAST (StorageUnavailableError without
    a connect attempt) until a half-open probe succeeds — a dead
    storage server costs microseconds, not timeout x retries, which is
    what lets the engine server flip to degraded mode instead of
    stalling."""

    def __init__(self, base_url: str, auth_key: Optional[str], timeout: float,
                 retries: int = 3, backoff: float = 0.2):
        self.base_url = base_url.rstrip("/")
        self.auth_key = auth_key
        self.timeout = timeout
        self.retries = max(0, int(retries))
        self.backoff = backoff
        self.policy = Policy(deadline=timeout, retries=self.retries,
                             backoff_base=backoff, backoff_cap=10.0)
        self.breaker = breaker_for(self.base_url)

    def _request_obj(self, path, body, method, content_type) -> urllib.request.Request:
        req = urllib.request.Request(
            self.base_url + path, data=body, method=method,
            headers={"Content-Type": content_type},
        )
        if self.auth_key:
            req.add_header("X-PIO-Storage-Key", self.auth_key)
        # propagate the serving request's trace id (and the active
        # span as X-PIO-Parent-Span) so the storage server's span
        # records join the same chain — and the federation collector
        # (obs/collect.py) can parent its edge span under this
        # client's storage.* span in the stitched cross-process tree
        for name, value in trace.traced_headers().items():
            req.add_header(name, value)
        return req

    def _error(self, path: str, e: urllib.error.HTTPError) -> S.StorageError:
        payload = e.read()
        error_type = None
        row_error = False
        try:
            body = json.loads(payload)
            message = body.get("message", payload.decode())
            error_type = body.get("type")
            row_error = bool(body.get("row_error", False))
        except Exception:  # noqa: BLE001 — raw body is the best we have
            message = payload.decode(errors="replace")
        err = S.StorageError(
            f"storage server {self.base_url}{path}: HTTP {e.code}: {message}"
        )
        # structured discriminators (the server's "type" / "row_error"
        # fields) so callers can re-map client errors without grepping
        # messages; server_message carries the unwrapped text for
        # re-raises that want local/remote message parity
        err.error_type = error_type
        err.row_error = row_error
        err.server_message = message
        return err

    def _sleep_backoff(self, attempt: int) -> None:
        # the outer scan/fetch retry loops share the policy's jittered
        # schedule (full jitter: spreads a retry storm instead of
        # synchronizing it)
        time.sleep(self.policy.backoff_seconds(attempt))

    def _circuit_open_error(self, e: CircuitOpenError) -> S.StorageError:
        return StorageCircuitOpenError(
            f"storage server {self.base_url} unreachable (circuit open, "
            f"next probe in {e.retry_after:.1f}s)")

    def request(
        self,
        path: str,
        body: Optional[bytes] = None,
        method: str = "POST",
        content_type: str = "application/json",
        timeout: Optional[float] = None,
        idempotent: bool = False,
    ):
        """(status, body bytes). A 404 is returned (not raised) ONLY when
        the server marks it as a data miss (``{"missing": true}``); a
        bare 404 means route/version skew and raises StorageError, so it
        can never masquerade as empty data. Connection-level failures
        raise StorageUnavailableError — after the policy's bounded
        retries when ``idempotent``, immediately (fail-fast, no connect)
        while the endpoint's circuit is open."""
        with trace.span(_span_name(path), endpoint=self.base_url):
            try:
                return self.policy.run(
                    lambda: self._one_attempt(path, body, method,
                                              content_type, timeout),
                    target=self.base_url,  # per-endpoint retry metrics
                    idempotent=idempotent,
                    retry_on=(S.StorageUnavailableError,),
                    breaker=self.breaker,
                )
            except CircuitOpenError as e:
                raise self._circuit_open_error(e) from None

    def _one_attempt(self, path, body, method, content_type, timeout):
        req = self._request_obj(path, body, method, content_type)
        try:
            with urllib.request.urlopen(
                req, timeout=timeout if timeout is not None else self.timeout
            ) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            # an HTTP answer means the server is UP: these are
            # application errors — never retried, invisible to the
            # breaker's consecutive-failure count
            if e.code == 404:
                payload = e.read()
                try:
                    missing = json.loads(payload).get("missing", False)
                except Exception:  # noqa: BLE001
                    missing = False
                if missing:
                    return 404, payload
                raise S.StorageError(
                    f"storage server {self.base_url}{path}: unknown route "
                    "(server/client version skew?)"
                ) from None
            raise self._error(path, e) from None
        except (urllib.error.URLError, ConnectionError, TimeoutError) as e:
            reason = getattr(e, "reason", e)
            raise S.StorageUnavailableError(
                f"storage server {self.base_url} unreachable: {reason}"
            ) from None

    def json_call(self, path: str, payload: Dict[str, Any],
                  idempotent: bool = False) -> Any:
        status, body = self.request(path, json.dumps(payload).encode(),
                                    idempotent=idempotent)
        if status == 404:
            return None
        return json.loads(body)

    def stream_lines(self, path: str, payload: Dict[str, Any]):
        """Yield non-empty response lines without buffering the body
        (the server chunk-streams finds; urllib decodes transparently).
        Connection failures — at connect or mid-stream — raise
        StorageUnavailableError so read callers can retry the scan.
        Streaming cannot run inside ``Policy.run`` (the generator
        outlives the call), so the breaker is applied by hand: fail
        fast while open, one failure/success record per stream."""
        if not self.breaker.allow():
            raise self._circuit_open_error(
                CircuitOpenError(self.base_url, self.breaker.retry_after()))
        req = self._request_obj(
            path, json.dumps(payload).encode(), "POST", "application/json"
        )
        try:
            resp = urllib.request.urlopen(req, timeout=self.timeout)
        except urllib.error.HTTPError as e:
            self.breaker.record_success()  # an HTTP answer: reachable
            raise self._error(path, e) from None
        except (urllib.error.URLError, ConnectionError, TimeoutError) as e:
            self.breaker.record_failure()
            raise S.StorageUnavailableError(
                f"storage server {self.base_url} unreachable: "
                f"{getattr(e, 'reason', e)}"
            ) from None
        try:
            with resp:
                for line in resp:
                    line = line.strip()
                    if line:
                        yield line
        except (urllib.error.URLError, ConnectionError, TimeoutError,
                IncompleteRead) as e:
            self.breaker.record_failure()
            raise S.StorageUnavailableError(
                f"storage server {self.base_url}: connection lost "
                f"mid-stream: {getattr(e, 'reason', e)}"
            ) from None
        self.breaker.record_success()


class RestEventStore(S.EventStore):
    def __init__(self, transport: _Transport):
        self._t = transport

    def _call(self, method: str, app_id, channel_id, idempotent=False,
              **extra) -> Any:
        payload = {"app_id": int(app_id), "channel_id": channel_id}
        payload.update(extra)
        return self._t.json_call(f"/storage/events/{method}", payload,
                                 idempotent=idempotent)

    def init(self, app_id, channel_id=None):
        self._call("init", app_id, channel_id, idempotent=True)

    def remove(self, app_id, channel_id=None):
        self._call("remove", app_id, channel_id, idempotent=True)

    def compact(self, app_id, channel_id=None):
        # runs ON the storage server, against its local backend; None
        # when that backend stores events in place
        return self._call("compact", app_id, channel_id,
                          idempotent=True)["stats"]

    def insert(self, event: Event, app_id, channel_id=None) -> str:
        # NOT retried: a lost response would double-insert
        out = self._call("insert", app_id, channel_id,
                         event=event.to_dict(api_format=False))
        return out["eventId"]

    def insert_batch(self, events, app_id, channel_id=None) -> List[str]:
        out = self._call("insert_batch", app_id, channel_id,
                         events=[e.to_dict(api_format=False) for e in events])
        return out["eventIds"]

    def insert_json_batch(self, raw: bytes, app_id, channel_id=None, *,
                          strict: bool = True):
        """Forward the RAW API-format JSON array to the storage
        server's native encoder (/storage/events/insert_json) — the
        event server's batch route then has zero per-row Python on
        either host. Raises JsonRowsUnsupported when the server's
        backend has no native lane (or declines the shape), so callers
        fall back to the per-row wire path. Same return contract as
        EventLogEventStore.insert_json_batch."""
        from urllib.parse import urlencode

        from predictionio_tpu.data.backends.eventlog import (
            JsonRowsUnsupported,
        )

        params = {"app_id": int(app_id), "strict": "1" if strict else "0"}
        if channel_id is not None:
            params["channel_id"] = int(channel_id)
        try:
            status, body = self._t.request(
                "/storage/events/insert_json?" + urlencode(params), raw)
        except S.StorageError as e:
            if "unknown route" in str(e):
                raise JsonRowsUnsupported() from None  # older server
            if getattr(e, "error_type", None) == "ValueError":
                # the server's structured discriminator: a CLIENT error
                # (malformed body) — re-raise as ValueError so the
                # batch route answers 400, not 500
                raise ValueError(str(e)) from None
            if getattr(e, "row_error", False):
                # the server's row_error discriminator, set ONLY for a
                # strict=True row-validation failure: re-raise clean
                # (transport wrapper stripped) under the same type the
                # local DAO raises synchronously. Other StorageErrors
                # (lock contention, I/O) keep their transport context
                # and type (ADVICE r4 low + r5 review)
                raise S.RowValidationError(
                    getattr(e, "server_message", str(e))) from None
            raise
        out = json.loads(body)
        if out.get("unsupported"):
            raise JsonRowsUnsupported()
        return out["ids"], out["codes"], out["names"], out["etypes"]

    def get(self, event_id, app_id, channel_id=None) -> Optional[Event]:
        out = self._call("get", app_id, channel_id, event_id=event_id,
                         idempotent=True)
        return Event.from_dict(out["event"]) if out else None

    def delete(self, event_id, app_id, channel_id=None) -> bool:
        # retried: deleting an id twice converges to the same state (the
        # replay may report found=False if the first attempt landed)
        return bool(self._call("delete", app_id, channel_id,
                               event_id=event_id, idempotent=True)["found"])

    _FIND_KEYS = frozenset(
        {"start_time", "until_time", "entity_type", "entity_id",
         "event_names", "target_entity_type", "target_entity_id",
         "limit", "reversed"}
    )

    @classmethod
    def _find_payload(cls, app_id, channel_id, find_kwargs) -> Dict[str, Any]:
        unknown = set(find_kwargs) - cls._FIND_KEYS
        if unknown:
            # a typo'd filter must fail loudly, never scan unfiltered
            # (the eventlog backend enforces the same invariant)
            raise TypeError(
                f"got unexpected filters {sorted(unknown)}"
            )
        payload: Dict[str, Any] = {
            "app_id": int(app_id), "channel_id": channel_id,
        }
        for key in ("start_time", "until_time"):
            v = find_kwargs.get(key)
            payload[key] = v.isoformat() if v is not None else None
        for key in ("entity_type", "entity_id", "limit"):
            payload[key] = find_kwargs.get(key)
        names = find_kwargs.get("event_names")
        payload["event_names"] = list(names) if names is not None else None
        payload["reversed"] = bool(find_kwargs.get("reversed", False))
        # tri-state target filters (absent | null | value) via *_set flags
        tt = find_kwargs.get("target_entity_type", S.UNSET)
        if tt is not S.UNSET:
            payload["target_entity_type_set"] = True
            payload["target_entity_type"] = tt
        ti = find_kwargs.get("target_entity_id", S.UNSET)
        if ti is not S.UNSET:
            payload["target_entity_id_set"] = True
            payload["target_entity_id"] = ti
        return payload

    def find(
        self,
        app_id,
        channel_id=None,
        start_time=None,
        until_time=None,
        entity_type=None,
        entity_id=None,
        event_names=None,
        target_entity_type=S.UNSET,
        target_entity_id=S.UNSET,
        limit=None,
        reversed=False,
        placement_shards=None,
        placement_count=None,
    ) -> List[Event]:
        """``placement_shards``/``placement_count`` (beyond the abstract
        contract; used by ShardedRestEventStore under replication) ask
        the SERVER to return only rows whose entity hash-routes to one
        of those shards — a replica holding R shards' copies then sends
        one shard's bytes, not its whole event set."""
        payload = self._find_payload(app_id, channel_id, {
            "start_time": start_time, "until_time": until_time,
            "entity_type": entity_type, "entity_id": entity_id,
            "event_names": event_names,
            "target_entity_type": target_entity_type,
            "target_entity_id": target_entity_id,
            "limit": limit, "reversed": reversed,
        })
        if placement_count is not None:
            payload["placement_shards"] = [int(x) for x in placement_shards]
            payload["placement_count"] = int(placement_count)
        # a read: on a mid-stream connection drop, retry the whole scan
        last = None
        with trace.span("storage.find", endpoint=self._t.base_url):
            for attempt in range(1 + self._t.retries):
                if attempt:
                    self._t._sleep_backoff(attempt - 1)
                try:
                    return [
                        Event.from_dict(json.loads(line))
                        for line in self._t.stream_lines(
                            "/storage/events/find", payload)
                    ]
                except StorageCircuitOpenError:
                    # guaranteed to fail fast again until the half-open
                    # window: backoff-sleeping against it would defeat
                    # the breaker (failover happens a layer up)
                    raise
                except S.StorageUnavailableError as e:
                    last = e
            raise last

    def find_columnar(
        self,
        app_id,
        channel_id=None,
        value_property=None,
        time_ordered=True,
        shard_index=None,
        shard_count=None,
        **find_kwargs,
    ) -> S.EventColumns:
        """Bulk training read over the wire as one binary npz of
        dict-encoded columns — 20M rows without per-event JSON.

        ``shard_index``/``shard_count`` travel in the request so the
        SERVER applies the entity-hash read shard: each of N training
        hosts receives only its ~1/N of the bytes (the per-executor
        HBase region-scan role, hbase/HBPEvents.scala:48).

        Two-phase, resumable: the server runs the scan once and spools
        the npz to disk (POST find_columnar -> {"scan_id", "bytes"});
        the bytes stream via GET .../scan/<id>?offset=N, so a dropped
        connection resumes from the last received byte instead of
        re-scanning, and an expired/restarted server triggers a
        re-prepare. The scan is released when fully received."""
        import tempfile

        S.EventStore.check_shard_params(shard_index, shard_count)
        payload = self._find_payload(app_id, channel_id, find_kwargs)
        payload["value_property"] = value_property
        payload["time_ordered"] = bool(time_ordered)
        if shard_count is not None:
            payload["shard_index"] = int(shard_index)
            payload["shard_count"] = int(shard_count)
        body = json.dumps(payload).encode()
        # outer loop retries SCAN EXPIRY only (the `continue` below);
        # connection failures raise out of request() after its own
        # idempotent retries — the budgets are for different failure
        # modes and do not multiply
        for attempt in range(1 + self._t.retries):
            if attempt:
                self._t._sleep_backoff(attempt - 1)
            status, prep_body = self._t.request(
                "/storage/events/find_columnar", body,
                timeout=max(self._t.timeout, 600.0),  # scans take minutes
                idempotent=True,
            )
            try:
                prep = json.loads(prep_body)
                scan_id, total = prep["scan_id"], int(prep["bytes"])
            except (ValueError, KeyError, TypeError):
                raise S.StorageError(
                    f"storage server {self._t.base_url}: find_columnar did "
                    "not answer the scan handshake (server/client version "
                    "skew?)"
                ) from None
            # spool to a client-side temp file: the multi-GB blob never
            # sits in memory next to the decoded arrays
            with tempfile.TemporaryFile() as spool:
                if not self._fetch_scan(scan_id, total, spool):
                    continue  # scan expired / server restarted: re-prepare
                try:
                    self._t.request(f"/storage/events/scan/{scan_id}",
                                    method="DELETE", idempotent=True)
                except S.StorageError:
                    pass  # best-effort release; the server TTL reaps it
                spool.seek(0)
                return S.npz_to_columns(spool)
        raise S.StorageUnavailableError(
            f"storage server {self._t.base_url}: bulk scan kept expiring "
            f"after {1 + self._t.retries} attempts"
        )

    def _fetch_scan(self, scan_id: str, total: int, spool) -> bool:
        """Stream a spooled scan into ``spool``, resuming from the
        received-byte offset on connection failures (each received
        chunk resets the retry budget — only LACK OF PROGRESS counts
        against it). False when the scan is gone server-side (caller
        re-prepares)."""
        received = 0
        failures = 0
        breaker = self._t.breaker
        while received < total:
            if not breaker.allow():
                raise StorageCircuitOpenError(
                    f"storage server {self._t.base_url} unreachable "
                    f"(circuit open mid-scan, {received}/{total} bytes)")
            req = self._t._request_obj(
                f"/storage/events/scan/{scan_id}?offset={received}",
                None, "GET", "application/octet-stream",
            )
            try:
                with urllib.request.urlopen(req, timeout=self._t.timeout) as resp:
                    while True:
                        chunk = resp.read(1 << 20)
                        if not chunk:
                            break
                        spool.write(chunk)
                        received += len(chunk)
                        failures = 0
                breaker.record_success()
            except urllib.error.HTTPError as e:
                breaker.record_success()  # an HTTP answer: reachable
                if e.code == 404:
                    return False
                raise self._t._error(f"/storage/events/scan/{scan_id}", e) from None
            except (urllib.error.URLError, ConnectionError, TimeoutError,
                    IncompleteRead):
                breaker.record_failure()
                failures += 1
                if failures > self._t.retries:
                    raise S.StorageUnavailableError(
                        f"storage server {self._t.base_url}: scan fetch made "
                        f"no progress after {failures} attempts "
                        f"({received}/{total} bytes)"
                    ) from None
                self._t._sleep_backoff(failures - 1)
        return True

    def insert_columnar(
        self,
        cols: S.EventColumns,
        app_id,
        channel_id=None,
        *,
        entity_type: str,
        target_entity_type=None,
        value_property=None,
    ) -> int:
        """Bulk ingest over the wire: npz body, scalar params in the
        query string (percent-encoded UTF-8 — header values would be
        latin-1-only)."""
        from urllib.parse import urlencode

        params = {"app_id": int(app_id), "entity_type": entity_type}
        if channel_id is not None:
            params["channel_id"] = int(channel_id)
        if target_entity_type is not None:
            params["target_entity_type"] = target_entity_type
        if value_property is not None:
            params["value_property"] = value_property
        status, body = self._t.request(
            "/storage/events/insert_columnar?" + urlencode(params),
            S.columns_to_npz(cols),
            content_type="application/octet-stream",
            timeout=max(self._t.timeout, 600.0),  # bulk ingest
        )
        return int(json.loads(body)["count"])


class ShardedRestEventStore(S.EventStore):
    """EVENTDATA partitioned across N storage servers by entity hash —
    the HBase region model (rowkey = MD5(entity) prefix spreads load
    across region servers, hbase/HBEventsUtil.scala:96-108) rebuilt on
    the framework's own storage service.

    Writes route by ``stable_hash(entity_id) % N`` (all of one entity's
    events live on one server); reads fan out to every shard and merge.
    A down shard fails LOUDLY: the underlying transport error names the
    shard's endpoint, and no read silently returns a partial result.

    ``replicas=R`` adds successor replication (the HDFS-under-HBase
    role): shard k's rows are written synchronously to servers
    k..k+R-1 (mod N), and reads pick the first LIVE server of each
    shard's replica set, asking it for shard k's rows only (the
    server-side shard filter keeps replica-held foreign shards out), so
    any R-1 servers can be down and every read still completes with the
    full data. Write availability intentionally requires a shard's
    whole replica set up: a failed replica write fails loudly, rolls
    back the copies already written (row path, by client-stamped id;
    best-effort), and writes land successors-first/owner-last so any
    un-rolled-back partial sits where owner-preferring reads don't
    look. Row-path inserts stamp event ids CLIENT-side so all copies
    share one id (get/delete/rollback stay consistent); bulk columnar
    ingest replicates rows but each copy gets its own server-assigned
    id — fine for the immutable interaction logs it exists for, not for
    rows that will be point-deleted; a mid-ingest failure is recovered
    by ``remove()`` + re-init + re-ingest, NOT a blind re-run (which
    would duplicate rows on replicas that already took the part).
    """

    def __init__(self, stores: List[RestEventStore], replicas: int = 1):
        assert len(stores) > 1
        if not 1 <= replicas <= len(stores):
            raise S.StorageError(
                f"REPLICAS={replicas} needs between 1 and {len(stores)} "
                "(the endpoint count) storage servers"
            )
        self._stores = stores
        self._replicas = replicas

    def _shard_of(self, entity_id: str) -> int:
        return S.stable_hash(entity_id) % len(self._stores)

    def _shard_for(self, entity_id: str) -> RestEventStore:
        return self._stores[self._shard_of(entity_id)]

    def _owners(self, shard: int) -> List[int]:
        """Server indexes holding shard ``shard``, owner first."""
        n = len(self._stores)
        return [(shard + r) % n for r in range(self._replicas)]

    def shard_names(self) -> List[str]:
        return [st._t.base_url for st in self._stores]

    def _pmap(self, items, fn) -> List[Any]:
        """fn(item) concurrently, results in order — fan-out reads must
        overlap the per-shard network I/O, and one slow shard must not
        serialize the others. The first error propagates (loud, the
        transport message names the endpoint). Worker count is bounded:
        rollbacks can fan over thousands of (server, id) pairs."""
        from concurrent.futures import ThreadPoolExecutor

        items = list(items)
        with ThreadPoolExecutor(max_workers=min(16, max(1, len(items)))) as ex:
            return list(ex.map(fn, items))

    def _map_shards(self, fn) -> List[Any]:
        return self._pmap(self._stores, fn)

    def _assign_live_servers(self) -> Dict[int, List[int]]:
        """server index -> shards it should answer for, choosing each
        shard's first LIVE replica (one cheap concurrent liveness probe,
        then each distinct server is scanned once). Raises when some
        shard's whole replica set is down, naming the shard."""
        def probe(st: RestEventStore) -> bool:
            try:
                st._t.request("/", method="GET")
                return True
            except S.StorageError:
                return False

        alive = self._pmap(self._stores, probe)
        assignment: Dict[int, List[int]] = {}
        for k in range(len(self._stores)):
            srv = next((o for o in self._owners(k) if alive[o]), None)
            if srv is None:
                raise S.StorageUnavailableError(
                    f"event shard {k}: every replica is down "
                    f"({', '.join(self._stores[o]._t.base_url for o in self._owners(k))})"
                )
            if srv != k:
                log.warning("shard %d: owner down, reading from replica %s",
                            k, self._stores[srv]._t.base_url)
            assignment.setdefault(srv, []).append(k)
        return assignment

    def _first_live(self, shard: int, fn):
        """fn(store) against the first live server of the shard's
        replica set — read failover. Only connection-level failures
        advance to the next replica; application errors propagate."""
        last: Optional[Exception] = None
        for s in self._owners(shard):
            try:
                return fn(self._stores[s])
            except S.StorageUnavailableError as e:
                log.warning("shard %d: %s down, trying next replica: %s",
                            shard, self._stores[s]._t.base_url, e)
                last = e
        raise last  # every replica of this shard is down

    # -- lifecycle: every shard ---------------------------------------------
    def init(self, app_id, channel_id=None):
        self._map_shards(lambda st: st.init(app_id, channel_id))

    def remove(self, app_id, channel_id=None):
        self._map_shards(lambda st: st.remove(app_id, channel_id))

    def compact(self, app_id, channel_id=None):
        return self._map_shards(lambda st: st.compact(app_id, channel_id))

    # -- writes: routed (to every replica when replicas > 1) ----------------
    #
    # Replica-write consistency: copies are written SUCCESSORS-FIRST,
    # owner last — reads prefer the owner, so a partial failure leaves
    # phantom rows only on replicas no healthy read consults — and a
    # row-path failure additionally ROLLS BACK the already-written
    # copies by their client-stamped ids (best-effort; a rollback
    # failure is logged and the original error still raised). Bulk
    # columnar ingest has no ids to roll back by: a failed replica
    # write there means re-running the ingest (documented).

    def _rollback(self, written: List[tuple], app_id, channel_id) -> None:
        """Best-effort delete of already-written copies: ``written`` is
        (server index, [event ids]) pairs, fanned out concurrently (a
        1000-row rollback must not serialize 1000 round-trips on the
        failure path)."""
        pairs = [(s, eid) for s, eids in written for eid in eids]

        def drop(pair):
            s, eid = pair
            try:
                self._stores[s].delete(eid, app_id, channel_id)
            except S.StorageError:
                log.warning(
                    "replica write rollback failed on %s for %s — "
                    "copies diverged until the delete is replayed",
                    self._stores[s]._t.base_url, eid)

        if pairs:
            self._pmap(pairs, drop)

    def insert(self, event: Event, app_id, channel_id=None) -> str:
        # one CLIENT-assigned id shared by every copy, so point reads,
        # deletes and rollbacks address all replicas consistently
        event = event if event.event_id else event.with_id()
        written: List[tuple] = []
        for s in reversed(self._owners(self._shard_of(event.entity_id))):
            try:
                self._stores[s].insert(event, app_id, channel_id)
            except S.StorageError:
                # roll back the committed copies AND the failing server:
                # a connection drop AFTER the server committed raises
                # here too, and the idempotent delete covers both
                # outcomes (the client-stamped id names every copy)
                self._rollback(written + [(s, [event.event_id])],
                               app_id, channel_id)
                raise
            written.append((s, [event.event_id]))
        return event.event_id

    def insert_batch(self, events, app_id, channel_id=None) -> List[str]:
        # ids are client-stamped at ANY replica count so a failure can
        # roll back every copy — including a commit-then-drop on the
        # very server that raised
        events = [e if e.event_id else e.with_id() for e in events]
        by_shard: Dict[int, List[int]] = {}
        for pos, e in enumerate(events):
            by_shard.setdefault(self._shard_of(e.entity_id), []).append(pos)
        ids: List[Optional[str]] = [None] * len(events)
        # rollback scope is the WHOLE batch, across shard groups: a
        # caller retrying a "failed" batch gets fresh ids, so any
        # committed group left behind would duplicate its rows
        all_written: List[tuple] = []
        for shard, positions in by_shard.items():
            batch = [events[p] for p in positions]
            batch_ids = [e.event_id for e in batch]
            for s in reversed(self._owners(shard)):
                try:
                    out = self._stores[s].insert_batch(batch, app_id, channel_id)
                except S.StorageError:
                    self._rollback(all_written + [(s, batch_ids)],
                                   app_id, channel_id)
                    raise
                all_written.append((s, batch_ids))
            for p, eid in zip(positions, out):
                ids[p] = eid
        return ids  # type: ignore[return-value]

    def insert_columnar(self, cols, app_id, channel_id=None, *,
                        entity_type, target_entity_type=None,
                        value_property=None) -> int:
        n = len(self._stores)
        total = 0
        for shard in range(n):
            part = S.shard_columns(cols, shard, n)
            if len(part):
                # successors first, owner last: a partial failure's
                # phantom copies sit where owner-preferring reads don't
                # look. Rows carry no client ids, so there is no
                # rollback here — recovery from a mid-ingest failure is
                # remove() + re-init + re-ingest (a blind re-run would
                # DUPLICATE rows on replicas that already took the part)
                for s in reversed(self._owners(shard)):
                    count = self._stores[s].insert_columnar(
                        part, app_id, channel_id, entity_type=entity_type,
                        target_entity_type=target_entity_type,
                        value_property=value_property)
                total += count
        return total

    # -- anti-entropy -------------------------------------------------------
    @staticmethod
    def _content_key(e: Event) -> tuple:
        """Identity of an event MINUS its id — columnar-ingested copies
        carry per-server ids, so content equality is what says two
        differently-id'd rows are the same event."""
        return (e.event, e.entity_type, e.entity_id,
                e.target_entity_type, e.target_entity_id,
                e.event_time,
                json.dumps(e.properties.to_dict()
                           if hasattr(e.properties, "to_dict")
                           else dict(e.properties), sort_keys=True))

    def repair(self, app_id, channel_id=None) -> Dict[str, int]:
        """Owner-authoritative replica reconciliation — the anti-entropy
        role HBase inherits from HDFS block repair. The write protocol's
        commit point is the OWNER copy (written last), so for every
        shard the owner's rows are truth: each replica gains the owner
        rows it is missing and drops rows the owner does not have
        (rollback leftovers, re-ingested duplicates). Rows are matched
        by id first, then by CONTENT multiset, so columnar-ingested
        copies (same rows, per-server ids) are recognized as consistent
        instead of rewritten.

        Operational preconditions: the full replica set of every shard
        must be up (repairing against a down owner would erase
        committed data), and writes must be QUIESCED for the repaired
        app — an insert in flight (replica written, owner not yet) is
        indistinguishable from an orphan and would be deleted, like an
        HBase major compaction this runs in a maintenance window.
        Memory is proportional to the largest shard's row count (owner
        and replica rows are materialized per shard for the diff); for
        huge bulk-ingested immutable logs prefer remove() + re-ingest.
        Raises on an unreplicated store — a zeros result must always
        mean "checked and consistent", never "nothing to check".
        Returns {"copied": n, "deleted": n}."""
        if self._replicas == 1:
            raise S.StorageError(
                "EVENTDATA is sharded but not replicated (REPLICAS=1) — "
                "nothing to repair"
            )
        import collections as _c

        n = len(self._stores)
        copied = 0
        to_delete: List[tuple] = []   # (server, event_id)
        for shard in range(n):
            owners = self._owners(shard)
            truth_rows = self._stores[owners[0]].find(
                app_id, channel_id=channel_id,
                placement_shards=[shard], placement_count=n)
            truth_by_id = {e.event_id: e for e in truth_rows}
            for r in owners[1:]:
                have = self._stores[r].find(
                    app_id, channel_id=channel_id,
                    placement_shards=[shard], placement_count=n)
                have_ids = {e.event_id for e in have}
                # unmatched-by-id remainders pair up by content
                owner_rest = [truth_by_id[i]
                              for i in truth_by_id.keys() - have_ids]
                replica_rest = [e for e in have
                                if e.event_id not in truth_by_id]
                owner_content = _c.Counter(
                    self._content_key(e) for e in owner_rest)
                missing, extras = [], []
                matched = _c.Counter()
                for e in replica_rest:
                    k = self._content_key(e)
                    if matched[k] < owner_content[k]:
                        matched[k] += 1   # same event, different id
                    else:
                        extras.append(e)
                seen = _c.Counter()
                for e in owner_rest:
                    k = self._content_key(e)
                    seen[k] += 1
                    if seen[k] > matched[k]:
                        missing.append(e)
                if missing:
                    self._stores[r].insert_batch(missing, app_id, channel_id)
                    copied += len(missing)
                to_delete.extend((r, e.event_id) for e in extras)

        def drop(pair):
            r, eid = pair
            self._stores[r].delete(eid, app_id, channel_id)

        if to_delete:
            # fanned out, same reasoning as _rollback: a large orphan
            # set must not serialize one round-trip per id
            self._pmap(to_delete, drop)
        return {"copied": copied, "deleted": len(to_delete)}

    # -- point reads: the id does not encode its shard ----------------------
    def get(self, event_id, app_id, channel_id=None) -> Optional[Event]:
        if self._replicas == 1:
            results = self._map_shards(
                lambda st: st.get(event_id, app_id, channel_id))
            return next((e for e in results if e is not None), None)

        # replicated read: a down server is tolerated as long as every
        # shard still has a live replica — then a miss is a REAL miss
        def probe(i):
            try:
                return self._stores[i].get(event_id, app_id, channel_id)
            except S.StorageUnavailableError as e:
                return e

        results = self._pmap(range(len(self._stores)), probe)
        for r in results:
            if isinstance(r, Event):
                return r
        down = {i for i, r in enumerate(results)
                if isinstance(r, S.StorageUnavailableError)}
        for k in range(len(self._stores)):
            if all(o in down for o in self._owners(k)):
                raise next(r for r in results
                           if isinstance(r, S.StorageUnavailableError))
        return None

    def delete(self, event_id, app_id, channel_id=None) -> bool:
        # a delete is a WRITE: it must reach every replica (a copy left
        # on a down server would resurrect on recovery), so server
        # unavailability propagates — same strictness as inserts
        return any(self._map_shards(
            lambda st: st.delete(event_id, app_id, channel_id)))

    # -- scans: fan out (one live replica per shard) + merge ----------------
    def find(self, app_id, channel_id=None, limit=None, reversed=False,
             **find_kwargs) -> List[Event]:
        n = len(self._stores)
        if self._replicas == 1:
            # per-shard results are time-ordered and individually
            # limited; the merged sort + truncation is the global answer
            parts = self._map_shards(
                lambda st: st.find(app_id, channel_id=channel_id,
                                   limit=limit, reversed=reversed,
                                   **find_kwargs))
        else:
            # replicated: resolve one live server per shard and scan
            # each distinct server ONCE for all its assigned shards —
            # the server's placement filter (applied BEFORE any row
            # limit) keeps a replica's foreign-shard copies off the
            # wire, so the per-shard limit optimization applies here
            # too. The client-side re-filter is a cheap guard against
            # an older server ignoring the placement keys (such a
            # server must not be mixed with limited scans).
            assignment = self._assign_live_servers()

            def fetch(srv, shards):
                return self._stores[srv].find(
                    app_id, channel_id=channel_id, limit=limit,
                    reversed=reversed, placement_shards=shards,
                    placement_count=n, **find_kwargs)

            def scan(item):
                srv, shards = item
                try:
                    part = fetch(srv, shards)
                except S.StorageUnavailableError:
                    # the server died between the liveness probe and
                    # the scan: fail over per shard through the rest
                    # of each replica set instead of failing the read
                    part = []
                    for k in shards:
                        part.extend(self._first_live(
                            k, lambda st: st.find(
                                app_id, channel_id=channel_id,
                                limit=limit, reversed=reversed,
                                placement_shards=[k], placement_count=n,
                                **find_kwargs)))
                mine = set(shards)
                return [e for e in part
                        if S.stable_hash(e.entity_id) % n in mine]

            parts = self._pmap(assignment.items(), scan)
        merged = sorted(
            (e for part in parts for e in part),
            key=lambda e: e.event_time, reverse=bool(reversed),
        )
        if limit is not None and limit >= 0:
            merged = merged[:limit]
        return merged

    def find_columnar(self, app_id, channel_id=None, value_property=None,
                      time_ordered=True, shard_index=None, shard_count=None,
                      limit=None, **find_kwargs) -> S.EventColumns:
        S.EventStore.check_shard_params(shard_index, shard_count)
        host_shard = ({"shard_index": shard_index, "shard_count": shard_count}
                      if shard_count is not None else {})
        newest_first = bool(find_kwargs.get("reversed", False))
        if limit is not None:
            # per-shard limit is a bandwidth optimization: each shard's
            # top-`limit` by time is a superset of its contribution to
            # the global top-`limit` (truncated again after the merge)
            find_kwargs["limit"] = limit
        n = len(self._stores)
        if self._replicas == 1:
            parts = self._map_shards(
                lambda st: st.find_columnar(
                    app_id, channel_id=channel_id,
                    value_property=value_property,
                    time_ordered=(time_ordered or limit is not None),
                    **host_shard, **find_kwargs))
        else:
            # replicated: the ONE server-side shard-filter pair carries
            # the PLACEMENT filter (keeps the replica's foreign shards
            # out); a requested host read shard is applied client-side
            # on each part instead
            kw = dict(find_kwargs)
            if host_shard:
                # the client-side host filter must precede any limit, so
                # the per-shard limit optimization is off in this combo
                kw.pop("limit", None)

            def one_shard(k):
                part = self._first_live(
                    k, lambda st: st.find_columnar(
                        app_id, channel_id=channel_id,
                        value_property=value_property,
                        time_ordered=(time_ordered or limit is not None),
                        shard_index=k, shard_count=n, **kw))
                if host_shard:
                    part = S.shard_columns(part, shard_index, shard_count)
                return part

            parts = self._pmap(range(n), one_shard)
        merged = S.merge_columns(
            parts, time_ordered=(time_ordered or limit is not None))
        if limit is not None:
            # respects `reversed` (keep the global NEWEST rows), unlike
            # a head-truncation of the ascending merge
            merged = S.limit_columns(merged, limit,
                                     newest_first=newest_first)
        elif time_ordered and newest_first and len(merged):
            # no limit, but reversed time order was asked for: the
            # ascending merge must flip to newest-first (find's order)
            import numpy as np

            flip = np.arange(len(merged))[::-1]
            merged = S.EventColumns(
                entity_codes=merged.entity_codes[flip],
                target_codes=merged.target_codes[flip],
                name_codes=merged.name_codes[flip],
                values=merged.values[flip],
                times_us=merged.times_us[flip],
                entity_vocab=merged.entity_vocab,
                target_vocab=merged.target_vocab,
                names=merged.names,
            )
        return merged


class _RestRepo:
    """Generic metadata repo proxy: method calls become /storage/meta RPCs."""

    repo: str = ""
    record_cls: type = object

    def __init__(self, transport: _Transport):
        self._t = transport

    def _rpc(self, method: str, args: List[Any], kind: str) -> Any:
        # reads, full-record updates and deletes are idempotent;
        # inserts are not (replaying one could double-create)
        idempotent = not method.startswith("insert")
        out = self._t.json_call(
            f"/storage/meta/{self.repo}/{method}", {"args": args},
            idempotent=idempotent,
        )
        result = out["result"] if out else None
        if result is None:
            return [] if kind == "records" else None
        if kind == "record":
            return MD.dict_to_record(self.record_cls, result)
        if kind == "records":
            return [MD.dict_to_record(self.record_cls, r) for r in result]
        return result


class RestAppsRepo(_RestRepo, S.AppsRepo):
    repo, record_cls = "apps", App

    def insert(self, name, description=None):
        return self._rpc("insert", [name, description], "record")

    def put(self, app):
        self._rpc("put", [MD.record_to_dict(app)], "scalar")

    def get(self, app_id):
        return self._rpc("get", [int(app_id)], "record")

    def get_by_name(self, name):
        return self._rpc("get_by_name", [name], "record")

    def get_all(self):
        return self._rpc("get_all", [], "records")

    def update(self, app):
        self._rpc("update", [MD.record_to_dict(app)], "scalar")

    def delete(self, app_id):
        self._rpc("delete", [int(app_id)], "scalar")


class RestAccessKeysRepo(_RestRepo, S.AccessKeysRepo):
    repo, record_cls = "access_keys", AccessKey

    def insert(self, access_key):
        return self._rpc("insert", [MD.record_to_dict(access_key)], "scalar")

    def put(self, access_key):
        self._rpc("put", [MD.record_to_dict(access_key)], "scalar")

    def get(self, key):
        return self._rpc("get", [key], "record")

    def get_all(self):
        return self._rpc("get_all", [], "records")

    def get_by_app_id(self, app_id):
        return self._rpc("get_by_app_id", [int(app_id)], "records")

    def update(self, access_key):
        self._rpc("update", [MD.record_to_dict(access_key)], "scalar")

    def delete(self, key):
        self._rpc("delete", [key], "scalar")


class RestChannelsRepo(_RestRepo, S.ChannelsRepo):
    repo, record_cls = "channels", Channel

    def insert(self, name, app_id):
        return self._rpc("insert", [name, int(app_id)], "record")

    def put(self, channel):
        self._rpc("put", [MD.record_to_dict(channel)], "scalar")

    def get(self, channel_id):
        return self._rpc("get", [int(channel_id)], "record")

    def get_by_app_id(self, app_id):
        return self._rpc("get_by_app_id", [int(app_id)], "records")

    def delete(self, channel_id):
        self._rpc("delete", [int(channel_id)], "scalar")


class RestEngineManifestsRepo(_RestRepo, S.EngineManifestsRepo):
    repo, record_cls = "engine_manifests", EngineManifest

    def insert(self, manifest):
        self._rpc("insert", [MD.record_to_dict(manifest)], "scalar")

    def put(self, manifest):
        self._rpc("put", [MD.record_to_dict(manifest)], "scalar")

    def get(self, id, version):
        return self._rpc("get", [id, version], "record")

    def get_all(self):
        return self._rpc("get_all", [], "records")

    def update(self, manifest):
        self._rpc("update", [MD.record_to_dict(manifest)], "scalar")

    def delete(self, id, version):
        self._rpc("delete", [id, version], "scalar")


class RestEngineInstancesRepo(_RestRepo, S.EngineInstancesRepo):
    repo, record_cls = "engine_instances", EngineInstance

    def insert(self, instance):
        return self._rpc("insert", [MD.record_to_dict(instance)], "scalar")

    def put(self, instance):
        self._rpc("put", [MD.record_to_dict(instance)], "scalar")

    def get(self, id):
        return self._rpc("get", [id], "record")

    def get_all(self):
        return self._rpc("get_all", [], "records")

    def get_latest_completed(self, engine_id, engine_version, engine_variant):
        return self._rpc(
            "get_latest_completed",
            [engine_id, engine_version, engine_variant], "record",
        )

    def get_completed(self, engine_id, engine_version, engine_variant):
        return self._rpc(
            "get_completed", [engine_id, engine_version, engine_variant],
            "records",
        )

    def update(self, instance):
        self._rpc("update", [MD.record_to_dict(instance)], "scalar")

    def delete(self, id):
        self._rpc("delete", [id], "scalar")


class RestEvaluationInstancesRepo(_RestRepo, S.EvaluationInstancesRepo):
    repo, record_cls = "evaluation_instances", EvaluationInstance

    def insert(self, instance):
        return self._rpc("insert", [MD.record_to_dict(instance)], "scalar")

    def put(self, instance):
        self._rpc("put", [MD.record_to_dict(instance)], "scalar")

    def get(self, id):
        return self._rpc("get", [id], "record")

    def get_all(self):
        return self._rpc("get_all", [], "records")

    def get_completed(self):
        return self._rpc("get_completed", [], "records")

    def update(self, instance):
        self._rpc("update", [MD.record_to_dict(instance)], "scalar")

    def delete(self, id):
        self._rpc("delete", [id], "scalar")


class RestModelsRepo(S.ModelsRepo):
    """Model blobs as raw bodies — the HDFSModels role over HTTP."""

    def __init__(self, transport: _Transport):
        self._t = transport

    def insert(self, model: Model) -> None:
        # PUT of the full blob under a fixed id: idempotent by nature
        self._t.request(
            f"/storage/models/{model.id}", bytes(model.models), method="PUT",
            content_type="application/octet-stream", idempotent=True,
        )

    def get(self, id: str) -> Optional[Model]:
        status, body = self._t.request(
            f"/storage/models/{id}", method="GET", idempotent=True
        )
        if status == 404:
            return None
        return Model(id=id, models=body)

    def delete(self, id: str) -> None:
        self._t.request(f"/storage/models/{id}", method="DELETE",
                        idempotent=True)

    def list(self) -> List[Dict[str, Any]]:
        status, body = self._t.request("/storage/models", method="GET",
                                       idempotent=True)
        return json.loads(body)["models"]


# ---------------------------------------------------------------------------
# Replicated METADATA / MODELDATA (VERDICT r3 item 1)
# ---------------------------------------------------------------------------
#
# The reference's metadata tier is highly available because
# Elasticsearch replicates every index across its cluster
# (elasticsearch/StorageClient.scala:42 — the transport client talks
# to a CLUSTER), and model blobs survive machine loss because HDFS
# keeps 3 copies of every block (hdfs/HDFSModels.scala:28). Here the
# same availability is built from the framework's own storage servers:
# with ``REPLICAS=R``, apps / access keys / channels / manifests /
# instances / model blobs live on the FIRST R endpoints — every write
# lands synchronously on all R, reads prefer the owner (endpoint 0)
# and fail over through its successors, and `pio storagerepair`
# reconciles divergence owner-authoritatively.
#
# Write-order invariant (same as the event tier): copies are written
# SUCCESSORS-FIRST, owner LAST. Reads prefer the owner, so a partial
# failure leaves phantom copies only where healthy reads don't look,
# and a failed write reads back as "never happened". The exception is
# the id-ASSIGNING inserts (apps, channels): their id comes from the
# owner's sequence, so the owner must be written first — a failed
# successor write then ROLLS BACK every copy by the now-known id.
# Write availability intentionally requires the full replica set up
# (a write that skipped a down replica would silently un-replicate);
# the error names the dead endpoint.


class _ReplicatedRepoBase:
    """R per-endpoint proxies; index 0 is the owner."""

    def __init__(self, proxies: List[Any]):
        assert len(proxies) > 1
        self._proxies = proxies

    @staticmethod
    def _url(proxy) -> str:
        return proxy._t.base_url

    def _read(self, fn):
        """fn against the first live replica, owner-preferred. Only
        connection-level failures advance; application errors (a 400,
        a validation failure) propagate from the owner."""
        last: Optional[Exception] = None
        for p in self._proxies:
            try:
                return fn(p)
            except S.StorageUnavailableError as e:
                log.warning("metadata replica %s down, failing over: %s",
                            self._url(p), e)
                last = e
        raise last

    def _write_all(self, fn, rollback=None) -> None:
        """fn on every replica, successors-first owner-last. On failure:
        best-effort ``rollback(proxy)`` on the already-written copies
        AND the failing endpoint (a commit-then-connection-drop raises
        here too, and an idempotent rollback covers both outcomes),
        then the original error propagates, naming the endpoint."""
        written: List[Any] = []
        for p in reversed(self._proxies):
            try:
                fn(p)
            except S.StorageError:
                if rollback is not None:
                    for q in written + [p]:
                        try:
                            rollback(q)
                        except S.StorageError:
                            log.warning(
                                "metadata write rollback failed on %s — "
                                "copies diverged until `pio storagerepair`",
                                self._url(q))
                raise
            written.append(p)

    def _insert_owner_first(self, insert_fn, record_of, rollback):
        """The id-assigning insert protocol: owner insert assigns the
        id, successors take the full record via put, failure rolls back
        every copy by id."""
        record = insert_fn(self._proxies[0])
        written = [self._proxies[0]]
        for p in self._proxies[1:]:
            try:
                p.put(record_of(record))
            except S.StorageError:
                for q in written + [p]:
                    try:
                        rollback(q, record)
                    except S.StorageError:
                        log.warning(
                            "metadata insert rollback failed on %s — "
                            "copies diverged until `pio storagerepair`",
                            self._url(q))
                raise
            written.append(p)
        return record


class ReplicatedAppsRepo(_ReplicatedRepoBase, S.AppsRepo):
    def insert(self, name, description=None):
        return self._insert_owner_first(
            lambda p: p.insert(name, description),
            lambda app: app,
            lambda q, app: q.delete(app.id))

    def get(self, app_id):
        return self._read(lambda p: p.get(app_id))

    def get_by_name(self, name):
        return self._read(lambda p: p.get_by_name(name))

    def get_all(self):
        return self._read(lambda p: p.get_all())

    def update(self, app):
        # put (an upsert) instead of update on every copy: it also
        # self-heals a replica that missed the record entirely
        self._write_all(lambda p: p.put(app))

    def put(self, app):
        self._write_all(lambda p: p.put(app))

    def delete(self, app_id):
        self._write_all(lambda p: p.delete(app_id))


class ReplicatedAccessKeysRepo(_ReplicatedRepoBase, S.AccessKeysRepo):
    def insert(self, access_key):
        # the key is generated CLIENT-side so every copy shares it (the
        # event tier's client-stamped-id move); server-side generation
        # would mint a different key per replica
        if not access_key.key:
            access_key = AccessKey.generate(access_key.appid,
                                            access_key.events)
        self._write_all(lambda p: p.put(access_key),
                        rollback=lambda q: q.delete(access_key.key))
        return access_key.key

    def get(self, key):
        return self._read(lambda p: p.get(key))

    def get_all(self):
        return self._read(lambda p: p.get_all())

    def get_by_app_id(self, app_id):
        return self._read(lambda p: p.get_by_app_id(app_id))

    def update(self, access_key):
        self._write_all(lambda p: p.put(access_key))

    def put(self, access_key):
        self._write_all(lambda p: p.put(access_key))

    def delete(self, key):
        self._write_all(lambda p: p.delete(key))


class ReplicatedChannelsRepo(_ReplicatedRepoBase, S.ChannelsRepo):
    def insert(self, name, app_id):
        return self._insert_owner_first(
            lambda p: p.insert(name, app_id),
            lambda ch: ch,
            lambda q, ch: q.delete(ch.id))

    def get(self, channel_id):
        return self._read(lambda p: p.get(channel_id))

    def get_by_app_id(self, app_id):
        return self._read(lambda p: p.get_by_app_id(app_id))

    def put(self, channel):
        self._write_all(lambda p: p.put(channel))

    def delete(self, channel_id):
        self._write_all(lambda p: p.delete(channel_id))


class ReplicatedEngineManifestsRepo(_ReplicatedRepoBase, S.EngineManifestsRepo):
    def insert(self, manifest):
        # manifests upsert by natural key (`pio build` re-registers), so
        # a rollback could erase a PRE-EXISTING registration — rely on
        # owner-last ordering + repair instead
        self._write_all(lambda p: p.put(manifest))

    def get(self, id, version):
        return self._read(lambda p: p.get(id, version))

    def get_all(self):
        return self._read(lambda p: p.get_all())

    def update(self, manifest):
        self._write_all(lambda p: p.put(manifest))

    def put(self, manifest):
        self._write_all(lambda p: p.put(manifest))

    def delete(self, id, version):
        self._write_all(lambda p: p.delete(id, version))


class ReplicatedEngineInstancesRepo(_ReplicatedRepoBase, S.EngineInstancesRepo):
    def insert(self, instance):
        # id client-stamped (the server would mint one per replica)
        if not instance.id:
            import uuid as _uuid

            instance.id = _uuid.uuid4().hex
        self._write_all(lambda p: p.put(instance),
                        rollback=lambda q: q.delete(instance.id))
        return instance.id

    def get(self, id):
        return self._read(lambda p: p.get(id))

    def get_all(self):
        return self._read(lambda p: p.get_all())

    def get_latest_completed(self, engine_id, engine_version, engine_variant):
        return self._read(lambda p: p.get_latest_completed(
            engine_id, engine_version, engine_variant))

    def get_completed(self, engine_id, engine_version, engine_variant):
        return self._read(lambda p: p.get_completed(
            engine_id, engine_version, engine_variant))

    def update(self, instance):
        self._write_all(lambda p: p.put(instance))

    def put(self, instance):
        self._write_all(lambda p: p.put(instance))

    def delete(self, id):
        self._write_all(lambda p: p.delete(id))


class ReplicatedEvaluationInstancesRepo(_ReplicatedRepoBase,
                                        S.EvaluationInstancesRepo):
    def insert(self, instance):
        if not instance.id:
            import uuid as _uuid

            instance.id = _uuid.uuid4().hex
        self._write_all(lambda p: p.put(instance),
                        rollback=lambda q: q.delete(instance.id))
        return instance.id

    def get(self, id):
        return self._read(lambda p: p.get(id))

    def get_all(self):
        return self._read(lambda p: p.get_all())

    def get_completed(self):
        return self._read(lambda p: p.get_completed())

    def update(self, instance):
        self._write_all(lambda p: p.put(instance))

    def put(self, instance):
        self._write_all(lambda p: p.put(instance))

    def delete(self, id):
        self._write_all(lambda p: p.delete(id))


class ReplicatedModelsRepo(_ReplicatedRepoBase, S.ModelsRepo):
    """Model blobs on R endpoints — the HDFS-3x-copies role
    (hdfs/HDFSModels.scala:28) so a serving host can /reload from a
    surviving replica after the blob's home dies."""

    def insert(self, model):
        self._write_all(lambda p: p.insert(model),
                        rollback=lambda q: q.delete(model.id))

    def get(self, id):
        return self._read(lambda p: p.get(id))

    def delete(self, id):
        self._write_all(lambda p: p.delete(id))

    def list(self):
        return self._read(lambda p: p.list())


#: (repo accessor, record key, enumerate(client) -> records) per
#: metadata repo — drives owner-authoritative reconciliation. Channels
#: have no get_all: they are enumerated through the endpoint's OWN apps
#: listing (apps are repaired first, so the listings agree by then).
_META_REPAIR_SPECS = [
    ("apps", lambda r: r.id, lambda c: c.get_all()),
    ("access_keys", lambda r: r.key, lambda c: c.get_all()),
    ("channels", lambda r: r.id, None),  # via apps; see _enumerate_channels
    ("engine_manifests", lambda r: (r.id, r.version), lambda c: c.get_all()),
    ("engine_instances", lambda r: r.id, lambda c: c.get_all()),
    ("evaluation_instances", lambda r: r.id, lambda c: c.get_all()),
]


class RestStorageClient(S.StorageClient):
    """Storage source of TYPE ``rest`` (HOSTS/PORTS per the env grammar).

    N comma-separated endpoints shard EVENTDATA by entity hash across N
    storage servers (ShardedRestEventStore — the HBase region-server
    fan-out role). Metadata and model blobs are NOT hash-shardable (they
    are keyed lookups + listings): with ``REPLICAS=1`` they pin to the
    FIRST endpoint; with ``REPLICAS=R>1`` they are REPLICATED across the
    first R endpoints (Replicated*Repo — the ES-index-replication /
    HDFS-3x-blobs roles), so the death of the metadata home no longer
    takes out apps, access keys, engine instances, or trained models.
    HOSTS/PORTS zip elementwise; a single value on
    one side broadcasts (``HOSTS=10.0.0.5 PORTS=7077,7078`` = two
    servers on one box; ``HOSTS=a,b PORTS=7077`` = one port on two).
    ``REPLICAS=R`` (default 1) adds successor replication of the event
    shards — any R-1 servers down, reads still complete (the
    HDFS-replication-under-HBase role; see ShardedRestEventStore).
    """

    def __init__(self, config: Dict[str, str]):
        super().__init__(config)
        hosts = [h.strip() for h in
                 (config.get("HOSTS") or "127.0.0.1").split(",")]
        ports = [p.strip() for p in
                 (config.get("PORTS") or "7077").split(",")]
        if len(hosts) == 1 and len(ports) > 1:
            hosts = hosts * len(ports)
        if len(ports) == 1 and len(hosts) > 1:
            ports = ports * len(hosts)
        if len(hosts) != len(ports):
            raise S.StorageError(
                f"rest source: {len(hosts)} HOSTS vs {len(ports)} PORTS "
                "(must match, or one side must be a single value)"
            )
        scheme = config.get("SCHEME", "http")
        timeout = float(config.get("TIMEOUT", "30"))
        retries = int(config.get("RETRIES", "3"))
        self._transports = [
            _Transport(f"{scheme}://{h}:{p}", config.get("AUTH_KEY"),
                       timeout, retries=retries)
            for h, p in zip(hosts, ports)
        ]
        self._transport = self._transports[0]  # metadata/models home
        replicas = int(config.get("REPLICAS", "1"))
        if len(self._transports) == 1:
            if replicas > 1:
                raise S.StorageError(
                    f"REPLICAS={replicas} needs multiple endpoints "
                    "(comma-separated HOSTS/PORTS)"
                )
            self._events: S.EventStore = RestEventStore(self._transport)
        else:
            self._events = ShardedRestEventStore(
                [RestEventStore(t) for t in self._transports],
                replicas=replicas)
        self._meta_replicas = replicas if len(self._transports) > 1 else 1
        if self._meta_replicas > 1:
            # metadata + models on the first R endpoints: synchronous
            # replication, owner-preferring read failover
            metas = self._transports[:self._meta_replicas]
            self._apps = ReplicatedAppsRepo([RestAppsRepo(t) for t in metas])
            self._access_keys = ReplicatedAccessKeysRepo(
                [RestAccessKeysRepo(t) for t in metas])
            self._channels = ReplicatedChannelsRepo(
                [RestChannelsRepo(t) for t in metas])
            self._engine_manifests = ReplicatedEngineManifestsRepo(
                [RestEngineManifestsRepo(t) for t in metas])
            self._engine_instances = ReplicatedEngineInstancesRepo(
                [RestEngineInstancesRepo(t) for t in metas])
            self._evaluation_instances = ReplicatedEvaluationInstancesRepo(
                [RestEvaluationInstancesRepo(t) for t in metas])
            self._models = ReplicatedModelsRepo(
                [RestModelsRepo(t) for t in metas])
        else:
            self._apps = RestAppsRepo(self._transport)
            self._access_keys = RestAccessKeysRepo(self._transport)
            self._channels = RestChannelsRepo(self._transport)
            self._engine_manifests = RestEngineManifestsRepo(self._transport)
            self._engine_instances = RestEngineInstancesRepo(self._transport)
            self._evaluation_instances = RestEvaluationInstancesRepo(self._transport)
            self._models = RestModelsRepo(self._transport)

    def events(self): return self._events
    def apps(self): return self._apps
    def access_keys(self): return self._access_keys
    def channels(self): return self._channels
    def engine_manifests(self): return self._engine_manifests
    def engine_instances(self): return self._engine_instances
    def evaluation_instances(self): return self._evaluation_instances
    def models(self): return self._models

    def health_check(self) -> bool:
        """`pio status` probe: EVERY shard must answer GET / as alive."""
        return all(self.health_detail().values())

    def health_detail(self) -> Dict[str, bool]:
        """Per-endpoint liveness, keyed by shard URL — `pio status`
        names the down shard instead of a bare FAILED. Deliberately
        conservative for the repos pinned to the first endpoint
        (metadata/models): ANY down shard marks the source unhealthy,
        because a partially-down event tier makes training reads fail
        even while metadata lookups still answer."""
        def probe(t: _Transport) -> bool:
            try:
                status, body = t.request("/", method="GET")
                return (status == 200
                        and json.loads(body).get("status") == "alive")
            except (S.StorageError, ValueError):
                # ValueError: a 200 with a non-JSON body (e.g. a proxy
                # error page) is just as dead as a refused connection —
                # it must mark THIS shard down, not abort the probe
                return False

        # concurrent: a down shard waiting out its timeout must not
        # stall the probes of the healthy ones
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=len(self._transports)) as ex:
            alive = list(ex.map(probe, self._transports))
        return {t.base_url: a for t, a in zip(self._transports, alive)}

    @property
    def meta_replicated(self) -> bool:
        """Whether METADATA/MODELDATA on this source is replicated —
        the capability probe `pio storagerepair` uses to SKIP an
        unreplicated source (vs repair_meta's loud StorageError, which
        must stay loud for direct callers)."""
        return self._meta_replicas > 1

    def health_tiers(self) -> Dict[str, Any]:
        """Tier-resolved health (VERDICT r3 item 9): beyond the
        conservative per-endpoint map, report whether each TIER can
        still ANSWER — metadata/models serve while ANY of their first R
        replicas lives; the event tier serves while EVERY shard has a
        live replica. `pio status` turns this into distinct exit codes
        so operators can page on "down" vs "degraded-but-serving"."""
        detail = self.health_detail()
        alive = [detail[t.base_url] for t in self._transports]
        n = len(self._transports)
        meta_serving = any(alive[:self._meta_replicas])
        if isinstance(self._events, ShardedRestEventStore):
            ev = self._events
            events_serving = all(
                any(alive[o] for o in ev._owners(k)) for k in range(n))
        else:
            events_serving = alive[0]
        return {
            "endpoints": detail,
            "metadata_serving": meta_serving,
            "events_serving": events_serving,
            "all_up": all(alive),
        }

    # -- metadata/model anti-entropy ----------------------------------------
    def _enumerate_channels(self, proxies_by_repo, endpoint) -> List[Channel]:
        """All channels an endpoint holds, via its OWN apps listing
        (ChannelsRepo has no get_all; apps are repaired first so the
        listings agree by the time channels reconcile)."""
        apps = proxies_by_repo["apps"][endpoint].get_all()
        chan_repo = proxies_by_repo["channels"][endpoint]
        out: List[Channel] = []
        for app in apps:
            out.extend(chan_repo.get_by_app_id(app.id))
        return out

    def repair_meta(self) -> Dict[str, int]:
        """Owner-authoritative reconciliation of the replicated
        METADATA + MODELDATA tier (`pio storagerepair`) — the
        anti-entropy role ES performs when a recovered node re-syncs
        its replica shards. For every repo the owner endpoint's records
        are truth: each replica gains the owner records it is missing
        or holds stale (compared as full dicts), and drops records the
        owner does not have (rollback leftovers). Model blobs compare
        by sha256 from the inventory route.

        Preconditions mirror ShardedRestEventStore.repair: every
        metadata replica must be up (the failover read would otherwise
        treat a stale successor as truth), and writes should be
        quiesced. Raises on an unreplicated source — zeros must mean
        "checked and consistent". Returns {"copied": n, "deleted": n}.
        """
        if self._meta_replicas <= 1:
            raise S.StorageError(
                "METADATA/MODELDATA is not replicated (REPLICAS=1) — "
                "nothing to repair"
            )
        metas = self._transports[:self._meta_replicas]
        proxies_by_repo = {
            "apps": [RestAppsRepo(t) for t in metas],
            "access_keys": [RestAccessKeysRepo(t) for t in metas],
            "channels": [RestChannelsRepo(t) for t in metas],
            "engine_manifests": [RestEngineManifestsRepo(t) for t in metas],
            "engine_instances": [RestEngineInstancesRepo(t) for t in metas],
            "evaluation_instances": [RestEvaluationInstancesRepo(t)
                                     for t in metas],
        }
        copied = deleted = 0
        for repo_name, key_of, enumerate_fn in _META_REPAIR_SPECS:
            proxies = proxies_by_repo[repo_name]

            def records_of(endpoint: int):
                if enumerate_fn is None:
                    return self._enumerate_channels(proxies_by_repo, endpoint)
                return enumerate_fn(proxies[endpoint])

            truth = {key_of(r): r for r in records_of(0)}
            if not truth:
                # empty-owner guard (code-review regression): a
                # re-provisioned BLANK owner must never erase the
                # surviving replicas' records under the banner of
                # "repair" — that is exactly the outage replication
                # exists to survive
                for endpoint in range(1, len(metas)):
                    n_replica = len(records_of(endpoint))
                    if n_replica:
                        raise S.StorageError(
                            f"metadata repair refused: owner "
                            f"{metas[0].base_url} has no {repo_name} "
                            f"records while replica "
                            f"{metas[endpoint].base_url} holds "
                            f"{n_replica} — a blank (re-provisioned?) "
                            "owner would delete them all; seed the "
                            "owner from a replica or remove the stale "
                            "replica data first")
                continue
            truth_dicts = {k: MD.record_to_dict(r) for k, r in truth.items()}
            for endpoint in range(1, len(metas)):
                have = {key_of(r): r for r in records_of(endpoint)}
                for k, rec in truth.items():
                    mine = have.get(k)
                    if mine is None or MD.record_to_dict(mine) != truth_dicts[k]:
                        proxies[endpoint].put(rec)
                        copied += 1
                for k, rec in have.items():
                    if k not in truth:
                        # delete signatures vary by repo; the key IS the
                        # delete argument except manifests' (id, version)
                        if repo_name == "engine_manifests":
                            proxies[endpoint].delete(*k)
                        else:
                            proxies[endpoint].delete(k)
                        deleted += 1
        # model blobs: sha256 inventory diff, owner-authoritative
        model_proxies = [RestModelsRepo(t) for t in metas]
        truth_inv = {m["id"]: m for m in model_proxies[0].list()}
        if not truth_inv:
            # same empty-owner guard as the record repos above
            for endpoint in range(1, len(metas)):
                n_replica = len(model_proxies[endpoint].list())
                if n_replica:
                    raise S.StorageError(
                        f"metadata repair refused: owner "
                        f"{metas[0].base_url} has no model blobs while "
                        f"replica {metas[endpoint].base_url} holds "
                        f"{n_replica} — seed the owner from a replica "
                        "or remove the stale replica data first")
            return {"copied": copied, "deleted": deleted}
        for endpoint in range(1, len(metas)):
            have_inv = {m["id"]: m for m in model_proxies[endpoint].list()}
            for mid, info in truth_inv.items():
                mine = have_inv.get(mid)
                if mine is None or mine["sha256"] != info["sha256"]:
                    blob = model_proxies[0].get(mid)
                    if blob is not None:  # deleted between list and get
                        model_proxies[endpoint].insert(blob)
                        copied += 1
            for mid in have_inv.keys() - truth_inv.keys():
                model_proxies[endpoint].delete(mid)
                deleted += 1
        return {"copied": copied, "deleted": deleted}


S.register_backend("rest", RestStorageClient)
