"""``eventlog`` storage backend: C++ append-only event log.

The scale-out EVENTDATA tier — the role HBase plays in the reference
(conf/pio-env.sh.template:43 makes HBase the default event store; scans
come from hbase/HBEventsUtil.scala:286 partial-rowkey + column filters).
Events live in a native append-only log with an in-memory index
(predictionio_tpu/native/eventlog.cpp); metadata/model repositories
delegate to the localfs backend rooted at the same path, mirroring how
the reference pairs HBase (events) with Elasticsearch (metadata).

Config (PIO_STORAGE_SOURCES_<NAME>_*):
  TYPE=eventlog
  PATH=<base dir>         (default ~/.pio_store/eventlog)
  FSYNC=1                 (optional: fdatasync per append batch)
"""

from __future__ import annotations

import ctypes
import datetime as _dt
import hashlib
import json
import os
import shutil
import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

from predictionio_tpu.data import storage as S
from predictionio_tpu.data.backends.localfs import LocalFSStorageClient
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event

UTC = _dt.timezone.utc
_EPOCH = _dt.datetime(1970, 1, 1, tzinfo=UTC)
_US = _dt.timedelta(microseconds=1)
_I64_MIN = -(2**63)
_I64_MAX = 2**63 - 1
_ABSENT = 0xFFFF


class _FindReq(ctypes.Structure):
    _fields_ = [
        ("start_us", ctypes.c_int64),
        ("until_us", ctypes.c_int64),
        ("entity_type", ctypes.c_char_p),
        ("entity_id", ctypes.c_char_p),
        ("target_type_mode", ctypes.c_int32),
        ("target_id_mode", ctypes.c_int32),
        ("target_entity_type", ctypes.c_char_p),
        ("target_entity_id", ctypes.c_char_p),
        ("event_names", ctypes.c_char_p),
        ("n_event_names", ctypes.c_int32),
        ("reversed", ctypes.c_int32),
        ("limit", ctypes.c_int64),
    ]


def _load():
    from predictionio_tpu import native

    lib = native.load_library("eventlog")
    lib.el_open.restype = ctypes.c_void_p
    lib.el_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.el_close.argtypes = [ctypes.c_void_p]
    lib.el_count.restype = ctypes.c_int64
    lib.el_count.argtypes = [ctypes.c_void_p]
    lib.el_append_batch.restype = ctypes.c_int64
    lib.el_append_batch.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
    lib.el_delete.restype = ctypes.c_int
    lib.el_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.el_get.restype = ctypes.c_int64
    lib.el_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
    lib.el_find.restype = ctypes.c_int64
    lib.el_find.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(_FindReq),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.el_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    return lib


# ---------------------------------------------------------------------------
# record (de)serialization — wire format documented in eventlog.cpp
# ---------------------------------------------------------------------------

def _id16(event_id: str) -> bytes:
    """32-hex ids (the framework's uuid4().hex) map to their raw bytes;
    anything else maps through MD5 — same trick as the reference's
    rowkey MD5(entityType-entityId) (HBEventsUtil.scala:96)."""
    try:
        raw = bytes.fromhex(event_id)
        if len(raw) == 16:
            return raw
    except ValueError:
        pass
    return hashlib.md5(event_id.encode("utf-8")).digest()


def _us(t: _dt.datetime) -> int:
    return (t.astimezone(UTC) - _EPOCH) // _US


def _pack(e: Event) -> bytes:
    # extra carries everything the filterable header doesn't: properties,
    # tags, prId, exact ISO times (tz offsets survive the round trip),
    # and the original id when it isn't canonical 16-byte hex
    extra: Dict[str, Any] = {
        "et": e.event_time.isoformat(),
        "ct": e.creation_time.isoformat(),
    }
    if len(e.properties):
        extra["p"] = e.properties.to_dict()
    if e.tags:
        extra["t"] = list(e.tags)
    if e.pr_id is not None:
        extra["pr"] = e.pr_id
    id16 = _id16(e.event_id)
    if id16.hex() != e.event_id:
        extra["id"] = e.event_id
    extra_b = json.dumps(extra, separators=(",", ":")).encode("utf-8")

    ev = e.event.encode("utf-8")
    et = e.entity_type.encode("utf-8")
    ei = e.entity_id.encode("utf-8")
    tt = e.target_entity_type.encode("utf-8") if e.target_entity_type is not None else None
    ti = e.target_entity_id.encode("utf-8") if e.target_entity_id is not None else None

    body = struct.pack(
        "<16sqqHHHHHI",
        id16,
        _us(e.event_time),
        _us(e.creation_time),
        len(ev),
        len(et),
        len(ei),
        _ABSENT if tt is None else len(tt),
        _ABSENT if ti is None else len(ti),
        len(extra_b),
    ) + ev + et + ei + (tt or b"") + (ti or b"") + extra_b
    return struct.pack("<I", len(body)) + body


def _unpack_records(buf: bytes) -> List[Event]:
    events = []
    off = 0
    n = len(buf)
    while off + 4 <= n:
        (rlen,) = struct.unpack_from("<I", buf, off)
        off += 4
        id16, t_us, c_us, l_ev, l_et, l_ei, l_tt, l_ti, l_ex = struct.unpack_from(
            "<16sqqHHHHHI", buf, off
        )
        p = off + 46
        ev = buf[p : p + l_ev].decode("utf-8"); p += l_ev
        et = buf[p : p + l_et].decode("utf-8"); p += l_et
        ei = buf[p : p + l_ei].decode("utf-8"); p += l_ei
        if l_tt != _ABSENT:
            tt = buf[p : p + l_tt].decode("utf-8"); p += l_tt
        else:
            tt = None
        if l_ti != _ABSENT:
            ti = buf[p : p + l_ti].decode("utf-8"); p += l_ti
        else:
            ti = None
        extra = json.loads(buf[p : p + l_ex].decode("utf-8")) if l_ex else {}
        off += rlen

        event_time = (
            _dt.datetime.fromisoformat(extra["et"])
            if "et" in extra
            else _EPOCH + t_us * _US
        )
        creation_time = (
            _dt.datetime.fromisoformat(extra["ct"])
            if "ct" in extra
            else _EPOCH + c_us * _US
        )
        events.append(
            Event(
                event=ev,
                entity_type=et,
                entity_id=ei,
                target_entity_type=tt,
                target_entity_id=ti,
                properties=DataMap(extra.get("p") or {}),
                event_time=event_time,
                tags=tuple(extra.get("t") or ()),
                pr_id=extra.get("pr"),
                event_id=extra.get("id") or id16.hex(),
                creation_time=creation_time,
            )
        )
    return events


# ---------------------------------------------------------------------------
# EventStore over the native log
# ---------------------------------------------------------------------------

class EventLogEventStore(S.EventStore):
    def __init__(self, base_path: str, fsync: bool = False):
        self._lib = _load()
        self._base = base_path
        self._fsync = fsync
        self._handles: Dict[Tuple[int, Optional[int]], int] = {}
        self._lock = threading.Lock()
        os.makedirs(base_path, exist_ok=True)

    def _dir(self, app_id: int, channel_id: Optional[int]) -> str:
        name = f"events_{app_id}" if channel_id is None else f"events_{app_id}_{channel_id}"
        return os.path.join(self._base, name)

    def _handle(self, app_id: int, channel_id: Optional[int], create: bool = False) -> int:
        key = (app_id, channel_id)
        with self._lock:
            h = self._handles.get(key)
            if h:
                return h
            path = self._dir(app_id, channel_id)
            if not create and not os.path.isdir(path):
                raise S.StorageError(
                    f"event log for app {app_id} channel {channel_id} not initialized"
                )
            h = self._lib.el_open(path.encode(), 1 if self._fsync else 0)
            if not h:
                raise S.StorageError(
                    f"cannot open event log at {path} (is another process "
                    "holding its LOCK? concurrent access goes through the "
                    "event server REST API)"
                )
            self._handles[key] = h
            return h

    def init(self, app_id, channel_id=None):
        self._handle(app_id, channel_id, create=True)

    def remove(self, app_id, channel_id=None):
        key = (app_id, channel_id)
        with self._lock:
            h = self._handles.pop(key, None)
            if h:
                self._lib.el_close(h)
            shutil.rmtree(self._dir(app_id, channel_id), ignore_errors=True)

    def insert(self, event: Event, app_id, channel_id=None) -> str:
        return self.insert_batch([event], app_id, channel_id)[0]

    def insert_batch(self, events, app_id, channel_id=None) -> List[str]:
        h = self._handle(app_id, channel_id)
        out_ids: List[str] = []
        parts: List[bytes] = []
        for e in events:
            e = e if e.event_id else e.with_id()
            out_ids.append(e.event_id)
            parts.append(_pack(e))
        buf = b"".join(parts)
        n = self._lib.el_append_batch(h, buf, len(buf))
        if n != len(events):
            raise S.StorageError(f"append failed ({n} of {len(events)} written)")
        return out_ids

    def get(self, event_id, app_id, channel_id=None) -> Optional[Event]:
        h = self._handle(app_id, channel_id)
        out = ctypes.POINTER(ctypes.c_uint8)()
        nbytes = self._lib.el_get(h, _id16(event_id), ctypes.byref(out))
        if nbytes <= 0:
            return None
        try:
            buf = ctypes.string_at(out, nbytes)
        finally:
            self._lib.el_free(out)
        events = _unpack_records(buf)
        return events[0] if events else None

    def delete(self, event_id, app_id, channel_id=None) -> bool:
        h = self._handle(app_id, channel_id)
        return self._lib.el_delete(h, _id16(event_id)) == 1

    def find(
        self,
        app_id,
        channel_id=None,
        start_time=None,
        until_time=None,
        entity_type=None,
        entity_id=None,
        event_names=None,
        target_entity_type=S.UNSET,
        target_entity_id=S.UNSET,
        limit=None,
        reversed=False,
    ) -> List[Event]:
        h = self._handle(app_id, channel_id)

        def target_mode(v) -> Tuple[int, Optional[bytes]]:
            if v is S.UNSET:
                return 0, None
            if v is None:
                return 1, None
            return 2, str(v).encode("utf-8")

        tt_mode, tt_val = target_mode(target_entity_type)
        ti_mode, ti_val = target_mode(target_entity_id)
        names = list(event_names) if event_names is not None else []

        req = _FindReq(
            start_us=_us(start_time) if start_time is not None else _I64_MIN,
            until_us=_us(until_time) if until_time is not None else _I64_MAX,
            entity_type=entity_type.encode() if entity_type is not None else None,
            entity_id=entity_id.encode() if entity_id is not None else None,
            target_type_mode=tt_mode,
            target_id_mode=ti_mode,
            target_entity_type=tt_val,
            target_entity_id=ti_val,
            event_names=b"\0".join(n.encode() for n in names) + b"\0" if names else None,
            n_event_names=len(names),
            reversed=1 if reversed else 0,
            limit=limit if limit is not None and limit >= 0 else -1,
        )
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_bytes = ctypes.c_uint64()
        n = self._lib.el_find(h, ctypes.byref(req), ctypes.byref(out), ctypes.byref(out_bytes))
        if n < 0:
            raise S.StorageError("find failed in native event log")
        if n == 0:
            return []
        try:
            buf = ctypes.string_at(out, out_bytes.value)
        finally:
            self._lib.el_free(out)
        return _unpack_records(buf)

    def close(self) -> None:
        with self._lock:
            for h in self._handles.values():
                self._lib.el_close(h)
            self._handles.clear()


class EventLogStorageClient(S.StorageClient):
    """events → native log; metadata/models → localfs at the same root
    (the HBase-for-events + ES-for-metadata pairing, single-binary)."""

    def __init__(self, config: Dict[str, str]):
        super().__init__(config)
        base = os.path.expanduser(
            config.get("PATH", os.path.join("~", ".pio_store", "eventlog"))
        )
        self._events = EventLogEventStore(
            os.path.join(base, "events"), fsync=config.get("FSYNC", "0") == "1"
        )
        self._meta = LocalFSStorageClient({"PATH": os.path.join(base, "meta")})

    def events(self):
        return self._events

    def apps(self):
        return self._meta.apps()

    def access_keys(self):
        return self._meta.access_keys()

    def channels(self):
        return self._meta.channels()

    def engine_manifests(self):
        return self._meta.engine_manifests()

    def engine_instances(self):
        return self._meta.engine_instances()

    def evaluation_instances(self):
        return self._meta.evaluation_instances()

    def models(self):
        return self._meta.models()


S.register_backend("eventlog", EventLogStorageClient)
