"""``eventlog`` storage backend: C++ append-only event log.

The scale-out EVENTDATA tier — the role HBase plays in the reference
(conf/pio-env.sh.template:43 makes HBase the default event store; scans
come from hbase/HBEventsUtil.scala:286 partial-rowkey + column filters).
Events live in a native append-only log with an in-memory index
(predictionio_tpu/native/eventlog.cpp); metadata/model repositories
delegate to the localfs backend rooted at the same path, mirroring how
the reference pairs HBase (events) with Elasticsearch (metadata).

Config (PIO_STORAGE_SOURCES_<NAME>_*):
  TYPE=eventlog
  PATH=<base dir>         (default ~/.pio_store/eventlog)
  FSYNC=1                 (optional: fdatasync per append batch)
"""

from __future__ import annotations

import ctypes
import datetime as _dt
import hashlib
import json
import os
import shutil
import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

from predictionio_tpu import native as native_mod
from predictionio_tpu.data import storage as S
from predictionio_tpu.data.backends.localfs import LocalFSStorageClient
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event

UTC = _dt.timezone.utc
_EPOCH = _dt.datetime(1970, 1, 1, tzinfo=UTC)
_US = _dt.timedelta(microseconds=1)
_I64_MIN = -(2**63)
_I64_MAX = 2**63 - 1
_ABSENT = 0xFFFF


#: binlayout::CSide mirror (shared with ops/ragged via native.CSide)
_CSide = native_mod.CSide


class _BinColumnarOut(ctypes.Structure):
    """Mirror of BinColumnarOut (eventlog.cpp el_bin_columnar)."""

    _fields_ = [
        ("user_side", _CSide),
        ("item_side", _CSide),
        ("ent_dict", ctypes.c_void_p),
        ("ent_offsets", ctypes.c_void_p),
        ("tgt_dict", ctypes.c_void_p),
        ("tgt_offsets", ctypes.c_void_p),
        ("hold_u", ctypes.c_void_p),
        ("hold_i", ctypes.c_void_p),
        ("hold_v", ctypes.c_void_p),
        ("ent_dict_bytes", ctypes.c_uint64),
        ("tgt_dict_bytes", ctypes.c_uint64),
        ("n_ent", ctypes.c_int64),
        ("n_tgt", ctypes.c_int64),
        ("n_hold", ctypes.c_int64),
        ("n_rows", ctypes.c_int64),
        ("scan_sec", ctypes.c_double),
        ("bin_sec", ctypes.c_double),
    ]


class _FindReq(ctypes.Structure):
    _fields_ = [
        ("start_us", ctypes.c_int64),
        ("until_us", ctypes.c_int64),
        ("entity_type", ctypes.c_char_p),
        ("entity_id", ctypes.c_char_p),
        ("target_type_mode", ctypes.c_int32),
        ("target_id_mode", ctypes.c_int32),
        ("target_entity_type", ctypes.c_char_p),
        ("target_entity_id", ctypes.c_char_p),
        ("event_names", ctypes.c_char_p),
        ("n_event_names", ctypes.c_int32),
        ("reversed", ctypes.c_int32),
        ("limit", ctypes.c_int64),
    ]


def _load():
    from predictionio_tpu import native

    lib = native.load_library("eventlog")
    lib.el_open.restype = ctypes.c_void_p
    lib.el_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.el_close.argtypes = [ctypes.c_void_p]
    lib.el_count.restype = ctypes.c_int64
    lib.el_count.argtypes = [ctypes.c_void_p]
    lib.el_append_batch.restype = ctypes.c_int64
    lib.el_append_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int32,
    ]
    lib.el_delete.restype = ctypes.c_int
    lib.el_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.el_compact.restype = ctypes.c_int64
    lib.el_compact.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.el_get.restype = ctypes.c_int64
    lib.el_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
    lib.el_find.restype = ctypes.c_int64
    lib.el_find.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(_FindReq),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.el_find_columnar.restype = ctypes.c_int64
    lib.el_find_columnar.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(_FindReq), ctypes.c_char_p,
        ctypes.c_int32,                                   # time_ordered
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),   # ent codes
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),   # tgt codes
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),   # name codes
        ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),  # values
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),   # times_us
        ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64)),  # ent dict offsets
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64)),  # tgt dict offsets
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64)),  # name dict offsets
    ]
    u8pp = ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))
    lib.el_append_json.restype = ctypes.c_int64
    lib.el_append_json.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.c_int64, ctypes.c_int32,
        u8pp, u8pp,
        u8pp, ctypes.POINTER(ctypes.c_uint64),
        u8pp, ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.el_append_columnar.restype = ctypes.c_int64
    lib.el_append_columnar.argtypes = [
        ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64,
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64,
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_double), ctypes.c_char_p,
    ]
    lib.el_find_columnar_since.restype = ctypes.c_int64
    lib.el_find_columnar_since.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(_FindReq), ctypes.c_char_p,
        ctypes.c_uint64, ctypes.c_uint64,                 # since gen/rec
        ctypes.POINTER(ctypes.c_uint64),                  # out gen
        ctypes.POINTER(ctypes.c_uint64),                  # out rec
        ctypes.POINTER(ctypes.c_int32),                   # out rebased
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),   # ent codes
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),   # tgt codes
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),   # name codes
        ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),  # values
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),   # times_us
        ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64)),  # ent dict offsets
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64)),  # tgt dict offsets
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64)),  # name dict offsets
    ]
    lib.el_fingerprint.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_uint64)]
    lib.el_fingerprint.restype = None
    lib.el_bin_columnar.restype = ctypes.c_int64
    lib.el_bin_columnar.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(_FindReq), ctypes.c_char_p,
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_double), ctypes.c_int32,
        ctypes.c_int64, ctypes.c_int64,                   # skip mod/rem
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,   # seg_len, max u/i
        ctypes.c_int64, ctypes.c_int64, ctypes.c_double,  # shards, block, cost
        ctypes.POINTER(_BinColumnarOut),
    ]
    lib.el_append_rows.restype = ctypes.c_int64
    u64p_ = ctypes.POINTER(ctypes.c_uint64)
    lib.el_append_rows.argtypes = [
        ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_char_p,                                  # ids n*16
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_char_p,                                  # flags
        ctypes.c_char_p, u64p_, ctypes.c_char_p, u64p_,   # ev, et
        ctypes.c_char_p, u64p_, ctypes.c_char_p, u64p_,   # ei, tt
        ctypes.c_char_p, u64p_, ctypes.c_char_p, u64p_,   # ti, extra
        ctypes.c_int32,                                   # fresh_ids
    ]
    lib.el_free.argtypes = [ctypes.c_void_p]
    return lib


# ---------------------------------------------------------------------------
# record (de)serialization — wire format documented in eventlog.cpp
# ---------------------------------------------------------------------------

def _id16(event_id: str) -> bytes:
    """32-hex ids (the framework's uuid4().hex) map to their raw bytes;
    anything else maps through MD5 — same trick as the reference's
    rowkey MD5(entityType-entityId) (HBEventsUtil.scala:96)."""
    try:
        raw = bytes.fromhex(event_id)
        if len(raw) == 16:
            return raw
    except ValueError:
        pass
    return hashlib.md5(event_id.encode("utf-8")).digest()


def _us(t: _dt.datetime) -> int:
    # aware-datetime subtraction already accounts for the offset;
    # astimezone() would only burn ~1us per call on the write hot path.
    # Naive times (query filters from callers) are treated as UTC,
    # matching the sqlite backend's normalization.
    if t.tzinfo is None:
        t = t.replace(tzinfo=UTC)
    return (t - _EPOCH) // _US


def _extra_bytes(e: Event, orig_id: Optional[str]) -> bytes:
    """The record's JSON ``extra`` blob: everything the filterable
    header doesn't carry — properties, tags, prId, exact ISO times when
    needed (tz offsets survive the round trip; a UTC time is exactly
    reconstructed from the micros header, so the common case skips both
    isoformats and shrinks the JSON — the row write lane is
    latency-sensitive), and the original id when it isn't canonical
    16-byte hex. The ONE implementation behind both the legacy _pack
    and the vectorized insert_batch fast lane."""
    extra: Dict[str, Any] = {}
    if e.event_time.utcoffset():
        extra["et"] = e.event_time.isoformat()
    if e.creation_time.utcoffset():
        extra["ct"] = e.creation_time.isoformat()
    if len(e.properties):
        extra["p"] = e.properties.to_dict()
    if e.tags:
        extra["t"] = list(e.tags)
    if e.pr_id is not None:
        extra["pr"] = e.pr_id
    if orig_id is not None:
        extra["id"] = orig_id
    if not extra:
        return b""
    if len(extra) == 1 and "p" in extra:
        # the dominant live-lane shape: properties only — and within
        # it, the single-numeric-property case ({"rating": 4.5}) is hot
        # enough that skipping json.dumps is worth a guarded formatter
        p = extra["p"]
        if len(p) == 1:
            k, v = next(iter(p.items()))
            tv = type(v)
            if ((tv is float and v == v and v not in (_INF, _NINF))
                    or tv is int) and _plain_key(k):
                return f'{{"p":{{"{k}":{v!r}}}}}'.encode("utf-8")
        return b'{"p":' + json.dumps(
            p, separators=(",", ":")
        ).encode("utf-8") + b"}"
    return json.dumps(extra, separators=(",", ":")).encode("utf-8")


_INF = float("inf")
_NINF = float("-inf")


def _plain_key(k: str) -> bool:
    """Key needs no JSON escaping (ascii, printable, no quote/backslash)
    — the guard on the formatter fast path above."""
    return (type(k) is str and k.isascii() and k.isprintable()
            and '"' not in k and "\\" not in k)


def _pack(e: Event, id16: Optional[bytes] = None) -> bytes:
    """One wire record. ``id16``: pre-derived raw id (callers that
    generate ids pass it); None derives it from e.event_id."""
    t_us = _us(e.event_time)
    c_us = _us(e.creation_time)
    orig_id = None
    if id16 is None:
        id16 = _id16(e.event_id)
        if id16.hex() != e.event_id:
            orig_id = e.event_id
    extra_b = _extra_bytes(e, orig_id)

    ev = e.event.encode("utf-8")
    et = e.entity_type.encode("utf-8")
    ei = e.entity_id.encode("utf-8")
    tt = e.target_entity_type.encode("utf-8") if e.target_entity_type is not None else None
    ti = e.target_entity_id.encode("utf-8") if e.target_entity_id is not None else None

    body = struct.pack(
        "<16sqqHHHHHI",
        id16,
        t_us,
        c_us,
        len(ev),
        len(et),
        len(ei),
        _ABSENT if tt is None else len(tt),
        _ABSENT if ti is None else len(ti),
        len(extra_b),
    ) + ev + et + ei + (tt or b"") + (ti or b"") + extra_b
    return struct.pack("<I", len(body)) + body


def _unpack_records(buf: bytes) -> List[Event]:
    events = []
    off = 0
    n = len(buf)
    while off + 4 <= n:
        (rlen,) = struct.unpack_from("<I", buf, off)
        off += 4
        id16, t_us, c_us, l_ev, l_et, l_ei, l_tt, l_ti, l_ex = struct.unpack_from(
            "<16sqqHHHHHI", buf, off
        )
        p = off + 46
        ev = buf[p : p + l_ev].decode("utf-8"); p += l_ev
        et = buf[p : p + l_et].decode("utf-8"); p += l_et
        ei = buf[p : p + l_ei].decode("utf-8"); p += l_ei
        if l_tt != _ABSENT:
            tt = buf[p : p + l_tt].decode("utf-8"); p += l_tt
        else:
            tt = None
        if l_ti != _ABSENT:
            ti = buf[p : p + l_ti].decode("utf-8"); p += l_ti
        else:
            ti = None
        extra = json.loads(buf[p : p + l_ex].decode("utf-8")) if l_ex else {}
        off += rlen

        event_time = (
            _dt.datetime.fromisoformat(extra["et"])
            if "et" in extra
            else _EPOCH + t_us * _US
        )
        creation_time = (
            _dt.datetime.fromisoformat(extra["ct"])
            if "ct" in extra
            else _EPOCH + c_us * _US
        )
        events.append(
            Event(
                event=ev,
                entity_type=et,
                entity_id=ei,
                target_entity_type=tt,
                target_entity_id=ti,
                properties=DataMap(extra.get("p") or {}),
                event_time=event_time,
                tags=tuple(extra.get("t") or ()),
                pr_id=extra.get("pr"),
                event_id=extra.get("id") or id16.hex(),
                creation_time=creation_time,
            )
        )
    return events


def _decode_vocab(ptr, nbytes: int, offs_ptr, count: int) -> List[str]:
    """Native dictionary -> vocabulary list: concatenated bytes + exact
    prefix offsets (the separator-free layout of DictEncoder.dump; ids
    may contain ANY byte). The ONE ctypes-side decoder, shared by the
    columnar reads and the binned lane."""
    if not count:
        return []
    raw = ctypes.string_at(ptr, nbytes)
    offs = ctypes.cast(offs_ptr, ctypes.POINTER(ctypes.c_uint64))
    return [raw[offs[i]:offs[i + 1]].decode("utf-8")
            for i in range(count)]


class JsonRowsUnsupported(Exception):
    """The JSON payload uses a construct the native fast lane does not
    handle (caller-stamped ids, exotic time formats, escaped property
    keys, non-object properties, …) — the caller falls back to the
    per-row Python path, which accepts everything."""


#: native RowErr codes -> the validate_event / from_dict message shapes
#: (data/event.py) — kept in lockstep with enum RowErr in eventlog.cpp
_ROW_ERRORS = {
    1: "field event is required",
    2: "field entityType is required",
    3: "field entityId is required",
    4: "event must not be empty.",
    5: "entityType must not be empty string.",
    6: "entityId must not be empty string.",
    7: "targetEntityType and targetEntityId must be specified together.",
    8: "targetEntityType must not be empty string.",
    9: "targetEntityId must not be empty string.",
    10: "properties cannot be empty for $unset event",
    11: "reserved event names must be one of $set/$unset/$delete.",
    12: "Reserved events cannot have targetEntity.",
    13: "The entityType is not allowed. 'pio_' is a reserved name prefix.",
    14: "The targetEntityType is not allowed. 'pio_' is a reserved name prefix.",
    15: "The property is not allowed. 'pio_' is a reserved name prefix.",
    16: "Invalid time string.",
    17: "event must be a JSON object",
    18: "a string field exceeds the 65534-byte wire-format limit",
}


class _ColumnarOut:
    """The columnar out-params of ``el_find_columnar[_since]`` plus the
    unpack/free plumbing both lanes share: 5 row arrays, 3 dictionaries
    with exact prefix offsets, and their counts."""

    def __init__(self, lib):
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        self._lib = lib
        self.ent = ctypes.POINTER(ctypes.c_int32)()
        self.tgt = ctypes.POINTER(ctypes.c_int32)()
        self.nam = ctypes.POINTER(ctypes.c_int32)()
        self.val = ctypes.POINTER(ctypes.c_double)()
        self.tim = ctypes.POINTER(ctypes.c_int64)()
        self.ent_d, self.tgt_d, self.nam_d = u8p(), u8p(), u8p()
        self.ent_db = ctypes.c_uint64()
        self.tgt_db = ctypes.c_uint64()
        self.nam_db = ctypes.c_uint64()
        self.n_ent, self.n_tgt, self.n_nam = (
            ctypes.c_int64(), ctypes.c_int64(), ctypes.c_int64())
        self.ent_o, self.tgt_o, self.nam_o = u64p(), u64p(), u64p()

    def argrefs(self):
        return tuple(ctypes.byref(p) for p in (
            self.ent, self.tgt, self.nam, self.val, self.tim,
            self.ent_d, self.ent_db, self.n_ent,
            self.tgt_d, self.tgt_db, self.n_tgt,
            self.nam_d, self.nam_db, self.n_nam,
            self.ent_o, self.tgt_o, self.nam_o))

    def take(self, n: int) -> S.EventColumns:
        """Copy the native buffers into a Python-owned EventColumns and
        free them (always frees, even when the copy raises)."""
        import numpy as np

        def arr(ptr, ctype, count, np_dtype):
            a = np.ctypeslib.as_array(
                ctypes.cast(ptr, ctypes.POINTER(ctype)), shape=(count,)
            ).copy() if count else np.empty(0, np_dtype)
            return a.astype(np_dtype, copy=False)

        try:
            return S.EventColumns(
                entity_codes=arr(self.ent, ctypes.c_int32, n, np.int32),
                target_codes=arr(self.tgt, ctypes.c_int32, n, np.int32),
                name_codes=arr(self.nam, ctypes.c_int32, n, np.int32),
                values=arr(self.val, ctypes.c_double, n, np.float64),
                times_us=arr(self.tim, ctypes.c_int64, n, np.int64),
                entity_vocab=_decode_vocab(self.ent_d, self.ent_db.value,
                                           self.ent_o, self.n_ent.value),
                target_vocab=_decode_vocab(self.tgt_d, self.tgt_db.value,
                                           self.tgt_o, self.n_tgt.value),
                names=_decode_vocab(self.nam_d, self.nam_db.value,
                                    self.nam_o, self.n_nam.value),
            )
        finally:
            self.free()

    def free(self) -> None:
        for p in (self.ent, self.tgt, self.nam, self.val, self.tim,
                  self.ent_d, self.tgt_d, self.nam_d,
                  self.ent_o, self.tgt_o, self.nam_o):
            if p:
                self._lib.el_free(p)


# ---------------------------------------------------------------------------
# EventStore over the native log
# ---------------------------------------------------------------------------

class EventLogEventStore(S.EventStore):
    def __init__(self, base_path: str, fsync: bool = False):
        self._lib = _load()
        self._base = base_path
        self._fsync = fsync
        self._handles: Dict[Tuple[int, Optional[int]], int] = {}
        self._lock = threading.Lock()
        os.makedirs(base_path, exist_ok=True)

    def _dir(self, app_id: int, channel_id: Optional[int]) -> str:
        name = f"events_{app_id}" if channel_id is None else f"events_{app_id}_{channel_id}"
        return os.path.join(self._base, name)

    def _handle(self, app_id: int, channel_id: Optional[int], create: bool = False) -> int:
        key = (app_id, channel_id)
        with self._lock:
            h = self._handles.get(key)
            if h:
                return h
            path = self._dir(app_id, channel_id)
            if not create and not os.path.isdir(path):
                raise S.StorageError(
                    f"event log for app {app_id} channel {channel_id} not initialized"
                )
            h = self._lib.el_open(path.encode(), 1 if self._fsync else 0)
            if not h:
                raise S.StorageError(
                    f"cannot open event log at {path} (is another process "
                    "holding its LOCK? concurrent access goes through the "
                    "event server REST API)"
                )
            self._handles[key] = h
            return h

    def init(self, app_id, channel_id=None):
        self._handle(app_id, channel_id, create=True)

    def remove(self, app_id, channel_id=None):
        key = (app_id, channel_id)
        with self._lock:
            h = self._handles.pop(key, None)
            if h:
                self._lib.el_close(h)
            shutil.rmtree(self._dir(app_id, channel_id), ignore_errors=True)

    def insert(self, event: Event, app_id, channel_id=None) -> str:
        # observation stays OFF: the event server's 201 lane already
        # observed this event at full fidelity (and single DAO writes
        # below the server are not observed by contract)
        return self.insert_batch([event], app_id, channel_id,
                                 _observe=False)[0]

    def insert_batch(self, events, app_id, channel_id=None, *,
                     _observe: bool = True) -> List[str]:
        """Row-lane bulk append, vectorized (the r03 30x gap fix): one
        Python pass collects per-field byte streams, numpy assembles
        the offset tables, and ONE native call (el_append_rows) packs
        every wire record and appends under a single lock with the GIL
        released — no per-row struct.pack, no per-row record join.
        Freshness-clock and fingerprint semantics are identical to the
        old per-row pack: ids minted here keep the lazy id index
        (fresh), caller-stamped ids pay the dup check, and one
        note_ingest covers the accepted batch."""
        import numpy as np

        h = self._handle(app_id, channel_id)
        events = list(events)
        n = len(events)
        if n == 0:
            return []
        rand = os.urandom(16 * n)
        ids = bytearray(rand)
        out_ids: List[str] = []
        fresh = True  # every id generated right here -> lazy id index
        times = np.empty(n, np.int64)
        ctimes = np.empty(n, np.int64)
        flags = bytearray(n)
        ev_p: List[bytes] = []
        et_p: List[bytes] = []
        ei_p: List[bytes] = []
        tt_p: List[bytes] = []
        ti_p: List[bytes] = []
        ex_p: List[bytes] = []
        empty = b""
        for i, e in enumerate(events):
            orig_id = None
            if e.event_id:
                fresh = False
                id16 = _id16(e.event_id)
                if id16.hex() != e.event_id:
                    orig_id = e.event_id
                ids[16 * i:16 * i + 16] = id16
                out_ids.append(e.event_id)
            else:
                out_ids.append(rand[16 * i:16 * i + 16].hex())
            times[i] = _us(e.event_time)
            ctimes[i] = _us(e.creation_time)
            ev_p.append(e.event.encode("utf-8"))
            et_p.append(e.entity_type.encode("utf-8"))
            ei_p.append(e.entity_id.encode("utf-8"))
            f = 0
            if e.target_entity_type is not None:
                tt_p.append(e.target_entity_type.encode("utf-8"))
                f |= 1
            else:
                tt_p.append(empty)
            if e.target_entity_id is not None:
                ti_p.append(e.target_entity_id.encode("utf-8"))
                f |= 2
            else:
                ti_p.append(empty)
            flags[i] = f
            ex_p.append(_extra_bytes(e, orig_id))

        def stream(parts):
            offs = np.zeros(n + 1, np.uint64)
            np.cumsum(np.fromiter(map(len, parts), np.uint64, count=n),
                      out=offs[1:])
            return b"".join(parts), offs

        ev_b, ev_o = stream(ev_p)
        et_b, et_o = stream(et_p)
        ei_b, ei_o = stream(ei_p)
        tt_b, tt_o = stream(tt_p)
        ti_b, ti_o = stream(ti_p)
        ex_b, ex_o = stream(ex_p)

        def optr(a):
            return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))

        rc = self._lib.el_append_rows(
            h, n, bytes(ids),
            times.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctimes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            bytes(flags),
            ev_b, optr(ev_o), et_b, optr(et_o), ei_b, optr(ei_o),
            tt_b, optr(tt_o), ti_b, optr(ti_o), ex_b, optr(ex_o),
            1 if fresh else 0,
        )
        if rc == -2:
            raise S.StorageError(
                "a string field exceeds the 65534-byte wire-format limit")
        if rc != n:
            raise S.StorageError(f"append failed ({rc} of {n} written)")
        # freshness clock: these rows now wait for a model publish
        from predictionio_tpu.obs import dataobs, perfacct

        perfacct.note_ingest()
        if _observe and dataobs.DATAOBS.enabled():
            # enqueue-only (the worker sketches): the lane hands over
            # the byte streams it already built, plus the extra-record
            # lengths as the payload-size proxy
            dataobs.DATAOBS.observe_batch(
                app_id, ev_p, entity_ids=ei_p, target_ids=ti_p,
                payload_lens=np.diff(ex_o.astype(np.int64)),
                events=events)
        return out_ids

    def insert_json_batch(
        self,
        raw: bytes,
        app_id,
        channel_id=None,
        *,
        strict: bool = True,
    ):
        """The native live lane (VERDICT r3 item 3): the API-format JSON
        array the event server receives goes straight to C++ — parse,
        EventValidation, wire-record packing and the append happen in
        one call with the GIL released; no per-row Python objects exist
        anywhere (the role of EventAPI's request pipeline,
        data/.../api/EventAPI.scala:209).

        Returns ``(ids, codes, names, entity_types)`` — per row: the
        event id hex (None for a failed row), the validation code (0 =
        appended; _ROW_ERRORS maps the rest), the event name and entity
        type (stats + whitelist checks). ``strict=True`` (the DAO bulk
        contract) raises on the first invalid row with NOTHING appended;
        ``strict=False`` (the batch API route) appends the valid rows
        and reports the rest. Raises JsonRowsUnsupported when the
        payload needs the Python path."""
        h = self._handle(app_id, channel_id)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        out_ids, out_codes, out_names, out_et = u8p(), u8p(), u8p(), u8p()
        names_b, et_b = ctypes.c_uint64(), ctypes.c_uint64()
        out_n = ctypes.c_int64()
        now_us = _us(_dt.datetime.now(tz=UTC))
        rc = self._lib.el_append_json(
            h, raw, len(raw), now_us, 0 if not strict else 1,
            ctypes.byref(out_ids), ctypes.byref(out_codes),
            ctypes.byref(out_names), ctypes.byref(names_b),
            ctypes.byref(out_et), ctypes.byref(et_b),
            ctypes.byref(out_n),
        )
        try:
            if rc == -2:
                raise JsonRowsUnsupported()
            if rc == -3:
                # a CLIENT error (the Python lane's json.loads would
                # refuse the body too) — ValueError so callers can map
                # it to 400 while I/O failures (StorageError below)
                # stay 500-shaped
                raise ValueError("malformed JSON event array")
            if rc == -4:
                n = out_n.value
                code = ctypes.string_at(out_codes, n)[-1] if out_codes else 0
                raise S.RowValidationError(
                    f"event {n - 1}: "
                    f"{_ROW_ERRORS.get(code, f'validation error {code}')}"
                )
            if rc < 0:
                raise S.StorageError("append failed in native event log")
            n = out_n.value
            ids_raw = ctypes.string_at(out_ids, 16 * n) if n else b""
            codes = list(ctypes.string_at(out_codes, n)) if n else []
            names = (ctypes.string_at(out_names, names_b.value)
                     .decode("utf-8").split("\0")[:-1] if n else [])
            etypes = (ctypes.string_at(out_et, et_b.value)
                      .decode("utf-8").split("\0")[:-1] if n else [])
        finally:
            for p in (out_ids, out_codes, out_names, out_et):
                if p:
                    self._lib.el_free(p)
        hex_all = ids_raw.hex()
        ids = [
            hex_all[32 * i:32 * i + 32] if codes[i] == 0 else None
            for i in range(n)
        ]
        if any(c == 0 for c in codes):
            from predictionio_tpu.obs import dataobs, perfacct

            perfacct.note_ingest()
            if dataobs.DATAOBS.enabled():
                # the native lane surfaces names only (ids never
                # become Python objects); count the accepted rows
                dataobs.DATAOBS.observe_batch(
                    app_id,
                    [nm for nm, c in zip(names, codes) if c == 0])
        return ids, codes, names, etypes

    def get(self, event_id, app_id, channel_id=None) -> Optional[Event]:
        h = self._handle(app_id, channel_id)
        out = ctypes.POINTER(ctypes.c_uint8)()
        nbytes = self._lib.el_get(h, _id16(event_id), ctypes.byref(out))
        if nbytes <= 0:
            return None
        try:
            buf = ctypes.string_at(out, nbytes)
        finally:
            self._lib.el_free(out)
        events = _unpack_records(buf)
        return events[0] if events else None

    def delete(self, event_id, app_id, channel_id=None) -> bool:
        h = self._handle(app_id, channel_id)
        return self._lib.el_delete(h, _id16(event_id)) == 1

    @staticmethod
    def _build_req(start_time, until_time, entity_type, entity_id,
                   event_names, target_entity_type, target_entity_id,
                   limit, reversed) -> _FindReq:
        def target_mode(v) -> Tuple[int, Optional[bytes]]:
            if v is S.UNSET:
                return 0, None
            if v is None:
                return 1, None
            return 2, str(v).encode("utf-8")

        tt_mode, tt_val = target_mode(target_entity_type)
        ti_mode, ti_val = target_mode(target_entity_id)
        names = list(event_names) if event_names is not None else []
        return _FindReq(
            start_us=_us(start_time) if start_time is not None else _I64_MIN,
            until_us=_us(until_time) if until_time is not None else _I64_MAX,
            entity_type=entity_type.encode() if entity_type is not None else None,
            entity_id=entity_id.encode() if entity_id is not None else None,
            target_type_mode=tt_mode,
            target_id_mode=ti_mode,
            target_entity_type=tt_val,
            target_entity_id=ti_val,
            event_names=b"\0".join(n.encode() for n in names) + b"\0" if names else None,
            n_event_names=len(names),
            reversed=1 if reversed else 0,
            limit=limit if limit is not None and limit >= 0 else -1,
        )

    def find(
        self,
        app_id,
        channel_id=None,
        start_time=None,
        until_time=None,
        entity_type=None,
        entity_id=None,
        event_names=None,
        target_entity_type=S.UNSET,
        target_entity_id=S.UNSET,
        limit=None,
        reversed=False,
    ) -> List[Event]:
        h = self._handle(app_id, channel_id)
        req = self._build_req(start_time, until_time, entity_type, entity_id,
                              event_names, target_entity_type,
                              target_entity_id, limit, reversed)
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_bytes = ctypes.c_uint64()
        n = self._lib.el_find(h, ctypes.byref(req), ctypes.byref(out), ctypes.byref(out_bytes))
        if n < 0:
            raise S.StorageError("find failed in native event log")
        if n == 0:
            return []
        try:
            buf = ctypes.string_at(out, out_bytes.value)
        finally:
            self._lib.el_free(out)
        return _unpack_records(buf)

    def find_columnar(
        self,
        app_id,
        channel_id=None,
        value_property=None,
        time_ordered=True,
        shard_index=None,
        shard_count=None,
        **find_kwargs,
    ) -> S.EventColumns:
        """One native pass: filter + dict-encode + property extraction
        (overrides the Event-object fallback in storage.EventStore).
        ``time_ordered=False`` (bulk training reads) fuses filter and
        encode into a single parse per record and skips the sort.
        Entity-hash read shards (shard_index/shard_count) are applied as
        a vectorized post-filter on the encoded columns — the native
        scan still reads the whole log (it is local disk), but only the
        shard's rows are materialized as Python-owned arrays (and, via
        the storage server, only they travel the wire)."""
        S.EventStore.check_shard_params(shard_index, shard_count)
        sharding = shard_count is not None and shard_count > 1
        # shard filter precedes any row limit (find's order-then-
        # truncate semantics per shard): run the native scan unlimited,
        # filter, then limit_columns
        shard_limit = find_kwargs.pop("limit", None) if sharding else None
        unknown = set(find_kwargs) - {
            "start_time", "until_time", "entity_type", "entity_id",
            "event_names", "target_entity_type", "target_entity_id",
            "limit", "reversed",
        }
        if unknown:
            # a typo'd filter must fail loudly, never scan unfiltered
            raise TypeError(
                f"find_columnar() got unexpected filters {sorted(unknown)}"
            )
        h = self._handle(app_id, channel_id)
        req = self._build_req(
            find_kwargs.get("start_time"), find_kwargs.get("until_time"),
            find_kwargs.get("entity_type"), find_kwargs.get("entity_id"),
            find_kwargs.get("event_names"),
            find_kwargs.get("target_entity_type", S.UNSET),
            find_kwargs.get("target_entity_id", S.UNSET),
            find_kwargs.get("limit"), find_kwargs.get("reversed", False),
        )
        out = _ColumnarOut(self._lib)
        n = self._lib.el_find_columnar(
            h, ctypes.byref(req),
            value_property.encode() if value_property is not None else None,
            1 if time_ordered else 0,
            *out.argrefs(),
        )
        if n < 0:
            raise S.StorageError("columnar find failed in native event log")
        cols = out.take(n)
        if sharding:
            cols = S.shard_columns(cols, shard_index, shard_count)
            cols = S.limit_columns(
                cols, shard_limit,
                newest_first=bool(find_kwargs.get("reversed", False)))
        return cols

    # -- fused zero-copy bin lane -------------------------------------------
    _BIN_FILTERS = {
        "start_time", "until_time", "entity_type", "entity_id",
        "event_names", "target_entity_type", "target_entity_id",
    }

    def bin_columnar(
        self,
        app_id,
        channel_id=None,
        *,
        value_property: Optional[str] = None,
        overrides: Optional[Dict[str, float]] = None,
        skip_mod: int = 0,
        skip_rem: int = 0,
        seg_len="auto",
        max_len_user: Optional[int] = None,
        max_len_item: Optional[int] = None,
        n_shards: int = 1,
        block_size: int = 4096,
        row_cost_slots: float = 16.0,
        **find_kwargs,
    ) -> S.BinnedInteractions:
        """The fused ingest->bin lane: ONE native call takes the mmap'd
        log to both sides' device-ready compressed layouts (grouped by
        entity and by target), with the GIL released for the whole
        scan+bin. The returned arrays are ZERO-COPY views over aligned
        native buffers — hand them to ``jax.device_put`` as-is; their
        buffer objects anchor the allocation's lifetime.

        ``overrides`` maps event names to constant ratings (the "buy
        means 4.0" rule); other rows take ``value_property`` with
        NaN -> 0.0. ``skip_mod``/``skip_rem`` hold out every row whose
        kept-row ordinal % mod == rem as an evaluation COO (the bench's
        5%% split). Rows without a target id are dropped
        (read_interactions semantics). The layout is bit-identical to
        ``compress_side(build_segmented_groups(...))`` over the same
        COO — pinned by tests/test_bin_columnar.py."""
        unknown = set(find_kwargs) - self._BIN_FILTERS
        if unknown:
            raise TypeError(
                f"bin_columnar() got unexpected filters {sorted(unknown)}"
            )
        h = self._handle(app_id, channel_id)
        req = self._build_req(
            find_kwargs.get("start_time"), find_kwargs.get("until_time"),
            find_kwargs.get("entity_type"), find_kwargs.get("entity_id"),
            find_kwargs.get("event_names"),
            find_kwargs.get("target_entity_type", S.UNSET),
            find_kwargs.get("target_entity_id", S.UNSET),
            None, False,
        )
        ov = dict(overrides or {})
        ov_names = b"".join(k.encode("utf-8") + b"\0" for k in ov) or None
        ov_vals = ((ctypes.c_double * len(ov))(*[float(v) for v in ov.values()])
                   if ov else None)
        if isinstance(seg_len, str):
            if seg_len != "auto":
                raise ValueError(
                    f"seg_len must be an int or 'auto', got {seg_len!r}")
            seg_len_i = -1
        else:
            seg_len_i = int(seg_len)
        out = _BinColumnarOut()
        n = self._lib.el_bin_columnar(
            h, ctypes.byref(req),
            value_property.encode() if value_property is not None else None,
            ov_names, ov_vals, len(ov),
            int(skip_mod), int(skip_rem),
            seg_len_i,
            -1 if max_len_user is None else int(max_len_user),
            -1 if max_len_item is None else int(max_len_item),
            int(n_shards), int(block_size), float(row_cost_slots),
            ctypes.byref(out),
        )
        if n == -3:
            raise ValueError(
                "vocab exceeds the 24-bit index wire format (widen "
                "idx_hi before raising this cap)")
        if n < 0:
            raise S.StorageError(
                f"native columnar binning failed (rc {n})")

        # one owner per independently-released allocation group: the
        # SIDES are dropped by the trainer the moment the device owns
        # the bytes (_note_transfer), while a HOLDOUT COO typically
        # lives to the end of an evaluation — a shared owner would let
        # the small holdout views pin the multi-hundred-MB side buffers
        owner = native_mod.NativeOwner(self._lib.el_free, [])
        hold_owner = native_mod.NativeOwner(self._lib.el_free, [])

        def side(c: _CSide) -> S.BinnedSide:
            return S.BinnedSide(**native_mod.unpack_cside(c, owner))

        try:
            user_side = side(out.user_side)
            item_side = side(out.item_side)
            ent_vocab = _decode_vocab(out.ent_dict, out.ent_dict_bytes,
                                      out.ent_offsets, out.n_ent)
            tgt_vocab = _decode_vocab(out.tgt_dict, out.tgt_dict_bytes,
                                      out.tgt_offsets, out.n_tgt)
            holdout = None
            if out.n_hold:
                import numpy as np

                nh = out.n_hold
                for p in (out.hold_u, out.hold_i, out.hold_v):
                    hold_owner.add(p)
                holdout = (
                    native_mod.as_ndarray(out.hold_u, nh * 4, np.int32,
                                          (nh,), hold_owner),
                    native_mod.as_ndarray(out.hold_i, nh * 4, np.int32,
                                          (nh,), hold_owner),
                    native_mod.as_ndarray(out.hold_v, nh * 4, np.float32,
                                          (nh,), hold_owner),
                )
        finally:
            # vocab buffers are copied into Python strings above; free
            # them now (the side/holdout buffers live via the owner)
            for p in (out.ent_dict, out.ent_offsets,
                      out.tgt_dict, out.tgt_offsets):
                if p:
                    self._lib.el_free(p)
        return S.BinnedInteractions(
            user_side=user_side, item_side=item_side,
            entity_vocab=ent_vocab, target_vocab=tgt_vocab,
            holdout=holdout, n_rows=int(n),
            scan_sec=float(out.scan_sec), bin_sec=float(out.bin_sec),
        )

    # -- streaming delta reads (ROADMAP item C) -----------------------------
    @staticmethod
    def _parse_cursor(cursor: str) -> Tuple[int, int]:
        try:
            gen_s, rec_s = cursor.split(":", 1)
            if gen_s[0] != "g" or rec_s[0] != "r":
                raise ValueError
            return int(gen_s[1:]), int(rec_s[1:])
        except (ValueError, IndexError):
            raise ValueError(
                f"malformed delta cursor {cursor!r} (expected 'g<gen>:r<rec>')"
            ) from None

    def delta_cursor(self, app_id, channel_id=None) -> str:
        """The current tail position as an opaque cursor string —
        ``find_columnar_since`` from here returns only rows appended
        AFTER this call. Built on el_fingerprint's generation/record
        counters, so it stays valid across process restarts."""
        h = self._handle(app_id, channel_id)
        out = (ctypes.c_uint64 * 4)()
        self._lib.el_fingerprint(h, out)
        return f"g{out[0]}:r{out[2]}"

    def find_columnar_since(
        self,
        app_id,
        channel_id=None,
        *,
        cursor: str,
        value_property: Optional[str] = None,
        **find_kwargs,
    ) -> Tuple[S.EventColumns, str, bool]:
        """Delta read: the live rows appended since ``cursor`` that
        match the filters, dict-encoded, in ARRIVAL order (one native
        pass over only the new records — the streaming tailer's lane).

        Returns ``(columns, new_cursor, rebased)``. ``rebased=True``
        means the cursor could not be mapped onto this log (a
        compaction renumbered records, or a crash truncated appends the
        cursor had seen): the returned columns are then a RESYNC of the
        entire live row set, not a delta — callers should treat it as
        "full retrain needed", not fold it in."""
        unknown = set(find_kwargs) - {
            "start_time", "until_time", "entity_type", "entity_id",
            "event_names", "target_entity_type", "target_entity_id",
        }
        if unknown:
            # same loud-failure contract as find_columnar (a typo'd
            # filter must never silently widen the delta); limit /
            # reversed are deliberately NOT accepted — a delta is
            # exactly-the-new-rows by definition
            raise TypeError(
                f"find_columnar_since() got unexpected filters {sorted(unknown)}"
            )
        gen, rec = self._parse_cursor(cursor)
        h = self._handle(app_id, channel_id)
        req = self._build_req(
            find_kwargs.get("start_time"), find_kwargs.get("until_time"),
            find_kwargs.get("entity_type"), find_kwargs.get("entity_id"),
            find_kwargs.get("event_names"),
            find_kwargs.get("target_entity_type", S.UNSET),
            find_kwargs.get("target_entity_id", S.UNSET),
            None, False,
        )
        out_gen = ctypes.c_uint64()
        out_rec = ctypes.c_uint64()
        out_rebased = ctypes.c_int32()
        out = _ColumnarOut(self._lib)
        n = self._lib.el_find_columnar_since(
            h, ctypes.byref(req),
            value_property.encode() if value_property is not None else None,
            gen, rec,
            ctypes.byref(out_gen), ctypes.byref(out_rec),
            ctypes.byref(out_rebased),
            *out.argrefs(),
        )
        if n < 0:
            raise S.StorageError("delta columnar read failed in native "
                                 "event log")
        cols = out.take(n)
        return (cols, f"g{out_gen.value}:r{out_rec.value}",
                bool(out_rebased.value))

    def insert_columnar(
        self,
        cols: S.EventColumns,
        app_id,
        channel_id=None,
        *,
        entity_type: str,
        target_entity_type: Optional[str] = None,
        value_property: Optional[str] = None,
    ) -> int:
        """Native bulk ingest: rows are packed into wire records in C++
        straight from the dict-encoded columns (overrides the
        Event-object fallback; ref: PEvents.write:124)."""
        import numpy as np

        h = self._handle(app_id, channel_id)

        # dictionaries packed WITHOUT separators; prefix offsets are exact
        def dict_concat(vocab):
            joined, offsets = S.pack_vocab(vocab)
            # u16 wire header: >= 0xFFFF wraps/aliases the absent
            # sentinel; fail loudly like the row path's struct 'H'
            widths = np.diff(offsets.astype(np.int64))
            if widths.size and int(widths.max()) >= 0xFFFF:
                raise S.StorageError(
                    f"id/name of {int(widths.max())} bytes exceeds the "
                    "65534-byte wire-format limit"
                )
            return joined, offsets

        ent_b, ent_off = dict_concat(cols.entity_vocab)
        tgt_b, tgt_off = dict_concat(cols.target_vocab)
        nam_b, nam_off = dict_concat(cols.names)

        ent_codes = np.ascontiguousarray(cols.entity_codes, np.int32)
        tgt_codes = np.ascontiguousarray(cols.target_codes, np.int32)
        nam_codes = np.ascontiguousarray(cols.name_codes, np.int32)
        times = np.ascontiguousarray(cols.times_us, np.int64)
        values = np.ascontiguousarray(cols.values, np.float64)

        def ptr(arr, ctype):
            return arr.ctypes.data_as(ctypes.POINTER(ctype))

        n = len(cols)
        chunk = 4_000_000
        total = 0
        for s in range(0, n, chunk):
            m = min(chunk, n - s)
            wrote = self._lib.el_append_columnar(
                h, m,
                entity_type.encode("utf-8"),
                target_entity_type.encode("utf-8")
                if target_entity_type is not None else None,
                value_property.encode("utf-8")
                if value_property is not None else None,
                ent_b, ptr(ent_off, ctypes.c_uint64), len(cols.entity_vocab),
                tgt_b, ptr(tgt_off, ctypes.c_uint64), len(cols.target_vocab),
                nam_b, ptr(nam_off, ctypes.c_uint64), len(cols.names),
                ptr(ent_codes[s:s + m], ctypes.c_int32),
                ptr(tgt_codes[s:s + m], ctypes.c_int32),
                ptr(nam_codes[s:s + m], ctypes.c_int32),
                ptr(times[s:s + m], ctypes.c_int64),
                ptr(values[s:s + m], ctypes.c_double),
                None,
            )
            if wrote != m:
                raise S.StorageError(
                    f"columnar append failed ({wrote} of {m} written)"
                )
            total += m
        if total:
            from predictionio_tpu.obs import dataobs, perfacct

            perfacct.note_ingest()
            if dataobs.DATAOBS.enabled():
                dataobs.DATAOBS.observe_columnar(app_id, cols)
        return total

    def data_fingerprint(self, app_id, channel_id=None) -> str:
        """O(1) content fingerprint — changes whenever the app's event
        data does. The binned-layout cache keys on it so retraining on
        unchanged events skips the 20M-row re-read (VERDICT r3 item 2).
        Backends without a cheap fingerprint simply lack this method.

        The key carries the LOG'S IDENTITY (a hash of the resolved log
        directory, which encodes app + channel) in addition to the
        content quadruple (generation, bytes, records, tombstones): the
        bincache directory is machine-global, and two different apps
        can realistically collide on the quadruple alone (fixed-size
        records, same row count — ADVICE r4 medium), which would
        silently train app B on app A's cached binned layout."""
        h = self._handle(app_id, channel_id)
        out = (ctypes.c_uint64 * 4)()
        self._lib.el_fingerprint(h, out)
        log_id = hashlib.sha256(
            os.path.realpath(self._dir(app_id, channel_id)).encode()
        ).hexdigest()[:12]
        return f"L{log_id}-g{out[0]}-b{out[1]}-n{out[2]}-t{out[3]}"

    def compact(self, app_id, channel_id=None) -> Dict[str, int]:
        """Rewrite the log keeping only live records: reclaims the space
        of $delete'd / superseded events and persists a fresh index
        snapshot (the role of an HBase major compaction — delete markers
        and shadowed cells physically removed). Returns
        {"dropped", "before_bytes", "after_bytes"}."""
        h = self._handle(app_id, channel_id)
        before = ctypes.c_uint64()
        after = ctypes.c_uint64()
        dropped = self._lib.el_compact(h, ctypes.byref(before), ctypes.byref(after))
        if dropped < 0:
            raise S.StorageError("compaction failed in native event log")
        return {
            "dropped": int(dropped),
            "before_bytes": int(before.value),
            "after_bytes": int(after.value),
        }

    def close(self) -> None:
        with self._lock:
            for h in self._handles.values():
                self._lib.el_close(h)
            self._handles.clear()


class EventLogStorageClient(S.StorageClient):
    """events → native log; metadata/models → localfs at the same root
    (the HBase-for-events + ES-for-metadata pairing, single-binary)."""

    def __init__(self, config: Dict[str, str]):
        super().__init__(config)
        base = os.path.expanduser(
            config.get("PATH", os.path.join("~", ".pio_store", "eventlog"))
        )
        self._events = EventLogEventStore(
            os.path.join(base, "events"), fsync=config.get("FSYNC", "0") == "1"
        )
        self._meta = LocalFSStorageClient({"PATH": os.path.join(base, "meta")})

    def events(self):
        return self._events

    def apps(self):
        return self._meta.apps()

    def access_keys(self):
        return self._meta.access_keys()

    def channels(self):
        return self._meta.channels()

    def engine_manifests(self):
        return self._meta.engine_manifests()

    def engine_instances(self):
        return self._meta.engine_instances()

    def evaluation_instances(self):
        return self._meta.evaluation_instances()

    def models(self):
        return self._meta.models()


S.register_backend("eventlog", EventLogStorageClient)
