"""In-memory storage backend — the test/embedded substrate.

Implements every DAO contract from predictionio_tpu.data.storage with
plain dicts under one RLock. This backend is what makes the whole
framework testable in-process (the reference's storage tests need a live
HBase + Elasticsearch; see SURVEY.md §4).

Records are deep-copied at the repo boundary (insert/update/get), so
callers mutating a dataclass after insert cannot bypass ``update`` —
matching the serialize-on-write behavior of the reference's real
backends. Repos accept ``on_change`` / ``pre_change`` hooks used by the
localfs backend to persist after, and reload before, each mutation.
"""

from __future__ import annotations

import copy
import threading
import uuid
from typing import Dict, List, Optional, Tuple

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.metadata import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EngineManifest,
    EvaluationInstance,
    Model,
)
from predictionio_tpu.data import storage as S

_cp = copy.deepcopy


def _table_key(app_id: int, channel_id: Optional[int]) -> Tuple[int, Optional[int]]:
    return (int(app_id), channel_id if channel_id is None else int(channel_id))


class MemoryEventStore(S.EventStore):
    def __init__(self):
        self._lock = threading.RLock()
        # (app_id, channel_id) -> {event_id: Event}
        self._tables: Dict[Tuple[int, Optional[int]], Dict[str, Event]] = {}

    def _table(self, app_id: int, channel_id: Optional[int], create: bool = False):
        key = _table_key(app_id, channel_id)
        if create:
            return self._tables.setdefault(key, {})
        tbl = self._tables.get(key)
        if tbl is None:
            # strict reads: an un-init()ed table is an error, like a missing
            # HBase table in the reference (hbase/HBLEvents.scala)
            raise S.StorageError(
                f"event table for app {app_id} channel {channel_id} not initialized"
            )
        return tbl

    def init(self, app_id, channel_id=None):
        with self._lock:
            self._table(app_id, channel_id, create=True)

    def remove(self, app_id, channel_id=None):
        with self._lock:
            self._tables.pop(_table_key(app_id, channel_id), None)

    def insert(self, event: Event, app_id, channel_id=None) -> str:
        with self._lock:
            tbl = self._table(app_id, channel_id)
            e = event if event.event_id else event.with_id()
            tbl[e.event_id] = e
            return e.event_id

    def get(self, event_id, app_id, channel_id=None):
        with self._lock:
            return self._table(app_id, channel_id).get(event_id)

    def delete(self, event_id, app_id, channel_id=None) -> bool:
        with self._lock:
            return self._table(app_id, channel_id).pop(event_id, None) is not None

    def find(
        self,
        app_id,
        channel_id=None,
        start_time=None,
        until_time=None,
        entity_type=None,
        entity_id=None,
        event_names=None,
        target_entity_type=S.UNSET,
        target_entity_id=S.UNSET,
        limit=None,
        reversed=False,
    ) -> List[Event]:
        with self._lock:
            events = list(self._table(app_id, channel_id).values())
        out = [
            e
            for e in events
            if _matches(
                e, start_time, until_time, entity_type, entity_id, event_names,
                target_entity_type, target_entity_id,
            )
        ]
        out.sort(key=lambda e: (e.event_time, e.creation_time), reverse=reversed)
        if limit is not None and limit >= 0:
            out = out[:limit]
        return out


def _matches(
    e: Event,
    start_time,
    until_time,
    entity_type,
    entity_id,
    event_names,
    target_entity_type,
    target_entity_id,
) -> bool:
    """Filter semantics of PEvents.find (ref: PEvents.scala:70):
    [start_time, until_time) half-open window; target filters use the
    UNSET sentinel so callers can ask for "no target entity"."""
    if start_time is not None and e.event_time < start_time:
        return False
    if until_time is not None and e.event_time >= until_time:
        return False
    if entity_type is not None and e.entity_type != entity_type:
        return False
    if entity_id is not None and e.entity_id != entity_id:
        return False
    if event_names is not None and e.event not in event_names:
        return False
    if target_entity_type is not S.UNSET and e.target_entity_type != target_entity_type:
        return False
    if target_entity_id is not S.UNSET and e.target_entity_id != target_entity_id:
        return False
    return True


class _Sequences:
    """Auto-increment ids (ref: elasticsearch/ESSequences.scala)."""

    def __init__(self):
        self._counters: Dict[str, int] = {}

    def next(self, name: str) -> int:
        self._counters[name] = self._counters.get(name, 0) + 1
        return self._counters[name]

    def state(self) -> Dict[str, int]:
        return dict(self._counters)

    def restore(self, state: Dict[str, int]) -> None:
        self._counters = dict(state)

    def merge_max(self, state: Dict[str, int]) -> None:
        for k, v in state.items():
            self._counters[k] = max(self._counters.get(k, 0), v)


class _RecordRepo:
    """Shared dict-backed repo plumbing: lock, boundary copies, hooks."""

    def __init__(self, lock: threading.RLock, on_change=None, pre_change=None):
        self._records: Dict = {}
        self._lock = lock
        self._on_change = on_change or (lambda: None)
        self._pre = pre_change or (lambda: None)

    def _put(self, key, record) -> None:
        self._records[key] = _cp(record)
        self._on_change()

    def _get(self, key):
        rec = self._records.get(key)
        return _cp(rec) if rec is not None else None

    def _all(self) -> list:
        return [_cp(r) for r in self._records.values()]

    def _drop(self, key) -> None:
        self._records.pop(key, None)
        self._on_change()


class MemoryAppsRepo(_RecordRepo, S.AppsRepo):
    def __init__(self, sequences: _Sequences, lock, on_change=None, pre_change=None):
        super().__init__(lock, on_change, pre_change)
        self._seq = sequences

    def insert(self, name, description=None) -> App:
        with self._lock:
            self._pre()
            if any(a.name == name for a in self._records.values()):
                raise S.StorageError(f"app name {name!r} already exists")
            app = App(id=self._seq.next("apps"), name=name, description=description)
            self._put(app.id, app)
            return _cp(app)

    def get(self, app_id):
        with self._lock:
            return self._get(int(app_id))

    def get_by_name(self, name):
        with self._lock:
            rec = next((a for a in self._records.values() if a.name == name), None)
            return _cp(rec) if rec is not None else None

    def get_all(self):
        with self._lock:
            return sorted(self._all(), key=lambda a: a.id)

    def update(self, app):
        with self._lock:
            self._pre()
            self._put(app.id, app)

    def delete(self, app_id):
        with self._lock:
            self._pre()
            self._drop(int(app_id))


class MemoryAccessKeysRepo(_RecordRepo, S.AccessKeysRepo):
    def insert(self, access_key: AccessKey) -> str:
        with self._lock:
            self._pre()
            if not access_key.key:
                access_key = AccessKey.generate(access_key.appid, access_key.events)
            self._put(access_key.key, access_key)
            return access_key.key

    def get(self, key):
        with self._lock:
            return self._get(key)

    def get_all(self):
        with self._lock:
            return self._all()

    def get_by_app_id(self, app_id):
        with self._lock:
            return [_cp(k) for k in self._records.values() if k.appid == int(app_id)]

    def update(self, access_key):
        with self._lock:
            self._pre()
            self._put(access_key.key, access_key)

    def delete(self, key):
        with self._lock:
            self._pre()
            self._drop(key)


class MemoryChannelsRepo(_RecordRepo, S.ChannelsRepo):
    def __init__(self, sequences: _Sequences, lock, on_change=None, pre_change=None):
        super().__init__(lock, on_change, pre_change)
        self._seq = sequences

    def insert(self, name, app_id) -> Channel:
        with self._lock:
            self._pre()
            if not Channel.is_valid_name(name):
                raise S.StorageError(
                    f"invalid channel name {name!r} (must match [a-zA-Z0-9-]{{1,16}})"
                )
            if any(c.name == name and c.appid == int(app_id) for c in self._records.values()):
                raise S.StorageError(f"channel {name!r} already exists for app {app_id}")
            ch = Channel(id=self._seq.next("channels"), name=name, appid=int(app_id))
            self._put(ch.id, ch)
            return _cp(ch)

    def get(self, channel_id):
        with self._lock:
            return self._get(int(channel_id))

    def get_by_app_id(self, app_id):
        with self._lock:
            return sorted(
                (_cp(c) for c in self._records.values() if c.appid == int(app_id)),
                key=lambda c: c.id,
            )

    def delete(self, channel_id):
        with self._lock:
            self._pre()
            self._drop(int(channel_id))

    def put(self, channel):
        # replication write: the record arrives pre-validated with its
        # id already assigned by the owner endpoint (S.ChannelsRepo.put)
        with self._lock:
            self._pre()
            self._put(int(channel.id), channel)


class MemoryEngineManifestsRepo(_RecordRepo, S.EngineManifestsRepo):
    def insert(self, manifest):
        with self._lock:
            self._pre()
            self._put((manifest.id, manifest.version), manifest)

    def get(self, id, version):
        with self._lock:
            return self._get((id, version))

    def get_all(self):
        with self._lock:
            return self._all()

    def update(self, manifest):
        self.insert(manifest)

    def delete(self, id, version):
        with self._lock:
            self._pre()
            self._drop((id, version))


class MemoryEngineInstancesRepo(_RecordRepo, S.EngineInstancesRepo):
    def insert(self, instance) -> str:
        with self._lock:
            self._pre()
            if not instance.id:
                instance.id = uuid.uuid4().hex
            self._put(instance.id, instance)
            return instance.id

    def get(self, id):
        with self._lock:
            return self._get(id)

    def get_all(self):
        with self._lock:
            return self._all()

    def get_completed(self, engine_id, engine_version, engine_variant):
        # ref: EngineInstances.getCompleted — newest first
        with self._lock:
            out = [
                _cp(i)
                for i in self._records.values()
                if i.status == "COMPLETED"
                and i.engine_id == engine_id
                and i.engine_version == engine_version
                and i.engine_variant == engine_variant
            ]
        out.sort(key=lambda i: i.start_time, reverse=True)
        return out

    def get_latest_completed(self, engine_id, engine_version, engine_variant):
        completed = self.get_completed(engine_id, engine_version, engine_variant)
        return completed[0] if completed else None

    def update(self, instance):
        with self._lock:
            self._pre()
            self._put(instance.id, instance)

    def delete(self, id):
        with self._lock:
            self._pre()
            self._drop(id)


class MemoryEvaluationInstancesRepo(_RecordRepo, S.EvaluationInstancesRepo):
    def insert(self, instance) -> str:
        with self._lock:
            self._pre()
            if not instance.id:
                instance.id = uuid.uuid4().hex
            self._put(instance.id, instance)
            return instance.id

    def get(self, id):
        with self._lock:
            return self._get(id)

    def get_all(self):
        with self._lock:
            return self._all()

    def get_completed(self):
        with self._lock:
            out = [_cp(i) for i in self._records.values() if i.status == "EVALCOMPLETED"]
        out.sort(key=lambda i: i.start_time, reverse=True)
        return out

    def update(self, instance):
        with self._lock:
            self._pre()
            self._put(instance.id, instance)

    def delete(self, id):
        with self._lock:
            self._pre()
            self._drop(id)


class MemoryModelsRepo(S.ModelsRepo):
    def __init__(self, lock: threading.RLock):
        self._models: Dict[str, Model] = {}
        self._lock = lock

    def insert(self, model):
        with self._lock:
            self._models[model.id] = Model(id=model.id, models=bytes(model.models))

    def get(self, id):
        with self._lock:
            m = self._models.get(id)
            return Model(id=m.id, models=m.models) if m is not None else None

    def size(self, id):
        with self._lock:
            m = self._models.get(id)
            return None if m is None else len(m.models)

    def delete(self, id):
        with self._lock:
            self._models.pop(id, None)

    def list(self):
        import hashlib

        with self._lock:
            return [
                {"id": m.id, "bytes": len(m.models),
                 "sha256": hashlib.sha256(m.models).hexdigest()}
                for m in self._models.values()
            ]


class MemoryStorageClient(S.StorageClient):
    """ref: a StorageClient per source (Storage.scala:151-166)."""

    def __init__(self, config: Dict[str, str]):
        super().__init__(config)
        self._lock = threading.RLock()
        self._sequences = _Sequences()
        self._events = MemoryEventStore()
        self._apps = MemoryAppsRepo(self._sequences, self._lock)
        self._access_keys = MemoryAccessKeysRepo(self._lock)
        self._channels = MemoryChannelsRepo(self._sequences, self._lock)
        self._engine_manifests = MemoryEngineManifestsRepo(self._lock)
        self._engine_instances = MemoryEngineInstancesRepo(self._lock)
        self._evaluation_instances = MemoryEvaluationInstancesRepo(self._lock)
        self._models = MemoryModelsRepo(self._lock)

    def events(self): return self._events
    def apps(self): return self._apps
    def access_keys(self): return self._access_keys
    def channels(self): return self._channels
    def engine_manifests(self): return self._engine_manifests
    def engine_instances(self): return self._engine_instances
    def evaluation_instances(self): return self._evaluation_instances
    def models(self): return self._models


S.register_backend("memory", MemoryStorageClient)
