"""In-memory storage backend — the test/embedded substrate.

Implements every DAO contract from predictionio_tpu.data.storage with
plain dicts under one RLock. This backend is what makes the whole
framework testable in-process (the reference's storage tests need a live
HBase + Elasticsearch; see SURVEY.md §4).
"""

from __future__ import annotations

import datetime as _dt
import itertools
import threading
import uuid
from typing import Any, Dict, List, Optional, Tuple

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.metadata import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EngineManifest,
    EvaluationInstance,
    Model,
)
from predictionio_tpu.data import storage as S


class MemoryEventStore(S.EventStore):
    def __init__(self):
        self._lock = threading.RLock()
        # (app_id, channel_id) -> {event_id: Event}
        self._tables: Dict[Tuple[int, Optional[int]], Dict[str, Event]] = {}

    def _table(self, app_id: int, channel_id: Optional[int], create: bool = False):
        key = (int(app_id), channel_id if channel_id is None else int(channel_id))
        if create:
            return self._tables.setdefault(key, {})
        tbl = self._tables.get(key)
        if tbl is None:
            raise S.StorageError(f"event table for app {app_id} channel {channel_id} not initialized")
        return tbl

    def init(self, app_id, channel_id=None):
        with self._lock:
            self._table(app_id, channel_id, create=True)

    def remove(self, app_id, channel_id=None):
        with self._lock:
            self._tables.pop((int(app_id), channel_id if channel_id is None else int(channel_id)), None)

    def insert(self, event: Event, app_id, channel_id=None) -> str:
        with self._lock:
            tbl = self._table(app_id, channel_id, create=True)
            e = event if event.event_id else event.with_id()
            tbl[e.event_id] = e
            return e.event_id

    def get(self, event_id, app_id, channel_id=None):
        with self._lock:
            return self._table(app_id, channel_id, create=True).get(event_id)

    def delete(self, event_id, app_id, channel_id=None) -> bool:
        with self._lock:
            return self._table(app_id, channel_id, create=True).pop(event_id, None) is not None

    def find(
        self,
        app_id,
        channel_id=None,
        start_time=None,
        until_time=None,
        entity_type=None,
        entity_id=None,
        event_names=None,
        target_entity_type=S.UNSET,
        target_entity_id=S.UNSET,
        limit=None,
        reversed=False,
    ) -> List[Event]:
        with self._lock:
            events = list(self._table(app_id, channel_id, create=True).values())
        out = [
            e
            for e in events
            if _matches(
                e, start_time, until_time, entity_type, entity_id, event_names,
                target_entity_type, target_entity_id,
            )
        ]
        out.sort(key=lambda e: (e.event_time, e.creation_time), reverse=reversed)
        if limit is not None and limit >= 0:
            out = out[:limit]
        return out


def _matches(
    e: Event,
    start_time,
    until_time,
    entity_type,
    entity_id,
    event_names,
    target_entity_type,
    target_entity_id,
) -> bool:
    """Filter semantics of PEvents.find (ref: PEvents.scala:70):
    [start_time, until_time) half-open window; target filters use the
    UNSET sentinel so callers can ask for "no target entity"."""
    if start_time is not None and e.event_time < start_time:
        return False
    if until_time is not None and e.event_time >= until_time:
        return False
    if entity_type is not None and e.entity_type != entity_type:
        return False
    if entity_id is not None and e.entity_id != entity_id:
        return False
    if event_names is not None and e.event not in event_names:
        return False
    if target_entity_type is not S.UNSET and e.target_entity_type != target_entity_type:
        return False
    if target_entity_id is not S.UNSET and e.target_entity_id != target_entity_id:
        return False
    return True


class _Sequences:
    """Auto-increment ids (ref: elasticsearch/ESSequences.scala)."""

    def __init__(self):
        self._counters: Dict[str, int] = {}

    def next(self, name: str) -> int:
        self._counters[name] = self._counters.get(name, 0) + 1
        return self._counters[name]

    def state(self) -> Dict[str, int]:
        return dict(self._counters)

    def restore(self, state: Dict[str, int]) -> None:
        self._counters = dict(state)


class MemoryAppsRepo(S.AppsRepo):
    def __init__(self, sequences: _Sequences, lock: threading.RLock, on_change=None):
        self._apps: Dict[int, App] = {}
        self._seq = sequences
        self._lock = lock
        self._on_change = on_change or (lambda: None)

    def insert(self, name, description=None) -> App:
        with self._lock:
            if self.get_by_name(name) is not None:
                raise S.StorageError(f"app name {name!r} already exists")
            app = App(id=self._seq.next("apps"), name=name, description=description)
            self._apps[app.id] = app
            self._on_change()
            return app

    def get(self, app_id):
        with self._lock:
            return self._apps.get(int(app_id))

    def get_by_name(self, name):
        with self._lock:
            return next((a for a in self._apps.values() if a.name == name), None)

    def get_all(self):
        with self._lock:
            return sorted(self._apps.values(), key=lambda a: a.id)

    def update(self, app):
        with self._lock:
            self._apps[app.id] = app
            self._on_change()

    def delete(self, app_id):
        with self._lock:
            self._apps.pop(int(app_id), None)
            self._on_change()


class MemoryAccessKeysRepo(S.AccessKeysRepo):
    def __init__(self, lock: threading.RLock, on_change=None):
        self._keys: Dict[str, AccessKey] = {}
        self._lock = lock
        self._on_change = on_change or (lambda: None)

    def insert(self, access_key: AccessKey) -> str:
        with self._lock:
            if not access_key.key:
                access_key = AccessKey.generate(access_key.appid, access_key.events)
            self._keys[access_key.key] = access_key
            self._on_change()
            return access_key.key

    def get(self, key):
        with self._lock:
            return self._keys.get(key)

    def get_all(self):
        with self._lock:
            return list(self._keys.values())

    def get_by_app_id(self, app_id):
        with self._lock:
            return [k for k in self._keys.values() if k.appid == int(app_id)]

    def update(self, access_key):
        with self._lock:
            self._keys[access_key.key] = access_key
            self._on_change()

    def delete(self, key):
        with self._lock:
            self._keys.pop(key, None)
            self._on_change()


class MemoryChannelsRepo(S.ChannelsRepo):
    def __init__(self, sequences: _Sequences, lock: threading.RLock, on_change=None):
        self._channels: Dict[int, Channel] = {}
        self._seq = sequences
        self._lock = lock
        self._on_change = on_change or (lambda: None)

    def insert(self, name, app_id) -> Channel:
        with self._lock:
            if not Channel.is_valid_name(name):
                raise S.StorageError(
                    f"invalid channel name {name!r} (must match [a-zA-Z0-9-]{{1,16}})"
                )
            if any(c.name == name and c.appid == int(app_id) for c in self._channels.values()):
                raise S.StorageError(f"channel {name!r} already exists for app {app_id}")
            ch = Channel(id=self._seq.next("channels"), name=name, appid=int(app_id))
            self._channels[ch.id] = ch
            self._on_change()
            return ch

    def get(self, channel_id):
        with self._lock:
            return self._channels.get(int(channel_id))

    def get_by_app_id(self, app_id):
        with self._lock:
            return sorted(
                (c for c in self._channels.values() if c.appid == int(app_id)),
                key=lambda c: c.id,
            )

    def delete(self, channel_id):
        with self._lock:
            self._channels.pop(int(channel_id), None)
            self._on_change()


class MemoryEngineManifestsRepo(S.EngineManifestsRepo):
    def __init__(self, lock: threading.RLock, on_change=None):
        self._manifests: Dict[Tuple[str, str], EngineManifest] = {}
        self._lock = lock
        self._on_change = on_change or (lambda: None)

    def insert(self, manifest):
        with self._lock:
            self._manifests[(manifest.id, manifest.version)] = manifest
            self._on_change()

    def get(self, id, version):
        with self._lock:
            return self._manifests.get((id, version))

    def get_all(self):
        with self._lock:
            return list(self._manifests.values())

    def update(self, manifest):
        self.insert(manifest)

    def delete(self, id, version):
        with self._lock:
            self._manifests.pop((id, version), None)
            self._on_change()


class MemoryEngineInstancesRepo(S.EngineInstancesRepo):
    def __init__(self, lock: threading.RLock, on_change=None):
        self._instances: Dict[str, EngineInstance] = {}
        self._lock = lock
        self._on_change = on_change or (lambda: None)

    def insert(self, instance) -> str:
        with self._lock:
            if not instance.id:
                instance.id = uuid.uuid4().hex
            self._instances[instance.id] = instance
            self._on_change()
            return instance.id

    def get(self, id):
        with self._lock:
            return self._instances.get(id)

    def get_all(self):
        with self._lock:
            return list(self._instances.values())

    def get_completed(self, engine_id, engine_version, engine_variant):
        # ref: EngineInstances.getCompleted — newest first
        with self._lock:
            out = [
                i
                for i in self._instances.values()
                if i.status == "COMPLETED"
                and i.engine_id == engine_id
                and i.engine_version == engine_version
                and i.engine_variant == engine_variant
            ]
            out.sort(key=lambda i: i.start_time, reverse=True)
            return out

    def get_latest_completed(self, engine_id, engine_version, engine_variant):
        completed = self.get_completed(engine_id, engine_version, engine_variant)
        return completed[0] if completed else None

    def update(self, instance):
        with self._lock:
            self._instances[instance.id] = instance
            self._on_change()

    def delete(self, id):
        with self._lock:
            self._instances.pop(id, None)
            self._on_change()


class MemoryEvaluationInstancesRepo(S.EvaluationInstancesRepo):
    def __init__(self, lock: threading.RLock, on_change=None):
        self._instances: Dict[str, EvaluationInstance] = {}
        self._lock = lock
        self._on_change = on_change or (lambda: None)

    def insert(self, instance) -> str:
        with self._lock:
            if not instance.id:
                instance.id = uuid.uuid4().hex
            self._instances[instance.id] = instance
            self._on_change()
            return instance.id

    def get(self, id):
        with self._lock:
            return self._instances.get(id)

    def get_all(self):
        with self._lock:
            return list(self._instances.values())

    def get_completed(self):
        with self._lock:
            out = [i for i in self._instances.values() if i.status == "EVALCOMPLETED"]
            out.sort(key=lambda i: i.start_time, reverse=True)
            return out

    def update(self, instance):
        with self._lock:
            self._instances[instance.id] = instance
            self._on_change()

    def delete(self, id):
        with self._lock:
            self._instances.pop(id, None)
            self._on_change()


class MemoryModelsRepo(S.ModelsRepo):
    def __init__(self, lock: threading.RLock):
        self._models: Dict[str, Model] = {}
        self._lock = lock

    def insert(self, model):
        with self._lock:
            self._models[model.id] = model

    def get(self, id):
        with self._lock:
            return self._models.get(id)

    def delete(self, id):
        with self._lock:
            self._models.pop(id, None)


class MemoryStorageClient(S.StorageClient):
    """ref: a StorageClient per source (Storage.scala:151-166)."""

    def __init__(self, config: Dict[str, str]):
        super().__init__(config)
        self._lock = threading.RLock()
        self._sequences = _Sequences()
        self._events = MemoryEventStore()
        self._apps = MemoryAppsRepo(self._sequences, self._lock)
        self._access_keys = MemoryAccessKeysRepo(self._lock)
        self._channels = MemoryChannelsRepo(self._sequences, self._lock)
        self._engine_manifests = MemoryEngineManifestsRepo(self._lock)
        self._engine_instances = MemoryEngineInstancesRepo(self._lock)
        self._evaluation_instances = MemoryEvaluationInstancesRepo(self._lock)
        self._models = MemoryModelsRepo(self._lock)

    def events(self): return self._events
    def apps(self): return self._apps
    def access_keys(self): return self._access_keys
    def channels(self): return self._channels
    def engine_manifests(self): return self._engine_manifests
    def engine_instances(self): return self._engine_instances
    def evaluation_instances(self): return self._evaluation_instances
    def models(self): return self._models


S.register_backend("memory", MemoryStorageClient)
