"""Public engine-facing event-store API.

Behavior contract from the reference's PEventStore / LEventStore
(data/.../store/PEventStore.scala:30, store/LEventStore.scala:32,
store/Common.scala:28): engines address data by *app name* (+ optional
channel name); the store resolves the (appId, channelId) pair from
metadata and raises if the app or channel does not exist. ``find`` /
``aggregate_properties`` are the training-read path; ``find_by_entity``
is the low-latency serve-time lookup.

Without Spark there is a single API: results are Python lists of Event
(converted to numpy/JAX buffers by DataSources in ``predictionio_tpu.ops``).
"""

from __future__ import annotations

import datetime as _dt
from typing import Any, Dict, List, Optional

from predictionio_tpu.data.datamap import PropertyMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import UNSET, Storage, StorageError, get_storage


def resolve_app(
    app_name: str,
    channel_name: Optional[str] = None,
    storage: Optional[Storage] = None,
):
    """app name (+channel name) -> (app_id, channel_id).

    ref: store/Common.scala:28 — errors mirror the reference's messages.
    """
    storage = storage or get_storage()
    app = storage.apps().get_by_name(app_name)
    if app is None:
        raise StorageError(f"App name {app_name} is not valid.")
    channel_id = None
    if channel_name is not None:
        channels = storage.channels().get_by_app_id(app.id)
        ch = next((c for c in channels if c.name == channel_name), None)
        if ch is None:
            raise StorageError(f"Channel name {channel_name} is not valid.")
        channel_id = ch.id
    return app.id, channel_id


def find(
    app_name: str,
    channel_name: Optional[str] = None,
    start_time: Optional[_dt.datetime] = None,
    until_time: Optional[_dt.datetime] = None,
    entity_type: Optional[str] = None,
    entity_id: Optional[str] = None,
    event_names: Optional[List[str]] = None,
    target_entity_type: Any = UNSET,
    target_entity_id: Any = UNSET,
    limit: Optional[int] = None,
    reversed: bool = False,
    storage: Optional[Storage] = None,
) -> List[Event]:
    """ref: PEventStore.find:30."""
    storage = storage or get_storage()
    app_id, channel_id = resolve_app(app_name, channel_name, storage)
    return storage.events().find(
        app_id,
        channel_id=channel_id,
        start_time=start_time,
        until_time=until_time,
        entity_type=entity_type,
        entity_id=entity_id,
        event_names=event_names,
        target_entity_type=target_entity_type,
        target_entity_id=target_entity_id,
        limit=limit,
        reversed=reversed,
    )


def find_columnar(
    app_name: str,
    channel_name: Optional[str] = None,
    value_property: Optional[str] = None,
    time_ordered: bool = True,
    shard_index: Optional[int] = None,
    shard_count: Optional[int] = None,
    storage: Optional[Storage] = None,
    **find_kwargs,
):
    """Bulk training read as dict-encoded columns (storage.EventColumns)
    — the fast path behind DataSources at ML-20M scale (the role of the
    reference's region-parallel HBase scans, hbase/HBPEvents.scala:48).
    ``shard_index``/``shard_count`` select this host's entity-hash read
    shard — N training hosts each fetch only ~1/N of the rows."""
    storage = storage or get_storage()
    app_id, channel_id = resolve_app(app_name, channel_name, storage)
    return storage.events().find_columnar(
        app_id,
        channel_id=channel_id,
        value_property=value_property,
        time_ordered=time_ordered,
        shard_index=shard_index,
        shard_count=shard_count,
        **find_kwargs,
    )


def supports_bin_columnar(
    app_name: str,
    channel_name: Optional[str] = None,
    storage: Optional[Storage] = None,
) -> bool:
    """Whether the app's event store offers the fused native
    ingest->bin lane (``bin_columnar`` — today only the eventlog
    backend, and only when its C++ toolchain is available). Raises
    StorageError for an unknown app/channel, exactly like every other
    store entry point — callers probing capability fall back so the
    read path raises the canonical error message."""
    storage = storage or get_storage()
    resolve_app(app_name, channel_name, storage)
    if getattr(storage.events(), "bin_columnar", None) is None:
        return False
    from predictionio_tpu import native

    return native.native_available("eventlog")


def bin_columnar(
    app_name: str,
    channel_name: Optional[str] = None,
    storage: Optional[Storage] = None,
    **kwargs,
):
    """The zero-copy training read: ONE native call scans the mmap'd
    log and bins BOTH sides into device-ready compressed layouts
    (storage.BinnedInteractions) — no Event objects, no Python row
    loop, no intermediate COO materialization. Callers must check
    :func:`supports_bin_columnar` first (other backends fall back to
    ``find_columnar`` + ops.ragged binning)."""
    storage = storage or get_storage()
    app_id, channel_id = resolve_app(app_name, channel_name, storage)
    return storage.events().bin_columnar(app_id, channel_id=channel_id,
                                         **kwargs)


def data_fingerprint(
    app_name: str,
    channel_name: Optional[str] = None,
    storage: Optional[Storage] = None,
) -> Optional[str]:
    """O(1) content fingerprint of an app's event data, or None when
    the backend has no cheap one (only the native eventlog does —
    el_fingerprint). Changes whenever the data does; the binned-layout
    cache (ops.bincache) keys on it so retraining on unchanged events
    skips the bulk re-read (VERDICT r3 item 2)."""
    storage = storage or get_storage()
    app_id, channel_id = resolve_app(app_name, channel_name, storage)
    fn = getattr(storage.events(), "data_fingerprint", None)
    if fn is None:
        return None
    return fn(app_id, channel_id)


def aggregate_properties(
    app_name: str,
    entity_type: str,
    channel_name: Optional[str] = None,
    start_time: Optional[_dt.datetime] = None,
    until_time: Optional[_dt.datetime] = None,
    required: Optional[List[str]] = None,
    storage: Optional[Storage] = None,
) -> Dict[str, PropertyMap]:
    """ref: PEventStore.aggregateProperties."""
    storage = storage or get_storage()
    app_id, channel_id = resolve_app(app_name, channel_name, storage)
    return storage.events().aggregate_properties(
        app_id,
        entity_type,
        channel_id=channel_id,
        start_time=start_time,
        until_time=until_time,
        required=required,
    )


def extract_entity_map(
    app_name: str,
    entity_type: str,
    extract,
    channel_name: Optional[str] = None,
    start_time: Optional[_dt.datetime] = None,
    until_time: Optional[_dt.datetime] = None,
    required: Optional[List[str]] = None,
    storage: Optional[Storage] = None,
):
    """Aggregate properties, then index entities into an EntityMap whose
    payload is ``extract(PropertyMap)`` per entity
    (ref: PEvents.extractEntityMap:109)."""
    from predictionio_tpu.data.bimap import EntityMap

    props = aggregate_properties(
        app_name,
        entity_type,
        channel_name=channel_name,
        start_time=start_time,
        until_time=until_time,
        required=required,
        storage=storage,
    )
    return EntityMap({eid: extract(pm) for eid, pm in props.items()})


def find_by_entity(
    app_name: str,
    entity_type: str,
    entity_id: str,
    channel_name: Optional[str] = None,
    event_names: Optional[List[str]] = None,
    target_entity_type: Any = UNSET,
    target_entity_id: Any = UNSET,
    start_time: Optional[_dt.datetime] = None,
    until_time: Optional[_dt.datetime] = None,
    limit: Optional[int] = None,
    latest: bool = True,
    storage: Optional[Storage] = None,
) -> List[Event]:
    """Serve-time entity lookup (ref: LEventStore.findByEntity:60)."""
    return find(
        app_name,
        channel_name=channel_name,
        start_time=start_time,
        until_time=until_time,
        entity_type=entity_type,
        entity_id=entity_id,
        event_names=event_names,
        target_entity_type=target_entity_type,
        target_entity_id=target_entity_id,
        limit=limit,
        reversed=latest,
        storage=storage,
    )
