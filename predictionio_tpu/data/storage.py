"""Storage abstraction + env-configured registry.

Behavior contract from the reference's Storage factory
(data/.../storage/Storage.scala:40,151,183): storage *sources* are
declared via ``PIO_STORAGE_SOURCES_<NAME>_TYPE`` (+ per-type config) and
the three *repositories* — METADATA, EVENTDATA, MODELDATA — are mapped
onto sources via ``PIO_STORAGE_REPOSITORIES_<REPO>_{NAME,SOURCE}``.
Backends register a ``StorageClient`` class per type; entity DAOs are
resolved per backend. The TPU build keeps the same env-var contract but
resolves backends from a Python registry instead of JVM reflection.

Unlike the reference (whose tests require a live HBase), an in-memory
backend ships first-class so the whole framework is testable in-process
(SURVEY.md §4 lesson).
"""

from __future__ import annotations

import abc
import dataclasses
import datetime as _dt
import hashlib
import logging
import os
import re
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple, TYPE_CHECKING

if TYPE_CHECKING:
    import numpy as np

from predictionio_tpu.data.datamap import PropertyMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.resilience import chaos
from predictionio_tpu.data.metadata import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EngineManifest,
    EvaluationInstance,
    Model,
)

log = logging.getLogger(__name__)

#: sentinel distinguishing "don't filter" from "filter for None"
#: (ref: PEvents.find targetEntityType: Option[Option[String]])
UNSET = object()


class StorageError(RuntimeError):
    pass


class StorageUnavailableError(StorageError):
    """Connection-level failure (refused/reset/timeout): the backend
    could not be REACHED, as opposed to an application error it
    answered with. Idempotent network-tier operations retry on this;
    callers can distinguish outage from bad-request."""


class RowValidationError(StorageError):
    """strict=True batch insert hit an invalid row: a PERMANENT
    client-data error (nothing was appended), never a backend fault —
    retrying the same batch can only fail the same way. The rest tier
    maps it to 400 with a ``row_error`` discriminator and re-raises it
    client-side under this same type, so local and remote strict paths
    fail identically (ADVICE r4 low)."""


@dataclasses.dataclass
class EventColumns:
    """Dict-encoded columnar view of a filtered event scan — the bulk
    training-read path (the role of the reference's region-parallel
    HBase scans feeding RDDs, hbase/HBPEvents.scala:48, redesigned
    columnar so a 20M-event read never materializes per-event objects).

    ``entity_codes[i]`` indexes ``entity_vocab`` (first-seen order);
    ``target_codes[i]`` likewise, with -1 for events without a target
    id. ``values[i]`` is the numeric property asked for via
    ``value_property`` (NaN when absent/non-numeric). ``times_us`` is
    the event time in epoch microseconds (UTC).
    """

    entity_codes: "np.ndarray"      # int32 [n]
    target_codes: "np.ndarray"      # int32 [n], -1 = no target id
    name_codes: "np.ndarray"        # int32 [n]
    values: "np.ndarray"            # float64 [n], NaN = absent
    times_us: "np.ndarray"          # int64 [n]
    entity_vocab: List[str]
    target_vocab: List[str]
    names: List[str]

    def __len__(self) -> int:
        return len(self.entity_codes)


@dataclasses.dataclass
class BinnedSide:
    """One side of a device-ready binned layout (the zero-copy data
    path): transfer-compressed segmented virtual rows as produced by
    the native builder (eventlog.cpp el_bin_columnar / raggedbin.cpp
    rb_bin_compressed) — identical in shape and bytes to what
    ops/als.compress_side(ops/ragged.build_segmented_groups(...))
    produces from the same COO. Arrays may be ZERO-COPY views over
    native buffers (their buffer objects anchor the allocation's
    lifetime — see native.as_ndarray)."""

    idx_lo: "np.ndarray"            # [R, L] uint16
    idx_hi: "Optional[np.ndarray]"  # [R, L] uint8, None when vocab < 2^16
    val: "np.ndarray"               # [R, L] uint8 codes | float32
    mask: "Optional[np.ndarray]"    # [R, L] uint8, None when val is coded
    seg: "np.ndarray"               # [R] int32
    counts: "np.ndarray"            # [G] int32
    affine: Optional[Tuple[float, float]]
    row_block: int
    group_block: int
    groups_per_shard: int
    n_shards: int
    n_groups: int                   # true group count (pre-padding)
    kept_entries: int
    kept_value_sum: float


@dataclasses.dataclass
class BinnedInteractions:
    """Both sides of an interaction dataset, binned straight off the
    event log by the native zero-copy lane — what `el_bin_columnar`
    hands back: grouped-by-entity (user) and grouped-by-target (item)
    compressed layouts, the id vocabularies, and (optionally) a
    held-out COO split for evaluation. ``scan_sec``/``bin_sec`` are the
    native call's own wall-time split (filter+encode+vocab vs
    resolve+plan+fill), feeding the data-path ledger's read/bin
    stages."""

    user_side: BinnedSide
    item_side: BinnedSide
    entity_vocab: List[str]
    target_vocab: List[str]
    #: (user_idx int32, item_idx int32, values float32) or None
    holdout: Optional[Tuple["np.ndarray", "np.ndarray", "np.ndarray"]]
    n_rows: int
    scan_sec: float
    bin_sec: float


def stable_hash(s: str) -> int:
    """Process-independent 64-bit hash of a string id — THE partition
    function of the framework. Every entity-routed split must agree on
    it: host-sharded training reads (parallel.multihost) and
    shard-filtered columnar scans
    (``find_columnar(shard_index=, shard_count=)``) today, the same way
    every HBase reader/writer agrees on the MD5 rowkey prefix
    (hbase/HBEventsUtil.scala:96-108). Builtin ``hash`` is salted per
    process and would break that agreement."""
    return int.from_bytes(hashlib.md5(s.encode()).digest()[:8], "little")


def _compact_columns(cols: EventColumns, keep: "np.ndarray") -> EventColumns:
    """Rows where ``keep`` is True, with every vocabulary compacted to
    the ids those rows actually reference (first-seen order preserved)."""
    import numpy as np

    def remap(codes, vocab, sentinel: bool):
        kept = codes[keep]
        used = np.unique(kept)
        if sentinel:
            used = used[used >= 0]
        table = np.full(len(vocab), -1, np.int32)
        table[used] = np.arange(len(used), dtype=np.int32)
        new_vocab = [vocab[int(c)] for c in used]
        if sentinel:
            new_codes = np.where(
                kept >= 0,
                table[np.maximum(kept, 0)] if table.size else np.int32(-1),
                np.int32(-1),
            ).astype(np.int32)
        else:
            new_codes = table[kept].astype(np.int32, copy=False)
        return new_codes, new_vocab

    ent, ent_v = remap(cols.entity_codes, cols.entity_vocab, False)
    tgt, tgt_v = remap(cols.target_codes, cols.target_vocab, True)
    nam, nam_v = remap(cols.name_codes, cols.names, False)
    return EventColumns(
        entity_codes=ent, target_codes=tgt, name_codes=nam,
        values=cols.values[keep], times_us=cols.times_us[keep],
        entity_vocab=ent_v, target_vocab=tgt_v, names=nam_v,
    )


def shard_columns(cols: EventColumns, shard_index: int,
                  shard_count: int) -> EventColumns:
    """The rows of ``cols`` whose ENTITY id hash-routes to shard
    ``shard_index`` of ``shard_count`` (stable_hash % count). Keeping the
    split entity-keyed means all of one entity's events land on one
    shard — the invariant host-local aggregation relies on, identical to
    the reference's rowkey-prefix region split (HBEventsUtil RowKey:81).
    Vocabularies are compacted to the surviving rows."""
    if shard_count <= 1:
        return cols
    import numpy as np

    vmask = np.fromiter(
        (stable_hash(v) % shard_count == shard_index
         for v in cols.entity_vocab),
        np.bool_, count=len(cols.entity_vocab),
    )
    keep = (vmask[cols.entity_codes] if len(cols)
            else np.zeros(0, np.bool_))
    return _compact_columns(cols, keep)


def limit_columns(cols: EventColumns, limit: Optional[int],
                  newest_first: bool = False) -> EventColumns:
    """The ``limit`` rows of ``cols`` by event time (newest when
    ``newest_first``), vocabularies compacted — how every shard-composed
    path applies a row limit AFTER its shard filter, matching find's
    order-then-truncate contract."""
    if limit is None or limit < 0 or len(cols) <= limit:
        return cols
    import numpy as np

    order = np.argsort(cols.times_us, kind="stable")
    if newest_first:
        order = order[::-1]
    take = order[:limit]
    sub = EventColumns(
        entity_codes=cols.entity_codes[take],
        target_codes=cols.target_codes[take],
        name_codes=cols.name_codes[take],
        values=cols.values[take],
        times_us=cols.times_us[take],
        entity_vocab=cols.entity_vocab,
        target_vocab=cols.target_vocab,
        names=cols.names,
    )
    return _compact_columns(sub, np.ones(limit, np.bool_))


def merge_columns(parts: Sequence[EventColumns],
                  time_ordered: bool = False) -> EventColumns:
    """Concatenate columnar scan results (e.g. one per storage shard)
    into one EventColumns with union vocabularies. Codes are remapped
    per part; ``time_ordered=True`` stably sorts the merged rows by
    event time (shard scans interleave times)."""
    import numpy as np

    if not parts:
        return EventColumns(
            entity_codes=np.empty(0, np.int32),
            target_codes=np.empty(0, np.int32),
            name_codes=np.empty(0, np.int32),
            values=np.empty(0, np.float64),
            times_us=np.empty(0, np.int64),
            entity_vocab=[], target_vocab=[], names=[],
        )
    if len(parts) == 1 and not time_ordered:
        return parts[0]
    ent_vocab: Dict[str, int] = {}
    tgt_vocab: Dict[str, int] = {}
    nam_vocab: Dict[str, int] = {}
    ents, tgts, nams, vals, tims = [], [], [], [], []
    for cols in parts:
        def vocab_map(vocab, union):
            return np.fromiter(
                (union.setdefault(v, len(union)) for v in vocab),
                np.int32, count=len(vocab),
            )

        ent_map = vocab_map(cols.entity_vocab, ent_vocab)
        tgt_map = vocab_map(cols.target_vocab, tgt_vocab)
        nam_map = vocab_map(cols.names, nam_vocab)
        ents.append(ent_map[cols.entity_codes] if len(cols)
                    else cols.entity_codes)
        if len(cols):
            tgts.append(np.where(
                cols.target_codes >= 0,
                tgt_map[np.maximum(cols.target_codes, 0)]
                if tgt_map.size else np.int32(-1),
                np.int32(-1),
            ).astype(np.int32))
            nams.append(nam_map[cols.name_codes])
        else:
            tgts.append(cols.target_codes)
            nams.append(cols.name_codes)
        vals.append(cols.values)
        tims.append(cols.times_us)
    merged = EventColumns(
        entity_codes=np.concatenate(ents).astype(np.int32, copy=False),
        target_codes=np.concatenate(tgts).astype(np.int32, copy=False),
        name_codes=np.concatenate(nams).astype(np.int32, copy=False),
        values=np.concatenate(vals),
        times_us=np.concatenate(tims),
        entity_vocab=list(ent_vocab),
        target_vocab=list(tgt_vocab),
        names=list(nam_vocab),
    )
    if time_ordered and len(merged):
        order = np.argsort(merged.times_us, kind="stable")
        merged = EventColumns(
            entity_codes=merged.entity_codes[order],
            target_codes=merged.target_codes[order],
            name_codes=merged.name_codes[order],
            values=merged.values[order],
            times_us=merged.times_us[order],
            entity_vocab=merged.entity_vocab,
            target_vocab=merged.target_vocab,
            names=merged.names,
        )
    return merged


def pack_vocab(vocab) -> tuple:
    """Concatenated UTF-8 bytes + exact (len+1) uint64 prefix offsets —
    the ONE separator-free dictionary layout shared by the npz wire
    format and the native columnar ABI, so ids containing ANY byte
    round-trip correctly."""
    import numpy as np

    bs = [s.encode("utf-8") for s in vocab]
    offsets = np.zeros(len(bs) + 1, np.uint64)
    if bs:
        np.cumsum(
            np.fromiter((len(b) for b in bs), np.uint64, count=len(bs)),
            out=offsets[1:],
        )
    return b"".join(bs), offsets


def unpack_vocab(data, offsets) -> List[str]:
    """Inverse of :func:`pack_vocab`: concatenated bytes (bytes or a
    uint8 array) + prefix offsets -> the vocabulary list."""
    raw = data.tobytes() if hasattr(data, "tobytes") else bytes(data)
    offs = [int(o) for o in offsets]
    return [raw[offs[i]:offs[i + 1]].decode("utf-8")
            for i in range(len(offs) - 1)]


def columns_to_npz(cols: EventColumns) -> bytes:
    """EventColumns -> one .npz blob — the wire format of the bulk
    columnar storage routes."""
    import io

    buf = io.BytesIO()
    columns_to_npz_file(cols, buf)
    return buf.getvalue()


def columns_to_npz_file(cols: EventColumns, f) -> None:
    """Write the npz wire format to an open binary file object — the
    storage server spools bulk scan results to disk this way instead of
    materializing a second in-memory copy of the columns. Vocabularies
    travel via pack_vocab."""
    import numpy as np

    def vocab_arrays(vocab):
        joined, offsets = pack_vocab(vocab)
        return np.frombuffer(joined, dtype=np.uint8), offsets

    ent_b, ent_off = vocab_arrays(cols.entity_vocab)
    tgt_b, tgt_off = vocab_arrays(cols.target_vocab)
    nam_b, nam_off = vocab_arrays(cols.names)
    np.savez(
        f,
        entity_codes=cols.entity_codes,
        target_codes=cols.target_codes,
        name_codes=cols.name_codes,
        values=cols.values,
        times_us=cols.times_us,
        entity_vocab=ent_b, entity_vocab_offsets=ent_off,
        target_vocab=tgt_b, target_vocab_offsets=tgt_off,
        names=nam_b, names_offsets=nam_off,
    )


def npz_to_columns(blob) -> EventColumns:
    """Inverse of columns_to_npz; accepts bytes, a binary file object,
    or a path (np.load's own contract)."""
    import io

    import numpy as np

    z = np.load(io.BytesIO(blob) if isinstance(blob, bytes) else blob)

    def vocab(key):
        raw = z[key].tobytes()
        off = z[key + "_offsets"]
        return [
            raw[int(off[i]):int(off[i + 1])].decode("utf-8")
            for i in range(len(off) - 1)
        ]

    return EventColumns(
        entity_codes=z["entity_codes"],
        target_codes=z["target_codes"],
        name_codes=z["name_codes"],
        values=z["values"],
        times_us=z["times_us"],
        entity_vocab=vocab("entity_vocab"),
        target_vocab=vocab("target_vocab"),
        names=vocab("names"),
    )


# ---------------------------------------------------------------------------
# Abstract DAOs
# ---------------------------------------------------------------------------

class EventStore(abc.ABC):
    """Unified event DAO.

    The reference splits this into LEvents (single-record async CRUD,
    data/.../storage/LEvents.scala:30) and PEvents (Spark RDD bulk
    reads, storage/PEvents.scala:30). Without Spark the split is
    unnecessary: one store serves both the server CRUD path and the
    bulk training-read path (which feeds host numpy buffers).

    OPTIONAL capability — streaming delta reads: backends with an
    append-order sequence expose ``delta_cursor(app_id, channel_id)``
    and ``find_columnar_since(app_id, channel_id, cursor=...)`` →
    ``(EventColumns, new_cursor, rebased)`` returning exactly the live
    rows appended since the cursor (the eventlog backend implements
    this natively; workflow/stream.py feature-detects via hasattr).
    """

    @abc.abstractmethod
    def init(self, app_id: int, channel_id: Optional[int] = None) -> None:
        """Create the event table/log for an app (ref: LEvents.init)."""

    @abc.abstractmethod
    def remove(self, app_id: int, channel_id: Optional[int] = None) -> None:
        """Drop the event table/log (ref: LEvents.remove)."""

    @abc.abstractmethod
    def insert(self, event: Event, app_id: int, channel_id: Optional[int] = None) -> str:
        """Append one event, returning its assigned eventId."""

    def insert_batch(
        self, events: List[Event], app_id: int, channel_id: Optional[int] = None
    ) -> List[str]:
        """Bulk append (ref: PEvents.write:124). Backends with
        transactions override this to commit once."""
        ids = [self.insert(e, app_id, channel_id) for e in events]
        if ids:
            # freshness clock (obs/perfacct.py): one note per accepted
            # batch — pio_model_staleness_seconds measures how long
            # these rows wait for a servable model
            from predictionio_tpu.obs import dataobs, perfacct

            perfacct.note_ingest()
            dataobs.DATAOBS.observe_events(app_id, events)
        return ids

    @abc.abstractmethod
    def get(self, event_id: str, app_id: int, channel_id: Optional[int] = None) -> Optional[Event]:
        ...

    @abc.abstractmethod
    def delete(self, event_id: str, app_id: int, channel_id: Optional[int] = None) -> bool:
        ...

    @abc.abstractmethod
    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[List[str]] = None,
        target_entity_type: Any = UNSET,
        target_entity_id: Any = UNSET,
        limit: Optional[int] = None,
        reversed: bool = False,
    ) -> List[Event]:
        """Filtered scan ordered by event time (ref: PEvents.find:70).

        ``limit=-1``/``None`` means all. ``reversed=True`` returns newest
        first (ref: GET /events.json ``reversed`` param).
        """

    # -- derived ------------------------------------------------------------
    @staticmethod
    def check_shard_params(shard_index: Optional[int],
                           shard_count: Optional[int]) -> None:
        """Validate the optional entity-hash read-shard pair (both set
        or neither; index in range). Shared by every find_columnar."""
        if (shard_index is None) != (shard_count is None):
            raise ValueError(
                "shard_index and shard_count must be given together"
            )
        if shard_count is not None and not 0 <= shard_index < shard_count:
            raise ValueError(
                f"shard_index {shard_index} out of range for "
                f"shard_count {shard_count}"
            )

    def find_columnar(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        value_property: Optional[str] = None,
        time_ordered: bool = True,
        shard_index: Optional[int] = None,
        shard_count: Optional[int] = None,
        **find_kwargs,
    ) -> EventColumns:
        """Filtered scan as dict-encoded columns (see EventColumns).
        ``time_ordered=False`` lets backends skip result ordering (bulk
        training reads don't need it).

        ``shard_index``/``shard_count`` select the entity-hash read
        shard (stable_hash(entity_id) % count == index): each of N
        training hosts reads only its ~1/N of the rows — the role of the
        reference's per-executor HBase region scans
        (hbase/HBPEvents.scala:48). All of one entity's events stay in
        one shard.

        Default implementation converts ``find`` results; the native
        eventlog backend overrides with a single C++ pass that never
        builds Event objects (SURVEY.md §7 hard-part (b): 20M-scale
        string-id indexing).
        """
        import numpy as np

        self.check_shard_params(shard_index, shard_count)
        sharding = shard_count is not None and shard_count > 1
        # a row limit applies AFTER the shard filter (find's
        # order-then-truncate semantics per shard), so the limited scan
        # must run unlimited first when a shard filter is active
        limit = find_kwargs.pop("limit", None) if sharding else None
        events = self.find(app_id, channel_id=channel_id, **find_kwargs)
        if sharding:
            events = [
                e for e in events
                if stable_hash(e.entity_id) % shard_count == shard_index
            ]
            if limit is not None and limit >= 0:
                events = events[:limit]
        n = len(events)
        ent_codes = np.empty(n, np.int32)
        tgt_codes = np.empty(n, np.int32)
        name_codes = np.empty(n, np.int32)
        values = np.full(n, np.nan, np.float64)
        times_us = np.empty(n, np.int64)
        ent_vocab: Dict[str, int] = {}
        tgt_vocab: Dict[str, int] = {}
        name_vocab: Dict[str, int] = {}
        epoch = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)
        for i, e in enumerate(events):
            ent_codes[i] = ent_vocab.setdefault(e.entity_id, len(ent_vocab))
            if e.target_entity_id is None:
                tgt_codes[i] = -1
            else:
                tgt_codes[i] = tgt_vocab.setdefault(
                    e.target_entity_id, len(tgt_vocab)
                )
            name_codes[i] = name_vocab.setdefault(e.event, len(name_vocab))
            times_us[i] = (e.event_time - epoch) // _dt.timedelta(microseconds=1)
            if value_property is not None:
                v = e.properties.get_opt(value_property)
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    values[i] = float(v)
        return EventColumns(
            entity_codes=ent_codes,
            target_codes=tgt_codes,
            name_codes=name_codes,
            values=values,
            times_us=times_us,
            entity_vocab=list(ent_vocab),
            target_vocab=list(tgt_vocab),
            names=list(name_vocab),
        )

    def insert_columnar(
        self,
        cols: EventColumns,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        entity_type: str,
        target_entity_type: Optional[str] = None,
        value_property: Optional[str] = None,
    ) -> int:
        """Bulk append from dict-encoded columns — the ingest mirror of
        ``find_columnar`` (ref: PEvents.write:124 bulk RDD writes; the
        path behind `pio import` at scale). ``values`` NaN = no
        property; ``target_codes`` -1 = no target. Event times come
        from ``times_us``; fresh event ids are assigned. Returns the
        row count. The native eventlog overrides with a C++ packer."""
        import math

        from predictionio_tpu.data.event import Event

        epoch = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)
        n = len(cols)
        chunk = 100_000
        for s in range(0, n, chunk):
            events = []
            for i in range(s, min(s + chunk, n)):
                props: Dict[str, Any] = {}
                v = float(cols.values[i]) if value_property is not None else math.nan
                if not math.isnan(v):
                    props[value_property] = v
                tc = int(cols.target_codes[i])
                events.append(
                    Event(
                        event=cols.names[cols.name_codes[i]],
                        entity_type=entity_type,
                        entity_id=cols.entity_vocab[cols.entity_codes[i]],
                        target_entity_type=target_entity_type if tc >= 0 else None,
                        target_entity_id=cols.target_vocab[tc] if tc >= 0 else None,
                        properties=props,
                        event_time=epoch
                        + _dt.timedelta(microseconds=int(cols.times_us[i])),
                    )
                )
            self.insert_batch(events, app_id, channel_id)
        if n:
            from predictionio_tpu.obs import perfacct

            perfacct.note_ingest()
        return n

    def compact(self, app_id: int, channel_id: Optional[int] = None):
        """Reclaim space held by deleted/superseded events (the HBase
        major-compaction role). Backends without physical garbage (the
        in-place stores) return None; the native eventlog overrides."""
        return None

    def aggregate_properties(
        self,
        app_id: int,
        entity_type: str,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        required: Optional[List[str]] = None,
    ) -> Dict[str, PropertyMap]:
        """Materialize entity properties (ref: PEvents.aggregateProperties:95)."""
        from predictionio_tpu.data.aggregation import aggregate_properties_from_events

        events = self.find(
            app_id,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            event_names=["$set", "$unset", "$delete"],
        )
        return aggregate_properties_from_events(events, required=required)


class AppsRepo(abc.ABC):
    """ref: Apps.scala"""

    @abc.abstractmethod
    def insert(self, name: str, description: Optional[str] = None) -> App: ...
    @abc.abstractmethod
    def get(self, app_id: int) -> Optional[App]: ...
    @abc.abstractmethod
    def get_by_name(self, name: str) -> Optional[App]: ...
    @abc.abstractmethod
    def get_all(self) -> List[App]: ...
    @abc.abstractmethod
    def update(self, app: App) -> None: ...
    @abc.abstractmethod
    def delete(self, app_id: int) -> None: ...

    def put(self, app: App) -> None:
        """Upsert the FULL record under its existing id — the
        replication / anti-entropy write (the metadata-tier role of
        ES's replica shards, elasticsearch/StorageClient.scala:42).
        Never assigns ids and never re-validates uniqueness: the
        owner's ``insert`` already did both. Backends whose ``update``
        is not an upsert override this."""
        self.update(app)


class AccessKeysRepo(abc.ABC):
    """ref: AccessKeys.scala"""

    @abc.abstractmethod
    def insert(self, access_key: AccessKey) -> str: ...
    @abc.abstractmethod
    def get(self, key: str) -> Optional[AccessKey]: ...
    @abc.abstractmethod
    def get_all(self) -> List[AccessKey]: ...
    @abc.abstractmethod
    def get_by_app_id(self, app_id: int) -> List[AccessKey]: ...
    @abc.abstractmethod
    def update(self, access_key: AccessKey) -> None: ...
    @abc.abstractmethod
    def delete(self, key: str) -> None: ...

    def put(self, access_key: AccessKey) -> None:
        """Replication/anti-entropy upsert (see AppsRepo.put)."""
        self.update(access_key)


class ChannelsRepo(abc.ABC):
    """ref: Channels.scala"""

    @abc.abstractmethod
    def insert(self, name: str, app_id: int) -> Channel: ...
    @abc.abstractmethod
    def get(self, channel_id: int) -> Optional[Channel]: ...
    @abc.abstractmethod
    def get_by_app_id(self, app_id: int) -> List[Channel]: ...
    @abc.abstractmethod
    def delete(self, channel_id: int) -> None: ...
    @abc.abstractmethod
    def put(self, channel: Channel) -> None:
        """Replication/anti-entropy upsert under the record's existing
        id (see AppsRepo.put). Abstract because ChannelsRepo has no
        ``update`` to default to."""


class EngineManifestsRepo(abc.ABC):
    """ref: EngineManifests.scala"""

    @abc.abstractmethod
    def insert(self, manifest: EngineManifest) -> None: ...
    @abc.abstractmethod
    def get(self, id: str, version: str) -> Optional[EngineManifest]: ...
    @abc.abstractmethod
    def get_all(self) -> List[EngineManifest]: ...
    @abc.abstractmethod
    def update(self, manifest: EngineManifest) -> None: ...
    @abc.abstractmethod
    def delete(self, id: str, version: str) -> None: ...

    def put(self, manifest: EngineManifest) -> None:
        """Replication/anti-entropy upsert (see AppsRepo.put)."""
        self.update(manifest)


class EngineInstancesRepo(abc.ABC):
    """ref: EngineInstances.scala"""

    @abc.abstractmethod
    def insert(self, instance: EngineInstance) -> str: ...
    @abc.abstractmethod
    def get(self, id: str) -> Optional[EngineInstance]: ...
    @abc.abstractmethod
    def get_all(self) -> List[EngineInstance]: ...
    @abc.abstractmethod
    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> Optional[EngineInstance]: ...
    @abc.abstractmethod
    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> List[EngineInstance]: ...
    @abc.abstractmethod
    def update(self, instance: EngineInstance) -> None: ...
    @abc.abstractmethod
    def delete(self, id: str) -> None: ...

    def put(self, instance: EngineInstance) -> None:
        """Replication/anti-entropy upsert (see AppsRepo.put)."""
        self.update(instance)


class EvaluationInstancesRepo(abc.ABC):
    """ref: EvaluationInstances.scala"""

    @abc.abstractmethod
    def insert(self, instance: EvaluationInstance) -> str: ...
    @abc.abstractmethod
    def get(self, id: str) -> Optional[EvaluationInstance]: ...
    @abc.abstractmethod
    def get_all(self) -> List[EvaluationInstance]: ...
    @abc.abstractmethod
    def get_completed(self) -> List[EvaluationInstance]: ...
    @abc.abstractmethod
    def update(self, instance: EvaluationInstance) -> None: ...
    @abc.abstractmethod
    def delete(self, id: str) -> None: ...

    def put(self, instance: EvaluationInstance) -> None:
        """Replication/anti-entropy upsert (see AppsRepo.put)."""
        self.update(instance)


class ModelsRepo(abc.ABC):
    """ref: Models.scala — model blobs keyed by engine-instance id."""

    @abc.abstractmethod
    def insert(self, model: Model) -> None: ...
    @abc.abstractmethod
    def get(self, id: str) -> Optional[Model]: ...
    @abc.abstractmethod
    def delete(self, id: str) -> None: ...

    def size(self, id: str) -> Optional[int]:
        """Blob length in bytes, or None when absent — the OOM
        preflight's question (obs/memacct.py prices a deploy BEFORE
        anything loads). Backends override with a metadata read
        (stat / SELECT length) so the preflight never downloads the
        blob the deploy is about to fetch anyway; this base fallback
        fetches and measures."""
        model = self.get(id)
        return None if model is None else len(model.models)
    @abc.abstractmethod
    def list(self) -> List[Dict[str, Any]]:
        """Inventory for replica reconciliation: one
        ``{"id", "bytes", "sha256"}`` per stored blob (the role of
        HDFS's block reports under 3x replication,
        hdfs/HDFSModels.scala:28). A maintenance-path call — the
        hash walk is priced accordingly."""


class StorageClient(abc.ABC):
    """One configured storage source (ref: BaseStorageClient, Storage.scala:298)."""

    def __init__(self, config: Dict[str, str]):
        self.config = config

    @abc.abstractmethod
    def events(self) -> EventStore: ...
    @abc.abstractmethod
    def apps(self) -> AppsRepo: ...
    @abc.abstractmethod
    def access_keys(self) -> AccessKeysRepo: ...
    @abc.abstractmethod
    def channels(self) -> ChannelsRepo: ...
    @abc.abstractmethod
    def engine_manifests(self) -> EngineManifestsRepo: ...
    @abc.abstractmethod
    def engine_instances(self) -> EngineInstancesRepo: ...
    @abc.abstractmethod
    def evaluation_instances(self) -> EvaluationInstancesRepo: ...
    @abc.abstractmethod
    def models(self) -> ModelsRepo: ...

    def health_check(self) -> bool:
        """Backend reachability probe (ref: Storage.verifyAllDataObjects
        instantiates each DAO against its live backend). Local backends
        are healthy by construction; network backends override."""
        return True


# ---------------------------------------------------------------------------
# Registry + env config
# ---------------------------------------------------------------------------

_BACKENDS: Dict[str, type] = {}


def register_backend(type_name: str, client_cls: type) -> None:
    _BACKENDS[type_name] = client_cls


def _load_backends() -> None:
    # import side-effect registers the built-in backends (the native
    # eventlog backend compiles lazily — importing it is cheap)
    from predictionio_tpu.data.backends import (  # noqa: F401
        memory, localfs, sqlite, eventlog, rest,
    )


_SOURCE_RE = re.compile(r"^PIO_STORAGE_SOURCES_([^_]+)_(.+)$")
_REPO_RE = re.compile(r"^PIO_STORAGE_REPOSITORIES_([^_]+)_(NAME|SOURCE)$")

REPOSITORIES = ("METADATA", "EVENTDATA", "MODELDATA")


class Storage:
    """Resolved storage: repositories mapped to live StorageClients.

    ref: Storage.scala:40-166 — sourcesToClientMeta + repositoriesToDataObjectMeta.
    """

    def __init__(self, clients: Dict[str, StorageClient], repo_to_source: Dict[str, str]):
        self._clients = clients
        self._repo_to_source = repo_to_source

    def client_for(self, repo: str) -> StorageClient:
        # the chaos harness's storage seam: every repository access —
        # DAO lookups, health probes, model loads — funnels through
        # here, so injected latency/errors/hangs hit local AND network
        # backends identically (resilience/chaos.py; ChaosError is a
        # ConnectionError, indistinguishable from a real outage)
        chaos.inject("storage")
        source = self._repo_to_source.get(repo.upper())
        if source is None or source not in self._clients:
            raise StorageError(f"repository {repo} has no configured source")
        return self._clients[source]

    # -- the accessors every layer uses (ref: Storage.getMetaData*/getLEvents/...) --
    def events(self) -> EventStore:
        return self.client_for("EVENTDATA").events()

    def apps(self) -> AppsRepo:
        return self.client_for("METADATA").apps()

    def access_keys(self) -> AccessKeysRepo:
        return self.client_for("METADATA").access_keys()

    def channels(self) -> ChannelsRepo:
        return self.client_for("METADATA").channels()

    def engine_manifests(self) -> EngineManifestsRepo:
        return self.client_for("METADATA").engine_manifests()

    def engine_instances(self) -> EngineInstancesRepo:
        return self.client_for("METADATA").engine_instances()

    def evaluation_instances(self) -> EvaluationInstancesRepo:
        return self.client_for("METADATA").evaluation_instances()

    def models(self) -> ModelsRepo:
        return self.client_for("MODELDATA").models()

    def verify_all_data_objects(self) -> Dict[str, bool]:
        """ref: Storage.verifyAllDataObjects:237 — used by `pio status`."""
        results: Dict[str, bool] = {}
        for repo in REPOSITORIES:
            try:
                results[repo] = self.client_for(repo).health_check()
            except Exception as e:
                log.warning("health check failed for %s: %s: %s",
                            repo, type(e).__name__, e)
                results[repo] = False
        return results

    def health_details(self) -> Dict[str, Dict[str, bool]]:
        """Per-repo, per-shard health for backends that expose it (the
        sharded rest source) — `pio status` names a down shard instead
        of a bare repo-level FAILED. Single-shard backends report one
        empty-named entry."""
        out: Dict[str, Dict[str, bool]] = {}
        probed: Dict[int, Dict[str, bool]] = {}  # one probe per client,
        # not per repo — three repos on one source ping its shards once
        for repo in REPOSITORIES:
            try:
                client = self.client_for(repo)
                cached = probed.get(id(client))
                if cached is None:
                    detail = getattr(client, "health_detail", None)
                    cached = (dict(detail()) if detail is not None
                              else {"": client.health_check()})
                    probed[id(client)] = cached
                out[repo] = dict(cached)
            except Exception as e:
                log.warning("health detail probe failed for %s: %s: %s",
                            repo, type(e).__name__, e)
                out[repo] = {"": False}
        return out

    def serving_status(self) -> Dict[str, Dict[str, Any]]:
        """Tier-resolved health for `pio status` exit codes: for each
        repository, whether its tier can still ANSWER (a replicated
        source serves through surviving replicas) and whether it is
        degraded (serving, but some endpoint down). Complements the
        deliberately conservative verify_all_data_objects, which fails
        a source on ANY down endpoint."""
        out: Dict[str, Dict[str, Any]] = {}
        probed: Dict[int, Dict[str, Any]] = {}  # one probe per client
        for repo in REPOSITORIES:
            try:
                client = self.client_for(repo)
                tiers = probed.get(id(client))
                if tiers is None:
                    fn = getattr(client, "health_tiers", None)
                    if fn is not None:
                        tiers = dict(fn())
                    else:
                        up = bool(client.health_check())
                        tiers = {"endpoints": {"": up},
                                 "metadata_serving": up,
                                 "events_serving": up, "all_up": up}
                    probed[id(client)] = tiers
                serving = (tiers["events_serving"] if repo == "EVENTDATA"
                           else tiers["metadata_serving"])
                out[repo] = {
                    "serving": bool(serving),
                    "degraded": bool(serving) and not tiers["all_up"],
                    "endpoints": dict(tiers["endpoints"]),
                }
            except Exception as e:
                log.warning("serving-status probe failed for %s: %s: %s",
                            repo, type(e).__name__, e)
                out[repo] = {"serving": False, "degraded": False,
                             "endpoints": {"": False}}
        return out

    # -- construction -------------------------------------------------------
    @staticmethod
    def from_env(env: Optional[Dict[str, str]] = None) -> "Storage":
        """Parse PIO_STORAGE_* env vars (ref: Storage.scala:45-128).

        With no storage vars at all, defaults to a single localfs source
        rooted at ``$PIO_FS_BASEDIR`` (default ``~/.pio_store``) serving
        all three repositories.
        """
        _load_backends()
        env = dict(env if env is not None else os.environ)
        sources: Dict[str, Dict[str, str]] = {}
        repos: Dict[str, Dict[str, str]] = {}
        for key, value in env.items():
            m = _SOURCE_RE.match(key)
            if m:
                sources.setdefault(m.group(1), {})[m.group(2)] = value
                continue
            m = _REPO_RE.match(key)
            if m:
                repos.setdefault(m.group(1), {})[m.group(2)] = value

        if not sources:
            basedir = env.get("PIO_FS_BASEDIR", os.path.expanduser("~/.pio_store"))
            sources = {"LOCALFS": {"TYPE": "localfs", "PATH": basedir}}
            repos = {r: {"NAME": r.lower(), "SOURCE": "LOCALFS"} for r in REPOSITORIES}

        clients: Dict[str, StorageClient] = {}
        for name, cfg in sources.items():
            type_name = cfg.get("TYPE")
            if type_name not in _BACKENDS:
                raise StorageError(
                    f"storage source {name}: unknown TYPE {type_name!r} "
                    f"(known: {sorted(_BACKENDS)})"
                )
            clients[name] = _BACKENDS[type_name](cfg)

        repo_to_source: Dict[str, str] = {}
        for repo in REPOSITORIES:
            cfg = repos.get(repo)
            if cfg and cfg.get("SOURCE"):
                repo_to_source[repo] = cfg["SOURCE"]
            elif len(clients) == 1:
                repo_to_source[repo] = next(iter(clients))
        return Storage(clients, repo_to_source)


# ---------------------------------------------------------------------------
# Process-wide singleton (overridable for tests / embedding)
# ---------------------------------------------------------------------------

_storage_lock = threading.Lock()
_storage: Optional[Storage] = None


def get_storage() -> Storage:
    global _storage
    with _storage_lock:
        if _storage is None:
            _storage = Storage.from_env()
        return _storage


def set_storage(storage: Optional[Storage]) -> None:
    """Install/replace (or with None, reset) the process-wide storage."""
    global _storage
    with _storage_lock:
        _storage = storage
