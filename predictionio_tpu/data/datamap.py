"""Typed JSON property bags.

Behavior contract from the reference's DataMap / PropertyMap
(data/.../storage/DataMap.scala:38, data/.../storage/PropertyMap.scala):
a DataMap is an immutable map of field name -> JSON value with typed
accessors (`get[T]` raising on missing field, `get_opt[T]` returning
None) and merge semantics where the right-hand side wins per key.
PropertyMap additionally carries first_updated / last_updated times, the
result of aggregating $set/$unset/$delete event streams.
"""

from __future__ import annotations

import datetime as _dt
import json
from typing import Any, Iterator, Mapping, Optional

_MISSING = object()


class DataMapError(KeyError):
    """Raised when a required field is missing (ref: DataMap.scala getException)."""


def _freeze(value: Any):
    """Hashable, ==-consistent view of a JSON value (1 and 1.0 freeze equal)."""
    if isinstance(value, dict):
        return frozenset((k, _freeze(v)) for k, v in value.items())
    if isinstance(value, list):
        return tuple(_freeze(v) for v in value)
    return value


class DataMap:
    """Immutable JSON property bag with typed accessors.

    Deliberately NOT a collections.abc.Mapping: the reference contract
    (DataMap.scala ``get[T]`` raising on a missing field) conflicts with
    ``Mapping.get``'s return-default semantics, so DataMap implements
    the read-only dict protocol itself.
    """

    __slots__ = ("_fields",)

    def __init__(self, fields: Optional[Mapping[str, Any]] = None):
        self._fields: dict = dict(fields) if fields else {}

    # -- dict protocol ------------------------------------------------------
    def __getitem__(self, key: str) -> Any:
        try:
            return self._fields[key]
        except KeyError:
            raise DataMapError(f"The field {key} is required.")

    def __iter__(self) -> Iterator[str]:
        return iter(self._fields)

    def __len__(self) -> int:
        return len(self._fields)

    def __contains__(self, key: object) -> bool:
        return key in self._fields

    def keys(self):
        return self._fields.keys()

    def values(self):
        return self._fields.values()

    def items(self):
        return self._fields.items()

    def __eq__(self, other: object) -> bool:
        if isinstance(other, DataMap):
            return self._fields == other._fields
        if isinstance(other, Mapping):
            return self._fields == dict(other)
        return NotImplemented

    def __hash__(self):
        return hash(frozenset((k, _freeze(v)) for k, v in self._fields.items()))

    def __repr__(self) -> str:
        return f"DataMap({self._fields!r})"

    # -- typed accessors ----------------------------------------------------
    def get(self, key: str, expected_type: Optional[type] = None, default: Any = _MISSING) -> Any:
        """Return the field value; raise DataMapError if absent (ref: get[T]).

        If ``expected_type`` is given, coerce compatible primitives
        (int->float) and raise TypeError on mismatch. For dict.get
        compatibility, a non-type second positional argument is treated
        as a default instead.
        """
        if expected_type is not None and not isinstance(expected_type, type):
            default, expected_type = expected_type, None
        if key not in self._fields:
            if default is not _MISSING:
                return default
            raise DataMapError(f"The field {key} is required.")
        value = self._fields[key]
        if expected_type is not None:
            value = _coerce(key, value, expected_type)
        return value

    def get_opt(self, key: str, expected_type: Optional[type] = None, default: Any = None) -> Any:
        if key not in self._fields:
            return default
        return self.get(key, expected_type)

    def get_or_else(self, key: str, default: Any) -> Any:
        return self._fields.get(key, default)

    # -- transformation -----------------------------------------------------
    def merge(self, other: "DataMap | Mapping[str, Any]") -> "DataMap":
        """Right-biased merge (ref: DataMap.scala ``++``)."""
        merged = dict(self._fields)
        merged.update(dict(other))
        return DataMap(merged)

    def remove(self, keys) -> "DataMap":
        """Drop the given keys (ref: DataMap.scala ``--``)."""
        drop = set(keys)
        return DataMap({k: v for k, v in self._fields.items() if k not in drop})

    def keyset(self) -> set:
        return set(self._fields)

    def to_dict(self) -> dict:
        return dict(self._fields)

    def to_json(self) -> str:
        return json.dumps(self._fields, sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "DataMap":
        return cls(json.loads(s))


def _coerce(key: str, value: Any, expected_type: type) -> Any:
    if expected_type is float and isinstance(value, int) and not isinstance(value, bool):
        return float(value)
    if expected_type is int and isinstance(value, bool):
        raise TypeError(f"field {key}: expected int, got bool")
    if not isinstance(value, expected_type):
        raise TypeError(
            f"field {key}: expected {expected_type.__name__}, got {type(value).__name__}"
        )
    return value


class PropertyMap(DataMap):
    """DataMap + first/last update times (ref: PropertyMap.scala)."""

    __slots__ = ("first_updated", "last_updated")

    def __init__(
        self,
        fields: Optional[Mapping[str, Any]],
        first_updated: _dt.datetime,
        last_updated: _dt.datetime,
    ):
        super().__init__(fields)
        self.first_updated = first_updated
        self.last_updated = last_updated

    def __repr__(self) -> str:
        return (
            f"PropertyMap({self.to_dict()!r}, first_updated={self.first_updated}, "
            f"last_updated={self.last_updated})"
        )

    def __eq__(self, other: object) -> bool:
        if isinstance(other, PropertyMap):
            return (
                self.to_dict() == other.to_dict()
                and self.first_updated == other.first_updated
                and self.last_updated == other.last_updated
            )
        return super().__eq__(other)

    __hash__ = DataMap.__hash__
