"""Batch views: filter/fold helpers over event lists (legacy surface).

Behavior contract from the reference's deprecated-but-shipped view API
(data/.../view/LBatchView.scala): `EventSeq` with predicate filtering
(event name, entity type, time window), per-entity time-ordered folds
(`aggregateByEntityOrdered`, LBatchView.scala:120), and the
$set/$unset/$delete DataMap aggregator (ViewAggregators,
LBatchView.scala:69). `BatchView` binds an app (+ channel) and reads
once through the Storage layer (LBatchView.scala:135).

One deliberate divergence: the reference's start-time predicate drops
events AT the start instant (LBatchView.scala:36 excludes isEqual —
inconsistent with its own find API); here the window is the same
half-open [start, until) used everywhere else in this framework.
"""

from __future__ import annotations

import datetime as _dt
from typing import Any, Callable, Dict, List, Optional, TypeVar

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import Storage, get_storage
from predictionio_tpu.data.store import resolve_app

T = TypeVar("T")


def datamap_aggregator() -> Callable[[Optional[dict], Event], Optional[dict]]:
    """Fold step materializing entity properties from $set/$unset/$delete
    (ref: ViewAggregators.getDataMapAggregator, LBatchView.scala:69)."""

    def op(props: Optional[dict], e: Event) -> Optional[dict]:
        if e.event == "$set":
            merged = dict(props) if props else {}
            merged.update(e.properties.to_dict())
            return merged
        if e.event == "$unset":
            if props is None:
                return None
            return {k: v for k, v in props.items()
                    if k not in e.properties.to_dict()}
        if e.event == "$delete":
            return None
        return props

    return op


class EventSeq:
    """A filterable, foldable event list (ref: EventSeq, LBatchView.scala:105)."""

    def __init__(self, events: List[Event]):
        self.events = list(events)

    def __len__(self) -> int:
        return len(self.events)

    def __iter__(self):
        return iter(self.events)

    def filter(
        self,
        event: Optional[str] = None,
        entity_type: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        predicate: Optional[Callable[[Event], bool]] = None,
    ) -> "EventSeq":
        out = self.events
        if event is not None:
            out = [e for e in out if e.event == event]
        if entity_type is not None:
            out = [e for e in out if e.entity_type == entity_type]
        if start_time is not None:
            out = [e for e in out if e.event_time >= start_time]
        if until_time is not None:
            out = [e for e in out if e.event_time < until_time]
        if predicate is not None:
            out = [e for e in out if predicate(e)]
        return EventSeq(out)

    def aggregate_by_entity_ordered(
        self, init: T, op: Callable[[T, Event], T]
    ) -> Dict[str, T]:
        """Per-entity fold in event-time order
        (ref: aggregateByEntityOrdered, LBatchView.scala:120)."""
        by_entity: Dict[str, List[Event]] = {}
        for e in self.events:
            by_entity.setdefault(e.entity_id, []).append(e)
        out: Dict[str, T] = {}
        for eid, evs in by_entity.items():
            acc = init
            for e in sorted(evs, key=lambda e: e.event_time):
                acc = op(acc, e)
            out[eid] = acc
        return out

    def aggregate_properties(self) -> Dict[str, dict]:
        """Materialized property map per entity, dropping deleted ones
        (ref: LBatchView.aggregateProperties, LBatchView.scala:144)."""
        folded = self.aggregate_by_entity_ordered(None, datamap_aggregator())
        return {k: v for k, v in folded.items() if v is not None}


class BatchView:
    """One-shot event snapshot of an app (ref: LBatchView, LBatchView.scala:131)."""

    def __init__(
        self,
        app_name: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        storage: Optional[Storage] = None,
    ):
        st = storage or get_storage()
        app_id, channel_id = resolve_app(app_name, channel_name, st)
        self.events = EventSeq(
            st.events().find(
                app_id, channel_id=channel_id,
                start_time=start_time, until_time=until_time,
            )
        )

    def filter(self, **kwargs) -> EventSeq:
        return self.events.filter(**kwargs)

    def aggregate_properties(self, entity_type: Optional[str] = None) -> Dict[str, dict]:
        seq = self.events if entity_type is None else self.events.filter(
            entity_type=entity_type
        )
        return seq.aggregate_properties()
