"""The append-only Event model.

Behavior contract from the reference's Event + EventValidation
(data/.../storage/Event.scala:37,57): an event has
event name, entityType/entityId, optional targetEntityType/Id,
a properties DataMap, eventTime, tags, optional prId, and creationTime.
Reserved special events are ``$set`` / ``$unset`` / ``$delete``; other
names starting with ``$`` are rejected, and the ``pio_`` prefix is
reserved for entity types, target entity types, and property names.
"""

from __future__ import annotations

import datetime as _dt
import uuid
from dataclasses import dataclass, field, replace
from typing import Any, List, Mapping, Optional

from predictionio_tpu.data.datamap import DataMap

UTC = _dt.timezone.utc

#: ref: Event.scala:57 EventValidation.specialEvents
SPECIAL_EVENTS = frozenset({"$set", "$unset", "$delete"})


class EventValidationError(ValueError):
    """Raised when an event violates the validation contract."""


def _now() -> _dt.datetime:
    return _dt.datetime.now(tz=UTC)


@dataclass(frozen=True)
class Event:
    """One immutable event (ref: Event.scala:37)."""

    event: str
    entity_type: str
    entity_id: str
    target_entity_type: Optional[str] = None
    target_entity_id: Optional[str] = None
    properties: DataMap = field(default_factory=DataMap)
    event_time: _dt.datetime = field(default_factory=_now)
    tags: tuple = ()
    pr_id: Optional[str] = None
    event_id: Optional[str] = None
    creation_time: _dt.datetime = field(default_factory=_now)

    def __post_init__(self):
        if not isinstance(self.properties, DataMap):
            object.__setattr__(self, "properties", DataMap(self.properties))
        if isinstance(self.tags, list):
            object.__setattr__(self, "tags", tuple(self.tags))
        for attr in ("event_time", "creation_time"):
            t = getattr(self, attr)
            if t.tzinfo is None:
                object.__setattr__(self, attr, t.replace(tzinfo=UTC))

    def with_id(self, event_id: Optional[str] = None) -> "Event":
        return replace(self, event_id=event_id or uuid.uuid4().hex)

    # -- serialization ------------------------------------------------------
    def to_dict(self, api_format: bool = True) -> dict:
        """JSON-ready dict (ref: EventJson4sSupport.scala API format)."""
        d: dict = {
            "event": self.event,
            "entityType": self.entity_type,
            "entityId": self.entity_id,
        }
        if self.event_id is not None:
            d["eventId"] = self.event_id
        if self.target_entity_type is not None:
            d["targetEntityType"] = self.target_entity_type
        if self.target_entity_id is not None:
            d["targetEntityId"] = self.target_entity_id
        if len(self.properties):
            d["properties"] = self.properties.to_dict()
        d["eventTime"] = _iso(self.event_time)
        if self.tags:
            d["tags"] = list(self.tags)
        if self.pr_id is not None:
            d["prId"] = self.pr_id
        if not api_format:
            d["creationTime"] = _iso(self.creation_time)
        return d

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "Event":
        try:
            event = d["event"]
            entity_type = d["entityType"]
            entity_id = d["entityId"]
        except KeyError as e:
            raise EventValidationError(f"field {e.args[0]} is required") from None
        return cls(
            event=event,
            entity_type=entity_type,
            entity_id=entity_id,
            target_entity_type=d.get("targetEntityType"),
            target_entity_id=d.get("targetEntityId"),
            properties=DataMap(d.get("properties") or {}),
            event_time=_parse_time(d["eventTime"]) if "eventTime" in d else _now(),
            tags=tuple(d.get("tags") or ()),
            pr_id=d.get("prId"),
            event_id=d.get("eventId"),
            creation_time=_parse_time(d["creationTime"]) if "creationTime" in d else _now(),
        )


def _iso(t: _dt.datetime) -> str:
    return t.astimezone(UTC).isoformat().replace("+00:00", "Z")


def _parse_time(s: Any) -> _dt.datetime:
    if isinstance(s, _dt.datetime):
        return s if s.tzinfo else s.replace(tzinfo=UTC)
    if isinstance(s, (int, float)):
        return _dt.datetime.fromtimestamp(s / 1000.0, tz=UTC)
    s = str(s)
    if s.endswith("Z"):
        s = s[:-1] + "+00:00"
    t = _dt.datetime.fromisoformat(s)
    return t if t.tzinfo else t.replace(tzinfo=UTC)


#: ref: Event.scala:104 builtinEntityTypes — the only entity types allowed
#: to use a reserved prefix
BUILTIN_ENTITY_TYPES = frozenset({"pio_pr"})
#: ref: Event.scala:105 builtinProperties — empty: no reserved-prefix
#: property key is allowed
BUILTIN_PROPERTIES: frozenset = frozenset()


def is_reserved_prefix(name: str) -> bool:
    """ref: Event.scala:62 — ``$`` and ``pio_`` prefixes are reserved."""
    return name.startswith("$") or name.startswith("pio_")


def validate_event(e: Event) -> None:
    """Enforce the reference's validation rules (ref: Event.scala:69-116).

    - event / entityType / entityId must be non-empty; target fields,
      when present, non-empty and specified together
    - reserved-prefix (``$``/``pio_``) event names must be one of the
      special events $set/$unset/$delete
    - special events must not have a target entity; $unset requires
      non-empty properties
    - reserved-prefix entityType / targetEntityType allowed only for
      the builtin set ({"pio_pr"}); reserved-prefix property keys are
      never allowed
    """
    if not e.event:
        raise EventValidationError("event must not be empty.")
    if not e.entity_type:
        raise EventValidationError("entityType must not be empty string.")
    if not e.entity_id:
        raise EventValidationError("entityId must not be empty string.")
    if (e.target_entity_type is None) != (e.target_entity_id is None):
        raise EventValidationError(
            "targetEntityType and targetEntityId must be specified together."
        )
    if e.target_entity_type is not None and not e.target_entity_type:
        raise EventValidationError("targetEntityType must not be empty string.")
    if e.target_entity_id is not None and not e.target_entity_id:
        raise EventValidationError("targetEntityId must not be empty string.")
    if e.event == "$unset" and not len(e.properties):
        raise EventValidationError("properties cannot be empty for $unset event")
    if is_reserved_prefix(e.event) and e.event not in SPECIAL_EVENTS:
        raise EventValidationError(f"{e.event} is not a supported reserved event name.")
    if e.event in SPECIAL_EVENTS and e.target_entity_id is not None:
        raise EventValidationError(
            f"Reserved event {e.event} cannot have targetEntity."
        )
    for name, value in (
        ("entityType", e.entity_type),
        ("targetEntityType", e.target_entity_type or ""),
    ):
        if is_reserved_prefix(value) and value not in BUILTIN_ENTITY_TYPES:
            raise EventValidationError(
                f"The {name} {value} is not allowed. "
                "'pio_' is a reserved name prefix."
            )
    for key in e.properties.keyset():
        if is_reserved_prefix(key) and key not in BUILTIN_PROPERTIES:
            raise EventValidationError(
                f"The property {key} is not allowed. 'pio_' is a reserved name prefix."
            )
