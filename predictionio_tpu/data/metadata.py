"""Metadata entities: apps, access keys, channels, engine/evaluation instances, models.

Behavior contract from the reference's metadata DAO layer
(data/.../storage/{Apps,AccessKeys,Channels,EngineManifests,
EngineInstances,EvaluationInstances,Models}.scala): plain records plus
per-entity repositories. The TPU build keeps the same record shapes so
the CLI / servers behave identically, but the repository interface is a
single Python ABC per entity implemented by each storage backend.
"""

from __future__ import annotations

import datetime as _dt
import re
import secrets
from dataclasses import dataclass, field, asdict
from typing import Any, Dict, List, Optional

UTC = _dt.timezone.utc

CHANNEL_NAME_RE = re.compile(r"^[a-zA-Z0-9-]{1,16}$")  # ref: Channels.scala nameConstraint


def _now() -> _dt.datetime:
    return _dt.datetime.now(tz=UTC)


@dataclass
class App:
    """ref: Apps.scala:27"""
    id: int
    name: str
    description: Optional[str] = None


@dataclass
class AccessKey:
    """ref: AccessKeys.scala:27 — key, owning app, allowed-event whitelist."""
    key: str
    appid: int
    events: List[str] = field(default_factory=list)

    @staticmethod
    def generate(appid: int, events: Optional[List[str]] = None) -> "AccessKey":
        # ref: AccessKeys.scala generateKey — 64-char url-safe random key
        return AccessKey(key=secrets.token_urlsafe(48)[:64], appid=appid, events=list(events or []))


@dataclass
class Channel:
    """ref: Channels.scala:27"""
    id: int
    name: str
    appid: int

    @staticmethod
    def is_valid_name(name: str) -> bool:
        return bool(CHANNEL_NAME_RE.match(name))


@dataclass
class EngineManifest:
    """ref: EngineManifests.scala:33 — a registered engine build."""
    id: str
    version: str
    name: str
    description: Optional[str] = None
    files: List[str] = field(default_factory=list)
    engine_factory: str = ""


@dataclass
class EngineInstance:
    """One training run + full params snapshot (ref: EngineInstances.scala:34)."""
    id: str
    status: str  # INIT | TRAINING | COMPLETED | FAILED
    start_time: _dt.datetime
    end_time: _dt.datetime
    engine_id: str
    engine_version: str
    engine_variant: str
    engine_factory: str
    batch: str = ""
    env: Dict[str, str] = field(default_factory=dict)
    runtime_conf: Dict[str, str] = field(default_factory=dict)
    data_source_params: str = ""
    preparator_params: str = ""
    algorithms_params: str = ""
    serving_params: str = ""


@dataclass
class EvaluationInstance:
    """One evaluation run (ref: EvaluationInstances.scala:38)."""
    id: str
    status: str  # INIT | EVALUATING | EVALCOMPLETED | FAILED
    start_time: _dt.datetime
    end_time: _dt.datetime
    evaluation_class: str = ""
    engine_params_generator_class: str = ""
    batch: str = ""
    env: Dict[str, str] = field(default_factory=dict)
    evaluator_results: str = ""
    evaluator_results_html: str = ""
    evaluator_results_json: str = ""


@dataclass
class Model:
    """Serialized model blob for one engine instance (ref: Models.scala:30)."""
    id: str
    models: bytes


def record_to_dict(obj: Any) -> dict:
    d = asdict(obj)
    for k, v in d.items():
        if isinstance(v, _dt.datetime):
            d[k] = v.astimezone(UTC).isoformat()
    return d


def dict_to_record(cls, d: Dict[str, Any]):
    kwargs = dict(d)
    for k, v in kwargs.items():
        if k in ("start_time", "end_time") and isinstance(v, str):
            kwargs[k] = _dt.datetime.fromisoformat(v)
    return cls(**kwargs)
