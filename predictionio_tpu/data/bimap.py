"""Bidirectional id maps for string-id <-> dense-index conversion.

Behavior contract from the reference's BiMap
(data/.../storage/BiMap.scala:25,96+): an immutable bidirectional map
with ``stringInt`` / ``stringLong`` constructors that index a collection
of string keys to contiguous integers 0..n-1 — the bridge between
entity ids in the event store and dense factor-matrix rows on the
device. The TPU build keeps this host-side and numpy-backed so a
20M-key index builds in seconds and converts id columns vectorized.
"""

from __future__ import annotations

from typing import Dict, Generic, Hashable, Iterable, List, Optional, Sequence, TypeVar

import numpy as np

K = TypeVar("K", bound=Hashable)
V = TypeVar("V", bound=Hashable)


class BiMap(Generic[K, V]):
    """Immutable bidirectional map; values must be unique."""

    def __init__(self, forward: Dict[K, V], _inverse: Optional[Dict[V, K]] = None):
        self._f = dict(forward)
        if _inverse is None:
            _inverse = {v: k for k, v in self._f.items()}
            if len(_inverse) != len(self._f):
                raise ValueError("BiMap values must be unique")
        self._i = _inverse

    # -- access -------------------------------------------------------------
    def __getitem__(self, key: K) -> V:
        return self._f[key]

    def get(self, key: K, default=None):
        return self._f.get(key, default)

    def __contains__(self, key: K) -> bool:
        return key in self._f

    def __len__(self) -> int:
        return len(self._f)

    def inverse(self) -> "BiMap[V, K]":
        return BiMap(self._i, self._f)

    def contains_value(self, value: V) -> bool:
        return value in self._i

    def to_dict(self) -> Dict[K, V]:
        return dict(self._f)

    def keys(self):
        return self._f.keys()

    def values(self):
        return self._f.values()

    def items(self):
        return self._f.items()

    # -- batch conversion ---------------------------------------------------
    def take(self, keys: Iterable[K]) -> "BiMap[K, V]":
        """Sub-map restricted to ``keys`` (ref: BiMap.scala take)."""
        return BiMap({k: self._f[k] for k in keys if k in self._f})

    def map_values(self, keys: Sequence[K]) -> List[V]:
        return [self._f[k] for k in keys]

    def to_index_array(self, keys: Sequence[K]) -> np.ndarray:
        """Vectorized key->int conversion (requires an int-valued BiMap)."""
        return np.fromiter((self._f[k] for k in keys), dtype=np.int64, count=len(keys))

    # -- constructors (ref: BiMap.scala stringInt/stringLong) ----------------
    @staticmethod
    def string_int(keys: Iterable[str]) -> "BiMap[str, int]":
        """Index distinct keys to 0..n-1 in first-seen order."""
        forward: Dict[str, int] = {}
        for k in keys:
            if k not in forward:
                forward[k] = len(forward)
        return BiMap(forward)

    string_long = string_int
