"""Bidirectional id maps for string-id <-> dense-index conversion.

Behavior contract from the reference's BiMap
(data/.../storage/BiMap.scala:25,96+): an immutable bidirectional map
with ``stringInt`` / ``stringLong`` constructors that index a collection
of string keys to contiguous integers 0..n-1 — the bridge between
entity ids in the event store and dense factor-matrix rows on the
device. The TPU build keeps this host-side and numpy-backed so a
20M-key index builds in seconds and converts id columns vectorized.
"""

from __future__ import annotations

from typing import Dict, Generic, Hashable, Iterable, List, Optional, Sequence, TypeVar

import numpy as np

K = TypeVar("K", bound=Hashable)
V = TypeVar("V", bound=Hashable)


class BiMap(Generic[K, V]):
    """Immutable bidirectional map; values must be unique."""

    def __init__(self, forward: Dict[K, V], _inverse: Optional[Dict[V, K]] = None):
        if _inverse is None:
            self._f = dict(forward)
            _inverse = {v: k for k, v in self._f.items()}
            if len(_inverse) != len(self._f):
                raise ValueError("BiMap values must be unique")
        else:
            # private fast path (inverse()): both dicts already exist and
            # stay immutable — no O(n) copy
            self._f = forward
        self._i = _inverse

    # -- access -------------------------------------------------------------
    def __getitem__(self, key: K) -> V:
        return self._f[key]

    def get(self, key: K, default=None):
        return self._f.get(key, default)

    def __contains__(self, key: K) -> bool:
        return key in self._f

    def __len__(self) -> int:
        return len(self._f)

    def inverse(self) -> "BiMap[V, K]":
        return BiMap(self._i, self._f)

    def contains_value(self, value: V) -> bool:
        return value in self._i

    def to_dict(self) -> Dict[K, V]:
        return dict(self._f)

    def keys(self):
        return self._f.keys()

    def values(self):
        return self._f.values()

    def items(self):
        return self._f.items()

    # -- batch conversion ---------------------------------------------------
    def take(self, keys: Iterable[K]) -> "BiMap[K, V]":
        """Sub-map restricted to ``keys`` (ref: BiMap.scala take)."""
        return BiMap({k: self._f[k] for k in keys if k in self._f})

    def map_values(self, keys: Sequence[K]) -> List[V]:
        return [self._f[k] for k in keys]

    def to_index_array(self, keys: Sequence[K]) -> np.ndarray:
        """Vectorized key->int conversion (requires an int-valued BiMap)."""
        return np.fromiter((self._f[k] for k in keys), dtype=np.int64, count=len(keys))

    def take_n(self, n: int) -> "BiMap[K, V]":
        """Sub-map of the first ``n`` entries (ref: BiMap.scala take(n))."""
        import itertools

        return BiMap(dict(itertools.islice(self._f.items(), n)))

    # -- constructors (ref: BiMap.scala stringInt/stringLong) ----------------
    @staticmethod
    def string_int(keys: Iterable[str]) -> "BiMap[str, int]":
        """Index distinct keys to 0..n-1 in first-seen order."""
        forward: Dict[str, int] = {}
        for k in keys:
            if k not in forward:
                forward[k] = len(forward)
        return BiMap(forward)

    string_long = string_int

    @staticmethod
    def from_vocab(vocab: Sequence[str]) -> "BiMap[str, int]":
        """Already-distinct keys -> their positions (the dict-encoded
        bulk path: storage.EventColumns vocabularies index directly)."""
        forward = {k: i for i, k in enumerate(vocab)}
        if len(forward) != len(vocab):
            raise ValueError("from_vocab requires distinct keys")
        return BiMap(forward, {i: k for k, i in forward.items()})


class EntityIdIxMap:
    """Entity-id <-> dense-index map (ref: storage/EntityMap.scala:27
    ``EntityIdIxMap``): a thin wrapper around an int-valued BiMap that
    answers lookups in both directions through one object."""

    def __init__(self, id_to_ix: BiMap):
        self.id_to_ix = id_to_ix
        self.ix_to_id = id_to_ix.inverse()

    @staticmethod
    def from_keys(keys: Iterable[str]) -> "EntityIdIxMap":
        return EntityIdIxMap(BiMap.string_long(keys))

    @staticmethod
    def _as_ix(key) -> int:
        """Strict integer coercion: floats/None are lookup bugs, not
        indices — reject instead of truncating."""
        import operator

        return operator.index(key)

    def __call__(self, key):
        """id -> ix for str keys, ix -> id for int keys (the reference's
        overloaded ``apply``)."""
        if isinstance(key, str):
            return self.id_to_ix[key]
        return self.ix_to_id[self._as_ix(key)]

    def __contains__(self, key) -> bool:
        if isinstance(key, str):
            return key in self.id_to_ix
        try:
            return self._as_ix(key) in self.ix_to_id
        except TypeError:
            return False

    def get(self, key, default=None):
        if isinstance(key, str):
            return self.id_to_ix.get(key, default)
        try:
            return self.ix_to_id.get(self._as_ix(key), default)
        except TypeError:
            return default

    def to_dict(self) -> Dict[str, int]:
        return self.id_to_ix.to_dict()

    def __len__(self) -> int:
        return len(self.id_to_ix)

    def take(self, n: int) -> "EntityIdIxMap":
        return EntityIdIxMap(self.id_to_ix.take_n(n))


class EntityMap(EntityIdIxMap, Generic[V]):
    """EntityIdIxMap + per-entity payload (ref: storage/EntityMap.scala:68
    ``EntityMap[A]``): id->data plus the dense index, so factor-matrix
    rows and entity payloads stay aligned. Used by engines that need
    per-entity features next to the index (experimental
    scala-parallel-recommendation-entitymap example)."""

    def __init__(self, id_to_data: Dict[str, V],
                 id_to_ix: Optional[BiMap] = None):
        if id_to_ix is None:
            id_to_ix = BiMap.string_long(id_to_data.keys())
        super().__init__(id_to_ix)
        self.id_to_data = dict(id_to_data)

    def data(self, key) -> V:
        if isinstance(key, str):
            return self.id_to_data[key]
        return self.id_to_data[self.ix_to_id[self._as_ix(key)]]

    def get_data(self, key, default=None):
        if isinstance(key, str):
            return self.id_to_data.get(key, default)
        try:
            rid = self.ix_to_id.get(self._as_ix(key))
        except TypeError:
            return default
        return default if rid is None else self.id_to_data.get(rid, default)

    def take(self, n: int) -> "EntityMap[V]":
        sub = self.id_to_ix.take_n(n)
        return EntityMap(
            {k: self.id_to_data[k] for k in sub.keys()}, sub
        )
