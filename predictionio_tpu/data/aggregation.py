"""Entity-property materialization from $set/$unset/$delete streams.

Behavior contract from the reference's EventOp monoid
(data/.../storage/PEventAggregator.scala:87-209 and
LEventAggregator.scala:24-123): folding an entity's special events in
event-time order yields the entity's current PropertyMap:

  - ``$set``:   merge properties, later event time wins per key
  - ``$unset``: remove the given property keys
  - ``$delete``: drop the entity entirely (a later $set recreates it)

Entities whose fold ends with no live properties-map are excluded from
the aggregate result. first_updated / last_updated track the earliest
and latest contributing special-event times since the last $delete.

The reference computes this as a Spark ``aggregateByKey`` with a
commutative-enough monoid; here the fold is a host-side linear pass per
entity (events pre-sorted by event time), which is the same result.
"""

from __future__ import annotations

import datetime as _dt
from typing import Dict, Iterable, Optional

from predictionio_tpu.data.datamap import PropertyMap
from predictionio_tpu.data.event import Event


class _EntityState:
    """Mutable fold state for one entity (the EventOp monoid's meaning)."""

    __slots__ = ("props", "prop_times", "first_updated", "last_updated", "exists")

    def __init__(self):
        self.props: dict = {}
        self.prop_times: dict = {}
        self.first_updated: Optional[_dt.datetime] = None
        self.last_updated: Optional[_dt.datetime] = None
        self.exists = False

    def _touch(self, t: _dt.datetime) -> None:
        if self.first_updated is None or t < self.first_updated:
            self.first_updated = t
        if self.last_updated is None or t > self.last_updated:
            self.last_updated = t

    def apply(self, e: Event) -> None:
        t = e.event_time
        if e.event == "$set":
            for k, v in e.properties.items():
                # later event time wins per key (ref: PEventAggregator.scala:95)
                prev = self.prop_times.get(k)
                if prev is None or t >= prev:
                    self.props[k] = v
                    self.prop_times[k] = t
            self.exists = True
            self._touch(t)
        elif e.event == "$unset":
            for k in e.properties.keyset():
                prev = self.prop_times.get(k)
                if prev is None or t >= prev:
                    self.props.pop(k, None)
                    self.prop_times[k] = t
            self._touch(t)
        elif e.event == "$delete":
            self.props.clear()
            self.prop_times.clear()
            self.first_updated = None
            self.last_updated = None
            self.exists = False

    def result(self) -> Optional[PropertyMap]:
        if not self.exists or self.first_updated is None:
            return None
        return PropertyMap(self.props, self.first_updated, self.last_updated)


def aggregate_properties_from_events(
    events: Iterable[Event],
    required: Optional[Iterable[str]] = None,
) -> Dict[str, PropertyMap]:
    """Fold special events (for a single entityType) into entityId -> PropertyMap.

    ``required``: keep only entities having all the listed property keys
    (ref: PEventStore.aggregateProperties ``required`` filter).
    """
    states: Dict[str, _EntityState] = {}
    for e in sorted(events, key=lambda ev: (ev.event_time, ev.creation_time)):
        if e.event not in ("$set", "$unset", "$delete"):
            continue
        states.setdefault(e.entity_id, _EntityState()).apply(e)
    out: Dict[str, PropertyMap] = {}
    req = list(required) if required else None
    for entity_id, st in states.items():
        pm = st.result()
        if pm is None:
            continue
        if req is not None and not all(k in pm for k in req):
            continue
        out[entity_id] = pm
    return out
