"""Mesh construction + sharding helpers.

The reference's unit of distribution is the Spark RDD partition; ours is
the device-mesh axis. Algorithms receive a `MeshContext` (the analogue
of the `sc: SparkContext` threaded through every DASE call in the
reference, e.g. controller/Engine.scala:135) and annotate their arrays
with `NamedSharding`s over it; XLA inserts the collectives.

Axis convention (used by the built-in algorithms):

  - ``data``  — batch / entity dimension (users, examples): DP
  - ``model`` — feature / item / expert dimension: TP-style sharding

A 1D mesh collapses ``model`` to size 1. Multi-host: `jax.distributed`
initialization enumerates global devices; the same mesh spec then spans
hosts with DCN between slices (SURVEY.md §5.8 mapping).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


#: The declared mesh axis names. graftlint JT05 parses this assignment
#: statically: a PartitionSpec in ops/, parallel/ or templates/ naming
#: an axis outside this tuple is flagged (the array would be silently
#: replicated instead of sharded). Extend HERE when adding an axis.
MESH_AXES: Tuple[str, ...] = ("data", "model")


def local_device_count() -> int:
    return jax.local_device_count()


def create_mesh(
    axes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a Mesh from axis-name -> size; one size may be -1 (infer).

    Default: all devices on the ``data`` axis, ``model`` axis of 1 —
    pure DP, the layout matching the reference's Spark data parallelism
    (SURVEY.md §2.9). Built-in code must stick to the ``MESH_AXES``
    names; custom meshes (tests, experiments) may name axes freely.
    """
    # every training/serving path builds a mesh before compiling; hook
    # the persistent executable cache here so repeat programs (fixed
    # shapes by design) skip XLA across processes
    from predictionio_tpu.parallel.compile_cache import enable_persistent_cache

    enable_persistent_cache()
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    axes = dict(axes or {"data": -1, "model": 1})
    unknown = [k for k, v in axes.items() if v == -1]
    if len(unknown) > 1:
        raise ValueError("at most one mesh axis may be -1")
    known = math.prod(v for v in axes.values() if v != -1)
    if unknown:
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        axes[unknown[0]] = n // known
    if math.prod(axes.values()) != n:
        raise ValueError(f"mesh {axes} does not cover {n} devices")
    shape = tuple(axes.values())
    return Mesh(np.array(devices).reshape(shape), tuple(axes.keys()))


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


@dataclasses.dataclass
class MeshContext:
    """Runtime context handed to every DASE component.

    The analogue of the reference's SparkContext parameter (built by
    WorkflowContext.scala:24): carries the device mesh, the RNG seed and
    free-form runtime config. Components that never touch a device can
    ignore it entirely (the reference's "local" L* components).
    """

    mesh: Optional[Mesh] = None
    seed: int = 0
    config: Dict[str, str] = dataclasses.field(default_factory=dict)

    def require_mesh(self) -> Mesh:
        if self.mesh is None:
            self.mesh = create_mesh()
        return self.mesh

    def rng(self) -> jax.Array:
        return jax.random.PRNGKey(self.seed)

    # -- sharding sugar -----------------------------------------------------
    def shard(self, *spec) -> NamedSharding:
        return named_sharding(self.require_mesh(), *spec)

    def replicated(self) -> NamedSharding:
        return replicated(self.require_mesh())

    def data_parallel_size(self) -> int:
        mesh = self.require_mesh()
        return mesh.shape.get("data", 1)
