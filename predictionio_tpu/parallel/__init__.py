"""Device mesh, sharding and distributed runtime.

This package is the TPU-native replacement for what the reference
delegates to Apache Spark (SURVEY.md §2.9): instead of RDD partitioning
+ shuffle, computation runs SPMD over a `jax.sharding.Mesh` with XLA
collectives riding ICI; multi-host coordination uses jax.distributed
over DCN instead of Spark's driver/executor control plane.
"""

from predictionio_tpu.parallel.mesh import (
    MeshContext,
    create_mesh,
    local_device_count,
    named_sharding,
    replicated,
)
from predictionio_tpu.parallel.multihost import (
    all_hosts_sum,
    exchange_columns,
    global_array,
    host_shard_by_entity,
    host_shard_slice,
    initialize_from_env,
)

__all__ = [
    "MeshContext",
    "create_mesh",
    "local_device_count",
    "named_sharding",
    "replicated",
    "all_hosts_sum",
    "exchange_columns",
    "global_array",
    "host_shard_by_entity",
    "host_shard_slice",
    "initialize_from_env",
]
