"""Persistent XLA compilation cache.

Everything under ``jit`` is traced once and compiled; at ML-20M scale
the ALS training program costs ~30s of XLA compile — paid, without this
module, on EVERY train, deploy warm-up, and ``/reload``. The framework's
fixed-shape bucketing discipline (ops/ragged.py) exists precisely so
that repeat runs produce byte-identical programs; this module makes
that pay off by caching compiled executables on disk, keyed by program
fingerprint, so warm trains skip XLA entirely.

The reference has no analogue (Spark jobs are interpreted JVM code);
this is a TPU-economics subsystem: compile time is the TPU world's
job-startup tax, as JVM spin-up + jar shipping is Spark's
(SURVEY.md §3.1 runtime notes).

Config:
  PIO_COMPILE_CACHE_DIR  cache directory (default
                         $PIO_FS_BASEDIR/compile_cache, i.e. the same
                         home the localfs storage tier uses)
  PIO_COMPILE_CACHE=0    disable

Multi-process safe: JAX writes entries atomically (temp + rename), so
N trainers sharing one cache dir (e.g. over NFS) only ever read
complete entries; concurrent writers of the same key are idempotent.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

from predictionio_tpu.obs import jaxmon

log = logging.getLogger(__name__)

_enabled_dir: Optional[str] = None


def cache_dir_default() -> str:
    base = os.environ.get("PIO_FS_BASEDIR", os.path.expanduser("~/.pio_store"))
    return os.path.join(base, "compile_cache")


def enable_persistent_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Point JAX's persistent compilation cache at the PIO home.

    Idempotent; returns the active cache directory (None when disabled
    via PIO_COMPILE_CACHE=0 or on failure — the framework must run
    without a writable home, just slower).
    """
    global _enabled_dir
    # hit/miss counters + compile-time histograms (obs/jaxmon.py) come
    # up with the cache: every train/deploy/reload path funnels through
    # here, and the counters are wanted even when the cache dir is
    # disabled (all-miss is exactly the signal an operator needs)
    jaxmon.install()
    if os.environ.get("PIO_COMPILE_CACHE", "1") == "0":
        return None
    if _enabled_dir is not None:
        return _enabled_dir
    path = (cache_dir or os.environ.get("PIO_COMPILE_CACHE_DIR")
            or cache_dir_default())
    try:
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        # the default 1s floor skips small serving/eval programs whose
        # recompiles still dominate /reload latency; cache everything
        # that took meaningful compile time
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # noqa: BLE001 — cache is an optimization
        log.warning("persistent compilation cache unavailable: %s", e)
        return None
    _enabled_dir = path
    log.info("persistent compilation cache at %s", path)
    return path
