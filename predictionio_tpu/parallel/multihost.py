"""Multi-host runtime: jax.distributed init + host-sharded data plane.

The reference scales out through Spark's driver/executor runtime (each
executor reads its HBase region slice, shuffles exchange blocks —
SURVEY.md §2.9). The TPU-native equivalent (§7.9): every host runs this
same program under a single-controller JAX runtime — `jax.distributed`
coordinates over DCN, each host reads its own slice of the event store,
and per-host arrays assemble into global `jax.Array`s over the full
mesh so XLA collectives ride ICI within a slice and DCN across hosts.

Single-host is the degenerate case (process_count == 1, every helper a
cheap identity), so engines written against this module run unchanged
from a laptop CPU mesh to a pod.
"""

from __future__ import annotations

import logging
import os
from typing import Iterable, List, Optional, Sequence, TypeVar

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from predictionio_tpu.data.storage import stable_hash as _stable_hash

logger = logging.getLogger(__name__)

T = TypeVar("T")

_initialized = False


def initialize_from_env() -> bool:
    """Bring up jax.distributed from PIO_* / JAX env vars; idempotent.

    Env contract (mirroring the reference's env-driven config shape,
    conf/pio-env.sh.template):

      PIO_COORDINATOR_ADDRESS  host:port of process 0 (required to opt in)
      PIO_NUM_PROCESSES        world size
      PIO_PROCESS_ID           this host's index

    Returns True when running distributed (after this call), False for
    single-process mode. JAX's own auto-detection (TPU pod metadata)
    still applies when only PIO_COORDINATOR_ADDRESS is unset.
    """
    global _initialized
    if _initialized:
        return jax.process_count() > 1
    addr = os.environ.get("PIO_COORDINATOR_ADDRESS")
    if not addr:
        return jax.process_count() > 1
    num_s = os.environ.get("PIO_NUM_PROCESSES")
    pid_s = os.environ.get("PIO_PROCESS_ID")
    if num_s is None or pid_s is None:
        raise RuntimeError(
            "PIO_COORDINATOR_ADDRESS is set but PIO_NUM_PROCESSES / "
            "PIO_PROCESS_ID are missing — all three are required for "
            "multi-host mode"
        )
    num, pid = int(num_s), int(pid_s)
    jax.distributed.initialize(
        coordinator_address=addr, num_processes=num, process_id=pid
    )
    _initialized = True
    logger.info("jax.distributed up: process %d/%d, %d global devices",
                pid, num, jax.device_count())
    return True


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def host_shard_by_entity(
    items: Iterable[T],
    entity_id: "callable[[T], str]",
    n_hosts: Optional[int] = None,
    host: Optional[int] = None,
) -> List[T]:
    """This host's slice of an event/record stream, split by entity id.

    Hash-partitioning on entity keeps all of one entity's events on one
    host — the property PDataSources rely on for local aggregation
    (the reference gets it from HBase rowkey prefix hashing,
    hbase/HBEventsUtil.scala RowKey:81).
    """
    n = n_hosts if n_hosts is not None else process_count()
    h = host if host is not None else process_index()
    if n <= 1:
        return list(items)
    return [x for x in items if _stable_hash(entity_id(x)) % n == h]


def host_shard_slice(n_total: int, n_hosts: Optional[int] = None,
                     host: Optional[int] = None) -> slice:
    """Contiguous [start, stop) slice of a length-``n_total`` axis owned
    by this host (balanced to within 1)."""
    n = n_hosts if n_hosts is not None else process_count()
    h = host if host is not None else process_index()
    base, extra = divmod(n_total, n)
    start = h * base + min(h, extra)
    return slice(start, start + base + (1 if h < extra else 0))


def broadcast_string(s: str) -> str:
    """Process 0's string on every process (identity single-process).

    The workflow layer uses this for single-writer coordination: all
    hosts run the same training program, but exactly one EngineInstance
    row / model blob may exist per run, so every host must agree on
    process 0's instance id (the reference has no such problem — only
    the Spark driver JVM touches metadata, CoreWorkflow.scala:60-81).
    """
    if jax.process_count() == 1:
        return s
    from jax.experimental import multihost_utils

    raw = np.frombuffer(s.encode("utf-8"), np.uint8)
    n = int(multihost_utils.broadcast_one_to_all(np.int64(raw.size)))
    buf = np.zeros(n, np.uint8)
    buf[: min(raw.size, n)] = raw[:n]
    out = np.asarray(multihost_utils.broadcast_one_to_all(buf))
    return out.tobytes().decode("utf-8")


def barrier(name: str) -> None:
    """Block until every process reaches this point (no-op single
    process). ``name`` must match across processes."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def exchange_columns(cols, time_ordered: bool = False):
    """All-exchange of per-host columnar read shards: every host hands
    in the EventColumns it read (its entity-hash shard of the event
    store, ``find_columnar(shard_index=process_index())``) and receives
    the merged FULL columns.

    This is the TPU-native split of the reference's region-scan +
    shuffle pipeline (hbase/HBPEvents.scala:48 feeding Spark shuffles):
    the storage tier serves each byte ONCE — N hosts each fetch ~1/N of
    the rows — and the re-assembly rides the job's own interconnect
    (jax allgather over DCN) instead of N full scans hammering the
    storage server. Deterministic: shards concatenate in process order,
    so every host assembles identical columns (required — the jitted
    collective train steps must see the same data layout everywhere).
    Pass ``time_ordered=True`` when downstream logic needs global time
    order (per-shard order does NOT survive concatenation).

    Single-process: identity (unless a time sort was asked for).
    """
    if jax.process_count() == 1:
        from predictionio_tpu.data.storage import merge_columns

        return merge_columns([cols], time_ordered=time_ordered)
    from jax.experimental import multihost_utils

    from predictionio_tpu.data.storage import (
        columns_to_npz, merge_columns, npz_to_columns,
    )

    blob = np.frombuffer(columns_to_npz(cols), np.uint8)
    lens = np.asarray(
        multihost_utils.process_allgather(np.array([blob.size], np.int64))
    ).reshape(-1)
    padded = np.zeros(int(lens.max()), np.uint8)
    padded[: blob.size] = blob
    gathered = np.asarray(multihost_utils.process_allgather(padded))
    parts = [
        npz_to_columns(gathered[h, : int(lens[h])].tobytes())
        for h in range(jax.process_count())
    ]
    return merge_columns(parts, time_ordered=time_ordered)


def global_array(
    local: np.ndarray,
    mesh: Mesh,
    *spec,
) -> jax.Array:
    """Assemble per-host shards into one global jax.Array.

    ``local`` is this host's contiguous shard of axis 0 (as produced by
    ``host_shard_slice``); ``spec`` is the PartitionSpec of the GLOBAL
    array. Single-host: a plain device_put with that sharding.
    """
    sharding = NamedSharding(mesh, PartitionSpec(*spec))
    if jax.process_count() == 1:
        return jax.device_put(local, sharding)
    return jax.make_array_from_process_local_data(sharding, local)


def to_host(x: "jax.Array") -> np.ndarray:
    """Device array -> host numpy, correct under multi-host: an array
    sharded across processes spans non-addressable devices and must be
    allgathered first (every host receives the full value)."""
    if getattr(x, "is_fully_addressable", True):
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def all_hosts_sum(x: np.ndarray, mesh: Mesh) -> np.ndarray:
    """Sum a small host-local array across hosts (metadata reconciliation,
    e.g. per-host event counts). Rides the mesh collectives so it works
    wherever a mesh exists; trivial on one host."""
    if jax.process_count() == 1:
        return np.asarray(x)
    # every local device carries a copy of this host's x; the global sum
    # over the device axis counts each host local_device_count times
    stacked = jax.make_array_from_process_local_data(
        NamedSharding(mesh, PartitionSpec(mesh.axis_names[0])),
        np.asarray(x)[None, ...].repeat(jax.local_device_count(), 0),
    )
    summed = jax.jit(
        lambda a: a.sum(axis=0) / jax.local_device_count(),
        out_shardings=NamedSharding(mesh, PartitionSpec()),
    )(stacked)
    return np.asarray(summed)
