"""Experimental admin REST API.

Behavior contract from the reference (tools/.../admin/AdminAPI.scala:64-101
+ CommandClient.scala):

  GET    /                      -> {"status": "alive"}
  GET    /cmd/app               -> list apps with access keys
  POST   /cmd/app {name, description?} -> create app (+ key)
  DELETE /cmd/app/<name>        -> delete app
  DELETE /cmd/app/<name>/data   -> wipe the app's event data

Beyond the reference, every PIO server (this one included) inherits the
shared diagnostics surface from serving/http.py:

  GET  /healthz                 -> liveness (always 200, no probes)
  GET  /readyz                  -> readiness (health probes incl. this
                                   server's storage; 503 on FAILED)
  GET  /metrics                 -> Prometheus exposition (OpenMetrics
                                   with exemplars via Accept)
  GET  /admin/flight[?n=&slow=] -> flight-recorder dump (obs/flight.py):
                                   last N completed request records with
                                   stage timings, span trees, trace ids,
                                   plus periodic metric snapshots
  POST /admin/profile?seconds=N -> on-demand JAX profiler window
                                   (obs/profiler.py); 501 on CPU
  GET  /admin/slo               -> SLO burn-rate evaluation (obs/slo.py)

The ``/admin/*`` routes answer 401 without ``Authorization: Bearer
$PIO_ADMIN_TOKEN`` once that env var is set; health and metrics stay
open for probers and scrapers.
"""

from __future__ import annotations

import json
import logging
from typing import Optional
from urllib.parse import urlparse

from predictionio_tpu.data.storage import Storage, get_storage
from predictionio_tpu.obs import logging as obs_logging
from predictionio_tpu.serving.http import HTTPServerBase, JSONRequestHandler
from predictionio_tpu.tools import commands
from predictionio_tpu.tools.commands import CommandError

log = logging.getLogger(__name__)

DEFAULT_PORT = 7071


def _app_json(info: commands.AppInfo) -> dict:
    return {
        "name": info.app.name,
        "id": info.app.id,
        "description": info.app.description or "",
        "accessKeys": [
            {"key": k.key, "events": list(k.events)} for k in info.access_keys
        ],
        "channels": [{"name": c.name, "id": c.id} for c in info.channels],
    }


class _AdminRequestHandler(JSONRequestHandler):
    server_version = "PIOAdminServer/0.1"

    @property
    def storage(self) -> Storage:
        return self.server_ref.storage

    def do_GET(self):
        path = urlparse(self.path).path
        if path == "/":
            self._send(200, {"status": "alive"})
        elif path == "/cmd/app":
            self._send(200, {
                "status": 1,
                "apps": [_app_json(i) for i in commands.app_list(self.storage)],
            })
        else:
            self._send(404, {"message": "Not Found"})

    def do_POST(self):
        path = urlparse(self.path).path
        if path != "/cmd/app":
            self._send(404, {"message": "Not Found"})
            return
        try:
            payload = self._read_json()
        except json.JSONDecodeError as e:
            self._send(400, {"message": f"invalid JSON: {e}"})
            return
        if not isinstance(payload, dict) or not payload.get("name"):
            self._send(400, {"message": "app name is required"})
            return
        try:
            info = commands.app_new(
                payload["name"], payload.get("description"), self.storage
            )
        except CommandError as e:
            self._send(409, {"message": str(e)})
            return
        self._send(200, {"status": 1, **_app_json(info)})

    def do_DELETE(self):
        parts = [p for p in urlparse(self.path).path.split("/") if p]
        try:
            if len(parts) == 3 and parts[:2] == ["cmd", "app"]:
                commands.app_delete(parts[2], self.storage)
                self._send(200, {"status": 1, "message": f"App deleted: {parts[2]}"})
            elif len(parts) == 4 and parts[:2] == ["cmd", "app"] and parts[3] == "data":
                commands.app_data_delete(parts[2], storage=self.storage)
                self._send(200, {"status": 1, "message": f"App data deleted: {parts[2]}"})
            else:
                self._send(404, {"message": "Not Found"})
        except CommandError as e:
            self._send(404, {"message": str(e)})


class AdminServer(HTTPServerBase):
    """ref: AdminServer.createAdminServer (AdminAPI.scala:113)."""

    def __init__(
        self,
        storage: Optional[Storage] = None,
        host: str = "0.0.0.0",
        port: int = DEFAULT_PORT,
    ):
        self.storage = storage or get_storage()
        super().__init__(host, port, _AdminRequestHandler)


def main(argv=None) -> None:
    import argparse

    parser = argparse.ArgumentParser(description="PIO-TPU admin API server")
    parser.add_argument("--ip", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=DEFAULT_PORT)
    args = parser.parse_args(argv)
    obs_logging.setup(level=logging.INFO)
    server = AdminServer(host=args.ip, port=args.port)
    log.info("admin server running on %s:%s", args.ip, server.port)
    server.serve_forever()


if __name__ == "__main__":
    main()
