"""``python -m predictionio_tpu.tools.lint`` entry point.

Per-file rules (JT01-JT17) by default; ``--project`` adds the
whole-program concurrency pass (JT18-JT21) over the same parse.
``bin/lint`` wraps this with ``--project`` preset — the CI gate."""

import sys

from predictionio_tpu.tools.lint.engine import main

if __name__ == "__main__":
    sys.exit(main())
