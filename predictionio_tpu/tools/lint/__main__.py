"""``python -m predictionio_tpu.tools.lint`` entry point."""

import sys

from predictionio_tpu.tools.lint.engine import main

if __name__ == "__main__":
    sys.exit(main())
