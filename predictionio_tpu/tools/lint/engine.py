"""graftlint engine: rule registry, suppressions, file walking, output.

The reference delegated correctness hazards to the JVM; the TPU rebuild
has a hazard class of its own — traced-value host syncs, silent
recompilation, low-precision accumulation, swallowed exceptions on
serving hot paths — that generic linters cannot see. graftlint encodes
those rules as AST passes over the tree (the analogue of DrJAX's
statically-analyzable-primitives discipline, PAPERS.md).

Suppression syntax (both require a one-line justification after the
rule list — an unjustified suppression is itself a finding, GL00):

    x = host_sync()  # graftlint: disable=JT01 — warm-up path, pre-trace
    # graftlint: disable-file=JT04 — probe loop, degradation is the signal

Run as ``python -m predictionio_tpu.tools.lint [paths]`` or
``pio lint``; exits 0 on a clean tree, 1 when findings remain.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import io
import json
import os
import re
import sys
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

#: ``# graftlint: disable=JT01,JT03 — justification`` (line scope) or
#: ``# graftlint: disable-file=JT04 — justification`` (file scope).
SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*(?P<scope>disable|disable-file)="
    r"(?P<rules>[A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)(?P<rest>.*)"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


@dataclasses.dataclass
class FileContext:
    """Everything a rule gets to look at for one file."""

    path: str      # path as given on the command line (for messages)
    abspath: str   # absolute, POSIX-separated (rules match on fragments)
    tree: ast.AST
    source: str
    lines: List[str]


class Rule:
    """A single static-analysis pass.

    Subclasses set ``id`` (``JTxx``), ``name`` and ``rationale`` and
    implement ``check``; ``applies_to`` restricts a rule to the layers
    where its hazard lives (e.g. JT04 only audits serving hot paths).
    """

    id: str = ""
    name: str = ""
    rationale: str = ""

    def applies_to(self, abspath: str) -> bool:
        return True

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError


#: rule id -> instance, in registration (= documentation) order.
RULES: Dict[str, Rule] = {}


def register(cls: type) -> type:
    rule = cls()
    if not rule.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if rule.id in RULES:
        raise ValueError(f"duplicate rule id {rule.id}")
    RULES[rule.id] = rule
    return cls


# -- suppressions --------------------------------------------------------------

@dataclasses.dataclass
class Suppressions:
    file_rules: Set[str]
    line_rules: Dict[int, Set[str]]
    unjustified: List[Tuple[int, str]]  # (line, directive text)

    def hides(self, finding: Finding) -> bool:
        if finding.rule == "GL00":
            return False  # the justification requirement is not itself
            # suppressible — otherwise `disable=all` with no reason
            # would hide its own GL00 and defeat the gate
        for rules in (self.file_rules, self.line_rules.get(finding.line, set())):
            if finding.rule in rules or "all" in rules:
                return True
        return False


def _iter_comments(source: str, lines: Sequence[str]):
    """(line, text) for every COMMENT token; falls back to a raw line
    scan when tokenization fails (malformed source still gets GL01 from
    the parse step — suppressions just degrade to line matching)."""
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        for i, line in enumerate(lines, start=1):
            yield i, line


def parse_suppressions(source: str, lines: Sequence[str]) -> Suppressions:
    """Directives are honored only in real comments — a suppression
    example quoted in a docstring or string literal is inert."""
    sup = Suppressions(file_rules=set(), line_rules={}, unjustified=[])
    for i, text in _iter_comments(source, lines):
        m = SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = {r.strip() for r in m.group("rules").split(",")}
        # the justification is whatever follows the rule list, minus
        # separator punctuation — it must contain actual words
        rest = m.group("rest").strip().lstrip("—–-:,. ").strip()
        if not re.search(r"\w", rest):
            sup.unjustified.append((i, m.group(0).strip()))
        if m.group("scope") == "disable-file":
            sup.file_rules.update(rules)
        else:
            sup.line_rules.setdefault(i, set()).update(rules)
    return sup


# -- driver --------------------------------------------------------------------

def iter_python_files(paths: Sequence[str]) -> Iterator[str]:
    for path in paths:
        if os.path.isfile(path):
            yield path
        elif os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d != "__pycache__" and not d.startswith(".")
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)
        else:
            raise FileNotFoundError(f"no such file or directory: {path}")


def lint_file(path: str, rules: Optional[Iterable[Rule]] = None) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    abspath = os.path.abspath(path).replace(os.sep, "/")
    lines = source.splitlines()
    sup = parse_suppressions(source, lines)
    findings: List[Finding] = [
        Finding("GL00", path, line, 0,
                f"suppression without justification: {text!r} — say why "
                "the hazard does not apply here")
        for line, text in sup.unjustified
    ]
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding("GL01", path, e.lineno or 1, e.offset or 0,
                        f"syntax error: {e.msg}")]
    ctx = FileContext(path=path, abspath=abspath, tree=tree,
                      source=source, lines=lines)
    for rule in (rules if rules is not None else RULES.values()):
        if rule.applies_to(abspath):
            findings.extend(rule.check(ctx))
    # dedupe: overlapping walks (e.g. a jit fn nested in a jit fn) may
    # report one site twice; Finding is frozen/hashable
    kept = list(dict.fromkeys(f for f in findings if not sup.hides(f)))
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return kept


def lint_paths(paths: Sequence[str],
               rules: Optional[Iterable[Rule]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        findings.extend(lint_file(path, rules=rules))
    return findings


# -- output --------------------------------------------------------------------

def format_human(findings: Sequence[Finding], n_files: int) -> str:
    out = [str(f) for f in findings]
    out.append(
        f"graftlint: {len(findings)} finding(s) in {n_files} file(s) scanned"
        if findings else f"graftlint: clean ({n_files} file(s) scanned)"
    )
    return "\n".join(out)


def format_json(findings: Sequence[Finding], n_files: int) -> str:
    return json.dumps(
        {"files_scanned": n_files,
         "findings": [f.to_dict() for f in findings]},
        indent=2, sort_keys=True,
    )


def list_rules() -> str:
    out = []
    for rule in RULES.values():
        out.append(f"{rule.id}  {rule.name}")
        out.append(f"      {rule.rationale}")
    return "\n".join(out)


def default_paths() -> List[str]:
    """The installed package directory — `pio lint` / `bin/lint` with no
    args must work from any cwd, not just the repo root."""
    here = os.path.abspath(__file__)  # .../predictionio_tpu/tools/lint/engine.py
    return [os.path.dirname(os.path.dirname(os.path.dirname(here)))]


def run_cli(paths: Sequence[str], fmt: str = "human",
            show_rules: bool = False, out=None) -> int:
    out = out if out is not None else sys.stdout
    # rule modules self-register on import; imported here (not at module
    # top) so `engine` stays import-cycle-free for the rules themselves
    from predictionio_tpu.tools.lint import rules as _rules  # noqa: F401

    if show_rules:
        print(list_rules(), file=out)
        return 0
    if not paths:
        paths = default_paths()
    files = list(iter_python_files(paths))
    findings: List[Finding] = []
    for path in files:
        findings.extend(lint_file(path))
    formatter = format_json if fmt == "json" else format_human
    print(formatter(findings, len(files)), file=out)
    return 1 if findings else 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m predictionio_tpu.tools.lint",
        description="graftlint — JAX/TPU-aware static analysis "
                    "(rules JT01-JT16; see --list-rules)",
    )
    parser.add_argument("paths", nargs="*", default=[],
                        help="files or directories to lint (default: the "
                             "installed predictionio_tpu package)")
    parser.add_argument("--format", choices=["human", "json"], default="human")
    parser.add_argument("--list-rules", action="store_true",
                        help="describe every registered rule and exit")
    return parser


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return run_cli(args.paths, fmt=args.format, show_rules=args.list_rules)
    except FileNotFoundError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2
