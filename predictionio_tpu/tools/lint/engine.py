"""graftlint engine: rule registry, suppressions, file walking, output.

The reference delegated correctness hazards to the JVM; the TPU rebuild
has a hazard class of its own — traced-value host syncs, silent
recompilation, low-precision accumulation, swallowed exceptions on
serving hot paths — that generic linters cannot see. graftlint encodes
those rules as AST passes over the tree (the analogue of DrJAX's
statically-analyzable-primitives discipline, PAPERS.md).

Suppression syntax (both require a one-line justification after the
rule list — an unjustified suppression is itself a finding, GL00):

    x = host_sync()  # graftlint: disable=JT01 — warm-up path, pre-trace
    # graftlint: disable-file=JT04 — probe loop, degradation is the signal

Run as ``python -m predictionio_tpu.tools.lint [paths]`` or
``pio lint``; exits 0 on a clean tree, 1 when findings remain.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import io
import json
import os
import re
import sys
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

#: ``# graftlint: disable=JT01,JT03 — justification`` (line scope) or
#: ``# graftlint: disable-file=JT04 — justification`` (file scope).
SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*(?P<scope>disable|disable-file)="
    r"(?P<rules>[A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)(?P<rest>.*)"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


@dataclasses.dataclass
class FileContext:
    """Everything a rule gets to look at for one file."""

    path: str      # path as given on the command line (for messages)
    abspath: str   # absolute, POSIX-separated (rules match on fragments)
    tree: ast.AST
    source: str
    lines: List[str]


class Rule:
    """A single static-analysis pass.

    Subclasses set ``id`` (``JTxx``), ``name`` and ``rationale`` and
    implement ``check``; ``applies_to`` restricts a rule to the layers
    where its hazard lives (e.g. JT04 only audits serving hot paths).
    """

    id: str = ""
    name: str = ""
    rationale: str = ""

    def applies_to(self, abspath: str) -> bool:
        return True

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError


#: rule id -> instance, in registration (= documentation) order.
RULES: Dict[str, Rule] = {}


def register(cls: type) -> type:
    rule = cls()
    if not rule.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if rule.id in RULES:
        raise ValueError(f"duplicate rule id {rule.id}")
    RULES[rule.id] = rule
    return cls


# -- suppressions --------------------------------------------------------------

@dataclasses.dataclass
class Suppressions:
    file_rules: Set[str]
    line_rules: Dict[int, Set[str]]
    unjustified: List[Tuple[int, str]]  # (line, directive text)

    def hides(self, finding: Finding) -> bool:
        if finding.rule == "GL00":
            return False  # the justification requirement is not itself
            # suppressible — otherwise `disable=all` with no reason
            # would hide its own GL00 and defeat the gate
        for rules in (self.file_rules, self.line_rules.get(finding.line, set())):
            if finding.rule in rules or "all" in rules:
                return True
        return False


def _iter_comments(source: str, lines: Sequence[str]):
    """(line, text) for every COMMENT token; falls back to a raw line
    scan when tokenization fails (malformed source still gets GL01 from
    the parse step — suppressions just degrade to line matching)."""
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        for i, line in enumerate(lines, start=1):
            yield i, line


def parse_suppressions(source: str, lines: Sequence[str],
                       tree: Optional[ast.AST] = None) -> Suppressions:
    """Directives are honored only in real comments — a suppression
    example quoted in a docstring or string literal is inert.

    With ``tree``, a directive anywhere inside a multi-line statement
    (e.g. on the closing line of a wrapped ``with`` header or call) is
    extended over the whole statement span, so findings reported at the
    statement's first line are still suppressed."""
    sup = Suppressions(file_rules=set(), line_rules={}, unjustified=[])
    for i, text in _iter_comments(source, lines):
        m = SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = {r.strip() for r in m.group("rules").split(",")}
        # the justification is whatever follows the rule list, minus
        # separator punctuation — it must contain actual words
        rest = m.group("rest").strip().lstrip("—–-:,. ").strip()
        if not re.search(r"\w", rest):
            sup.unjustified.append((i, m.group(0).strip()))
        if m.group("scope") == "disable-file":
            sup.file_rules.update(rules)
        else:
            sup.line_rules.setdefault(i, set()).update(rules)
    if tree is not None and sup.line_rules:
        _expand_multiline_spans(sup, tree)
    return sup


def _stmt_spans(tree: ast.AST) -> List[Tuple[int, int]]:
    """(start, end) line span per multi-line statement. For compound
    statements (with/if/for/def...) the span is the HEADER only — a
    comment inside the block body must not suppress at the header."""
    spans: List[Tuple[int, int]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.stmt):
            continue
        end = getattr(node, "end_lineno", None)
        body = getattr(node, "body", None)
        if isinstance(body, list) and body and isinstance(body[0], ast.stmt):
            end = body[0].lineno - 1
        if end is not None and end > node.lineno:
            spans.append((node.lineno, end))
    return spans


def _expand_multiline_spans(sup: Suppressions, tree: ast.AST) -> None:
    """A line directive inside a wrapped statement covers the whole
    statement: the finding is reported at the statement's first line,
    the human writes the comment where the statement ends."""
    spans = _stmt_spans(tree)
    for line, rules in list(sup.line_rules.items()):
        inner: Optional[Tuple[int, int]] = None
        for start, end in spans:
            if start <= line <= end and (inner is None or start > inner[0]):
                inner = (start, end)
        if inner is not None:
            for ln in range(inner[0], inner[1] + 1):
                if ln != line:
                    sup.line_rules.setdefault(ln, set()).update(rules)


# -- driver --------------------------------------------------------------------

def iter_python_files(paths: Sequence[str]) -> Iterator[str]:
    for path in paths:
        if os.path.isfile(path):
            yield path
        elif os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d != "__pycache__" and not d.startswith(".")
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)
        else:
            raise FileNotFoundError(f"no such file or directory: {path}")


def lint_file(path: str, rules: Optional[Iterable[Rule]] = None) -> List[Finding]:
    # the project layer owns the one-parse AST cache; per-file and
    # project passes share it so a file is parsed exactly once per run
    from predictionio_tpu.tools.lint import project as _project

    return _lint_module(_project.get_module(path), rules=rules)


def _lint_module(mod, rules: Optional[Iterable[Rule]] = None) -> List[Finding]:
    """Per-file pass over an already-parsed (cached) module."""
    sup = mod.suppressions
    if mod.tree is None:
        e = mod.error
        return [Finding("GL01", mod.path, e.lineno or 1, e.offset or 0,
                        f"syntax error: {e.msg}")]
    findings: List[Finding] = [
        Finding("GL00", mod.path, line, 0,
                f"suppression without justification: {text!r} — say why "
                "the hazard does not apply here")
        for line, text in sup.unjustified
    ]
    ctx = FileContext(path=mod.path, abspath=mod.abspath, tree=mod.tree,
                      source=mod.source, lines=mod.lines)
    for rule in (rules if rules is not None else RULES.values()):
        if rule.applies_to(mod.abspath):
            findings.extend(rule.check(ctx))
    # dedupe: overlapping walks (e.g. a jit fn nested in a jit fn) may
    # report one site twice; Finding is frozen/hashable
    kept = list(dict.fromkeys(f for f in findings if not sup.hides(f)))
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return kept


def lint_paths(paths: Sequence[str],
               rules: Optional[Iterable[Rule]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        findings.extend(lint_file(path, rules=rules))
    return findings


def lint_project(paths: Sequence[str]) -> Tuple[List[Finding], int]:
    """Whole-program mode: the per-file rules over every module PLUS the
    project rules (JT18-JT21) over the cross-module model. The given
    paths define the project universe; modules are parsed once (shared
    AST cache) and project findings honor each file's suppression
    comments exactly like per-file findings. Returns (findings, files)."""
    from predictionio_tpu.tools.lint import project as _project
    from predictionio_tpu.tools.lint import concurrency as _concurrency  # noqa: F401

    files = list(iter_python_files(paths))
    modules = [_project.get_module(p) for p in files]
    findings: List[Finding] = []
    for mod in modules:
        findings.extend(_lint_module(mod))
    model = _project.build([m for m in modules if m.tree is not None])
    sup_by_path = {m.path: m.suppressions for m in modules}
    project_findings: List[Finding] = []
    for rule in _project.PROJECT_RULES.values():
        project_findings.extend(rule.check(model))
    for f in project_findings:
        sup = sup_by_path.get(f.path)
        if sup is not None and sup.hides(f):
            continue
        findings.append(f)
    kept = list(dict.fromkeys(findings))
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return kept, len(files)


# -- output --------------------------------------------------------------------

def format_human(findings: Sequence[Finding], n_files: int) -> str:
    out = [str(f) for f in findings]
    out.append(
        f"graftlint: {len(findings)} finding(s) in {n_files} file(s) scanned"
        if findings else f"graftlint: clean ({n_files} file(s) scanned)"
    )
    return "\n".join(out)


def format_json(findings: Sequence[Finding], n_files: int) -> str:
    return json.dumps(
        {"files_scanned": n_files,
         "findings": [f.to_dict() for f in findings]},
        indent=2, sort_keys=True,
    )


def list_rules() -> str:
    from predictionio_tpu.tools.lint import project as _project
    from predictionio_tpu.tools.lint import concurrency as _concurrency  # noqa: F401

    out = []
    for rule in RULES.values():
        out.append(f"{rule.id}  {rule.name}")
        out.append(f"      {rule.rationale}")
    for prule in _project.PROJECT_RULES.values():
        out.append(f"{prule.id}  {prule.name}  [--project]")
        out.append(f"      {prule.rationale}")
    return "\n".join(out)


def default_paths() -> List[str]:
    """The installed package directory — `pio lint` / `bin/lint` with no
    args must work from any cwd, not just the repo root."""
    here = os.path.abspath(__file__)  # .../predictionio_tpu/tools/lint/engine.py
    return [os.path.dirname(os.path.dirname(os.path.dirname(here)))]


def run_cli(paths: Sequence[str], fmt: str = "human",
            show_rules: bool = False, out=None,
            project: bool = False) -> int:
    out = out if out is not None else sys.stdout
    # rule modules self-register on import; imported here (not at module
    # top) so `engine` stays import-cycle-free for the rules themselves
    from predictionio_tpu.tools.lint import rules as _rules  # noqa: F401

    if show_rules:
        print(list_rules(), file=out)
        return 0
    if not paths:
        paths = default_paths()
    if project:
        findings, n_files = lint_project(paths)
    else:
        files = list(iter_python_files(paths))
        n_files = len(files)
        findings = []
        for path in files:
            findings.extend(lint_file(path))
    formatter = format_json if fmt == "json" else format_human
    print(formatter(findings, n_files), file=out)
    return 1 if findings else 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m predictionio_tpu.tools.lint",
        description="graftlint — JAX/TPU-aware static analysis "
                    "(per-file rules JT01-JT17 + JT22-JT23, whole-program "
                    "rules JT18-JT21 with --project; see --list-rules)",
    )
    parser.add_argument("paths", nargs="*", default=[],
                        help="files or directories to lint (default: the "
                             "installed predictionio_tpu package)")
    parser.add_argument("--project", action="store_true",
                        help="whole-program mode: per-file rules plus the "
                             "cross-module concurrency rules JT18-JT21 "
                             "(lock-discipline inference, race/deadlock "
                             "detection) over the given paths as one "
                             "project")
    parser.add_argument("--format", choices=["human", "json"], default="human")
    parser.add_argument("--json", action="store_const", const="json",
                        dest="format",
                        help="shorthand for --format json (stable "
                             "rule/file/line keys for CI tooling)")
    parser.add_argument("--list-rules", action="store_true",
                        help="describe every registered rule and exit")
    return parser


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return run_cli(args.paths, fmt=args.format,
                       show_rules=args.list_rules, project=args.project)
    except FileNotFoundError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2
