"""graftlint whole-program concurrency rules JT18-JT21.

These rules consume the :mod:`project` model (class/attribute accesses,
inferred guard discipline, thread-entry reachability, the project-wide
lock-acquisition graph) and encode the three bug classes that per-file
analysis structurally cannot see:

* **JT18 unguarded-shared-mutation** — the probe-vs-drain class: an
  attribute the class itself treats as lock-guarded (majority of writes
  under ``with self._lock:``) mutated or iterated from thread-reachable
  code outside any region holding that lock.
* **JT19 lock-order-cycle** — the deadlock class: the project-wide
  acquisition graph (nested ``with`` regions plus cross-method calls)
  contains a cycle, or a known non-reentrant ``threading.Lock`` is
  re-acquired while already held.
* **JT20 check-then-act-split** — the check-and-spawn class fixed by
  hand in PR 8: a guarded attribute tested under the lock in one region
  and written under the lock in a later, separate region of the same
  function — the gap between the two regions is where another thread
  rewrites the premise.
* **JT21 blocking-call-under-lock** — the convoy class: a
  ``time.sleep``/socket/file-I/O/``urlopen`` call inside a ``with
  <lock>`` region (directly, or in a helper only ever invoked with the
  lock held) serializes every contending thread behind a kernel wait —
  one slow peer turns a microsecond critical section into the whole
  fleet's latency floor.

Deliberate lock-free designs (copy-on-write row swaps, ring buffers
that tolerate torn reads) are justified with the standard suppression
comment; the justification string is the design review record.
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, List, Set, Tuple

from predictionio_tpu.tools.lint.engine import Finding
from predictionio_tpu.tools.lint.project import (
    Access,
    BlockingCall,
    LockEdge,
    Project,
    ProjectRule,
    register_project,
)


def _pretty(subject: str) -> str:
    """Human form of a subject/lock id: class attrs stay ``Cls.attr``;
    module globals ``/abs/path.py::name`` compress to ``file.py:name``."""
    if "::" in subject:
        path, _, name = subject.rpartition("::")
        return f"{os.path.basename(path)}:{name}"
    return subject


# -- JT18 ----------------------------------------------------------------------

@register_project
class UnguardedSharedMutation(ProjectRule):
    id = "JT18"
    name = "unguarded-shared-mutation"
    rationale = (
        "An attribute whose writes the owning class routinely guards "
        "(`with self._lock:`) mutated — or iterated, which a concurrent "
        "mutation corrupts mid-loop — from thread-reachable code outside "
        "any region holding that lock races every guarded access: the "
        "probe-vs-drain class. Take the lock, or justify the lock-free "
        "design (copy-on-write swap, torn-read-tolerant ring) with a "
        "suppression naming why unguarded access is safe."
    )

    def check(self, project: Project) -> Iterator[Finding]:
        by_subject: Dict[str, List[Access]] = {}
        for acc in project.accesses:
            by_subject.setdefault(acc.subject, []).append(acc)
        for subject in sorted(project.guards):
            guard = project.guards[subject]
            for acc in by_subject.get(subject, []):
                if acc.in_init:
                    continue
                fi = project.funcs.get(acc.func)
                if fi is None or not fi.thread_reachable:
                    continue
                if guard.lock in project.effective_locks(acc):
                    continue
                if acc.kind in ("write", "mutate"):
                    what = ("rebound" if acc.kind == "write"
                            else "mutated in place")
                elif acc.kind == "read" and acc.is_iter:
                    what = "iterated"
                else:
                    continue
                yield Finding(
                    self.id, acc.path, acc.line, acc.col,
                    f"`{_pretty(subject)}` is guarded by "
                    f"`{_pretty(guard.lock)}` "
                    f"({guard.locked_writes}/{guard.total_writes} writes "
                    f"hold it) but is {what} here on a thread-reachable "
                    f"path without the lock — take the lock or justify "
                    f"the lock-free design",
                )


# -- JT19 ----------------------------------------------------------------------

@register_project
class LockOrderCycle(ProjectRule):
    id = "JT19"
    name = "lock-order-cycle"
    rationale = (
        "Two threads acquiring the same locks in opposite orders "
        "deadlock the moment their windows overlap; the project-wide "
        "acquisition graph (nested `with` regions plus locks taken by "
        "called methods) makes the order global and checkable. Any "
        "cycle is a potential deadlock; re-acquiring a non-reentrant "
        "threading.Lock while already holding it deadlocks a single "
        "thread outright. Fix by imposing one acquisition order (or "
        "dropping the outer lock before the call); suppress only with "
        "a reason proving the regions can never overlap."
    )

    def _sccs(self, nodes: Set[str],
              edges: Dict[str, Set[str]]) -> List[List[str]]:
        """Tarjan, iteratively (the lock graph is tiny but recursion
        limits are not worth betting on)."""
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        out: List[List[str]] = []
        counter = [0]

        for root in sorted(nodes):
            if root in index:
                continue
            work: List[Tuple[str, int]] = [(root, 0)]
            while work:
                node, pi = work.pop()
                if pi == 0:
                    index[node] = low[node] = counter[0]
                    counter[0] += 1
                    stack.append(node)
                    on_stack.add(node)
                recurse = False
                succs = sorted(edges.get(node, ()))
                for i in range(pi, len(succs)):
                    succ = succs[i]
                    if succ not in index:
                        work.append((node, i + 1))
                        work.append((succ, 0))
                        recurse = True
                        break
                    if succ in on_stack:
                        low[node] = min(low[node], index[succ])
                if recurse:
                    continue
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    out.append(scc)
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
        return out

    def check(self, project: Project) -> Iterator[Finding]:
        edges: Dict[str, Set[str]] = {}
        sites: Dict[Tuple[str, str], LockEdge] = {}
        nodes: Set[str] = set()
        for e in project.lock_edges:
            if e.src == e.dst:
                # single-thread self-deadlock: only certain when the
                # lock is known non-reentrant (threading.Lock); RLock/
                # Condition re-acquires are legal by design
                if project.lock_kinds.get(e.src) != "Lock":
                    continue
                via = f" via `{e.via.rpartition('::')[2]}`" if e.via else ""
                yield Finding(
                    self.id, e.path, e.line, e.col,
                    f"non-reentrant lock `{_pretty(e.src)}` re-acquired "
                    f"while already held{via} — a single thread "
                    f"deadlocks itself here",
                )
                continue
            nodes.update((e.src, e.dst))
            edges.setdefault(e.src, set()).add(e.dst)
            key = (e.src, e.dst)
            best = sites.get(key)
            if best is None or (e.path, e.line) < (best.path, best.line):
                sites[key] = e
        for scc in self._sccs(nodes, edges):
            if len(scc) < 2:
                continue
            members = set(scc)
            cyc_edges = sorted(
                (sites[k] for k in sites
                 if k[0] in members and k[1] in members),
                key=lambda e: (e.path, e.line))
            where = "; ".join(
                f"{_pretty(e.src)}->{_pretty(e.dst)} at {e.path}:{e.line}"
                + (f" (via {e.via.rpartition('::')[2]})" if e.via else "")
                for e in cyc_edges[:4])
            rep = cyc_edges[0]
            yield Finding(
                self.id, rep.path, rep.line, rep.col,
                f"lock-order cycle among "
                f"{', '.join(_pretty(n) for n in sorted(members))} — "
                f"threads taking these locks in different orders can "
                f"deadlock; impose one global order ({where})",
            )


# -- JT20 ----------------------------------------------------------------------

@register_project
class CheckThenActSplit(ProjectRule):
    id = "JT20"
    name = "check-then-act-split"
    rationale = (
        "A guarded attribute tested in one `with lock:` region and "
        "written in a LATER, separate region of the same function is a "
        "split transaction: between the two regions any other thread "
        "may rewrite the premise the second region acts on (the "
        "check-and-spawn atomicity bug fixed by hand in PR 8). Merge "
        "the regions into one critical section, or re-validate the "
        "premise inside the second region and justify the split."
    )

    def check(self, project: Project) -> Iterator[Finding]:
        for key in sorted(project.funcs):
            fi = project.funcs[key]
            if len(fi.regions) < 2:
                continue
            regions = sorted(fi.regions, key=lambda r: (r.line, r.col))
            seen: Set[Tuple[int, str]] = set()
            for i, r1 in enumerate(regions):
                for r2 in regions[i + 1:]:
                    if r2.lock != r1.lock or r2.line <= r1.end_line:
                        continue  # nested or same region, not a split
                    for subject in sorted(r1.tested & r2.written):
                        if subject in r2.tested:
                            # the second region re-validates the premise
                            # before acting (a re-check or an atomic
                            # dict.setdefault) — the sanctioned fix
                            continue
                        guard = project.guards.get(subject)
                        if guard is None or guard.lock != r1.lock:
                            continue
                        mark = (r2.line, subject)
                        if mark in seen:
                            continue
                        seen.add(mark)
                        yield Finding(
                            self.id, fi.path, r2.line, r2.col,
                            f"`{_pretty(subject)}` was tested under "
                            f"`{_pretty(r1.lock)}` at line {r1.line} but "
                            f"is written in this separate lock region — "
                            f"between the two, another thread can "
                            f"rewrite the premise; merge the regions or "
                            f"re-validate before acting",
                        )

# -- JT21 ----------------------------------------------------------------------

@register_project
class BlockingCallUnderLock(ProjectRule):
    id = "JT21"
    name = "blocking-call-under-lock"
    rationale = (
        "A sleep, socket, file or subprocess call inside a `with lock:` "
        "region parks the thread in the kernel WHILE every contending "
        "thread queues behind the lock — the convoy class: one slow "
        "peer or disk turns a microsecond critical section into the "
        "process's latency floor (and the GIL is released during the "
        "wait, so the serialization buys no safety the lock did not "
        "already have). Copy what the region needs under the lock, do "
        "the I/O outside it; suppress only with a reason the wait MUST "
        "be serialized (e.g. the sleep IS the guarded capture window, "
        "or the lock exists to serialize that very file handle)."
    )

    def check(self, project: Project) -> Iterator[Finding]:
        for bc in project.blocking_calls:
            held = bc.locks | project.inferred_held.get(
                bc.func, frozenset())
            if not held:
                continue
            locks = ", ".join(
                f"`{_pretty(lock)}`" for lock in sorted(held))
            via = ("" if bc.locks
                   else " (every resolvable caller holds it)")
            yield Finding(
                self.id, bc.path, bc.line, bc.col,
                f"blocking {bc.category} call `{bc.name}` while "
                f"holding {locks}{via} — contending threads convoy "
                f"behind the kernel wait; move the call outside the "
                f"critical section or justify the serialized wait",
            )
