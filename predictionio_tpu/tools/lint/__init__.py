"""graftlint — JAX/TPU-aware static analysis for the DASE pipeline.

Ordinary linters can't see this framework's hazard class: traced-value
host syncs (JT01), Python branches on tracers (JT02), low-precision
accumulation (JT03, the bf16-Gramian bug class), swallowed exceptions on
serving hot paths (JT04), undeclared mesh axes (JT05) and per-request
blocking transfers in HTTP handlers (JT06). With ``--project`` the
whole-program layer (project.py + concurrency.py) adds lock-discipline
inference and race/deadlock detection across the fleet substrate:
unguarded shared mutation (JT18), lock-order cycles (JT19) and
check-then-act splits (JT20).

    python -m predictionio_tpu.tools.lint [paths] [--project] [--json]
    pio lint [--project] [paths]
    bin/lint

Suppress a reviewed finding with a justified comment:

    ...  # graftlint: disable=JT01 — one-time warm-up, not a hot path
"""

from __future__ import annotations

from predictionio_tpu.tools.lint.engine import (
    Finding,
    Rule,
    RULES,
    lint_file,
    lint_paths,
    lint_project,
    main,
    register,
    run_cli,
)
from predictionio_tpu.tools.lint import rules  # noqa: F401 — registers JT01-JT17, JT22-JT23
from predictionio_tpu.tools.lint.project import PROJECT_RULES, register_project
from predictionio_tpu.tools.lint import concurrency  # noqa: F401 — registers JT18-JT21

__all__ = [
    "Finding",
    "Rule",
    "RULES",
    "PROJECT_RULES",
    "lint_file",
    "lint_paths",
    "lint_project",
    "main",
    "register",
    "register_project",
    "run_cli",
    "rules",
    "concurrency",
]
