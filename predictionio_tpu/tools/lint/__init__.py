"""graftlint — JAX/TPU-aware static analysis for the DASE pipeline.

Ordinary linters can't see this framework's hazard class: traced-value
host syncs (JT01), Python branches on tracers (JT02), low-precision
accumulation (JT03, the bf16-Gramian bug class), swallowed exceptions on
serving hot paths (JT04), undeclared mesh axes (JT05) and per-request
blocking transfers in HTTP handlers (JT06).

    python -m predictionio_tpu.tools.lint [paths] [--format json]
    pio lint [paths]
    bin/lint

Suppress a reviewed finding with a justified comment:

    ...  # graftlint: disable=JT01 — one-time warm-up, not a hot path
"""

from __future__ import annotations

from predictionio_tpu.tools.lint.engine import (
    Finding,
    Rule,
    RULES,
    lint_file,
    lint_paths,
    main,
    register,
    run_cli,
)
from predictionio_tpu.tools.lint import rules  # noqa: F401 — registers JT01-JT06

__all__ = [
    "Finding",
    "Rule",
    "RULES",
    "lint_file",
    "lint_paths",
    "main",
    "register",
    "run_cli",
    "rules",
]
