"""graftlint project layer: one-parse AST cache + whole-program model.

The per-file rules (JT01-JT17) see one function at a time; the hazard
class that has dominated recent review rounds — probe-vs-drain races,
swap-write fences after stop, export-lock ordering — only exists ACROSS
functions and files: a lock discipline is a property of every access to
an attribute, and a deadlock is a property of every acquisition order in
the program. This module builds the whole-program model those rules
need:

* an AST cache keyed by (path, mtime, size) so the per-file pass and the
  project pass parse every module exactly once;
* a class/attribute model: every ``self.X`` (and module-global) read,
  write and mutating call, with the set of locks held at each site;
* a thread-entry set — functions reached from
  ``threading.Thread(target=...)`` / ``Timer``, worker-pool
  ``submit(...)``, ``do_*`` HTTP handlers (one thread per connection)
  and registered callbacks (``add_*`` / ``register`` / ``watch``) — and
  the call-graph reachability closure over it;
* inferred guard discipline: an attribute is *guarded* when the
  majority of its writes happen while a lock is held (``with
  self._lock:`` or an equivalent named lock), directly or via the
  called-with-lock-held inference (a helper whose every resolvable call
  site holds L executes under L);
* the project-wide lock-acquisition graph (nested ``with`` regions plus
  cross-method calls) that JT19 searches for cycles.

Everything here is plain AST bookkeeping — no imports are executed, no
jax is touched — so ``pio lint --project`` stays a sub-ten-second gate.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, FrozenSet, Iterator, List, Optional, Sequence, Set, Tuple

from predictionio_tpu.tools.lint.engine import (
    Finding,
    Suppressions,
    parse_suppressions,
)

# -- AST cache -----------------------------------------------------------------

@dataclasses.dataclass
class ModuleInfo:
    """One parsed module, shared by the per-file and project passes."""

    path: str                      # as given on the command line
    abspath: str                   # absolute, POSIX-separated
    source: str
    lines: List[str]
    tree: Optional[ast.AST]        # None when the file failed to parse
    error: Optional[SyntaxError]
    suppressions: Suppressions


#: (abspath) -> (stat fingerprint, ModuleInfo); an edited file reparses.
_CACHE: Dict[str, Tuple[Tuple[int, int], ModuleInfo]] = {}


def get_module(path: str) -> ModuleInfo:
    abspath = os.path.abspath(path).replace(os.sep, "/")
    st = os.stat(path)
    stamp = (st.st_mtime_ns, st.st_size)
    hit = _CACHE.get(abspath)
    if hit is not None and hit[0] == stamp:
        return hit[1]
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    lines = source.splitlines()
    tree: Optional[ast.AST] = None
    error: Optional[SyntaxError] = None
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        error = e
    sup = parse_suppressions(source, lines, tree=tree)
    mod = ModuleInfo(path=path, abspath=abspath, source=source, lines=lines,
                     tree=tree, error=error, suppressions=sup)
    _CACHE[abspath] = (stamp, mod)
    return mod


# -- lock / access vocabulary --------------------------------------------------

#: attribute / name tails that denote a mutual-exclusion object; the
#: README "lock discipline conventions" section documents this contract
LOCK_NAME_RE = re.compile(r"(?:^|_)(?:lock|mutex|mu|cv|cond|condition)$",
                          re.IGNORECASE)

#: method calls that mutate their receiver in place
_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popitem", "popleft",
    "appendleft", "clear", "update", "setdefault", "add", "discard",
    "sort", "reverse", "rotate",
}

#: methods whose writes happen before the object is shared (constructor)
_INIT_METHODS = {"__init__", "__new__", "__init_subclass__", "__set_name__"}

_THREAD_TAILS = {"Thread"}
_CALLBACK_TAILS = {"register", "watch", "submit"}

#: calls that park the calling thread in the kernel (sleep, network,
#: file, socket I/O) — JT21's vocabulary, matched only when the call
#: does NOT resolve to a project function (a local helper named
#: ``sleep`` is not ``time.sleep``). Deliberately curated: ``wait`` is
#: absent (Condition.wait under its own lock is the correct idiom),
#: and so is generic ``read``/``write`` (too many in-memory buffers).
_BLOCKING_EXACT = {
    "time.sleep": "sleep",
    "sleep": "sleep",            # from time import sleep
    "open": "file I/O",          # the builtin
    "select.select": "socket I/O",
}
_BLOCKING_TAILS = {
    "urlopen": "network I/O",
    "create_connection": "socket I/O",
    "getaddrinfo": "network I/O",
    "accept": "socket I/O",
    "recv": "socket I/O",
    "recvfrom": "socket I/O",
    "sendall": "socket I/O",
    "check_output": "subprocess",
    "check_call": "subprocess",
    "communicate": "subprocess",
}


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


@dataclasses.dataclass
class Access:
    """One read/write/mutation of a shared subject at a source site."""

    subject: str                   # "Cls.attr" or "<module abspath>::name"
    kind: str                      # "write" | "mutate" | "read"
    func: str                      # FuncInfo key of the enclosing function
    path: str
    line: int
    col: int
    locks: FrozenSet[str]          # lock ids held syntactically at the site
    in_init: bool
    in_test: bool = False          # read inside a conditional test/compare
    is_iter: bool = False          # read is iterated over (for/comprehension)


@dataclasses.dataclass
class Region:
    """One ``with <lock>`` region inside one function (for JT20)."""

    lock: str
    line: int
    col: int
    end_line: int
    tested: Set[str] = dataclasses.field(default_factory=set)
    read: Set[str] = dataclasses.field(default_factory=set)
    written: Set[str] = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class FuncInfo:
    key: str                       # "<module abspath>::qualname"
    qualname: str                  # "Cls.method", "func", "Cls.m.<locals>.f"
    name: str
    cls: Optional[str]
    module: str                    # abspath
    path: str
    line: int
    calls: List[Tuple[str, FrozenSet[str], int]] = dataclasses.field(
        default_factory=list)      # (callee key, locks held, call line)
    acquires: Set[str] = dataclasses.field(default_factory=set)
    regions: List[Region] = dataclasses.field(default_factory=list)
    entry: Optional[str] = None    # why this runs on a non-main thread
    thread_reachable: bool = False


@dataclasses.dataclass
class LockEdge:
    """Held ``src`` while acquiring ``dst`` (possibly via a call chain)."""

    src: str
    dst: str
    path: str
    line: int
    col: int
    via: str                       # "" for syntactic nesting, callee key else


@dataclasses.dataclass
class BlockingCall:
    """One sleep/network/file/socket call site (JT21's subjects); the
    syntactic ``locks`` here combine with the called-with-lock-held
    inference at rule time, so a blocking helper only ever invoked
    under a lock is still caught."""

    name: str                      # the dotted call as written
    category: str                  # sleep | network I/O | file I/O | ...
    func: str                      # FuncInfo key of the enclosing function
    path: str
    line: int
    col: int
    locks: FrozenSet[str]          # lock ids held syntactically


@dataclasses.dataclass
class GuardInfo:
    lock: str
    locked_writes: int
    total_writes: int


@dataclasses.dataclass
class ClassInfo:
    name: str
    module: str
    path: str
    bases: List[str]
    methods: Dict[str, str] = dataclasses.field(default_factory=dict)
    attr_types: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Project:
    modules: List[ModuleInfo]
    funcs: Dict[str, FuncInfo]
    classes: Dict[str, ClassInfo]
    accesses: List[Access]
    guards: Dict[str, GuardInfo]   # subject -> inferred guard
    lock_edges: List[LockEdge]
    lock_kinds: Dict[str, str]     # lock id -> Lock|RLock|Condition|Semaphore
    inferred_held: Dict[str, FrozenSet[str]]
    blocking_calls: List[BlockingCall] = dataclasses.field(
        default_factory=list)

    def effective_locks(self, access: Access) -> FrozenSet[str]:
        """Locks held at an access site: syntactic plus the
        called-with-lock-held inference for its enclosing function."""
        return access.locks | self.inferred_held.get(access.func, frozenset())


# -- model builder -------------------------------------------------------------

class _ModuleVisitor:
    """Extracts functions, classes, accesses, locks from one module."""

    def __init__(self, mod: ModuleInfo, builder: "_Builder") -> None:
        self.mod = mod
        self.b = builder
        self.globals: Set[str] = set()        # module-level mutable names
        self.global_types: Dict[str, str] = {}  # NAME -> ClassName
        self.test_nodes: Set[int] = set()     # id(node) inside a test expr

    # phase 1: module-level declarations ------------------------------------

    def scan_toplevel(self) -> None:
        tree = self.mod.tree
        assert tree is not None
        for node in tree.body:  # type: ignore[attr-defined]
            if isinstance(node, ast.ClassDef):
                self._scan_class(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = f"{self.mod.abspath}::{node.name}"
                self.b.funcs[key] = FuncInfo(
                    key=key, qualname=node.name, name=node.name, cls=None,
                    module=self.mod.abspath, path=self.mod.path,
                    line=node.lineno)
                self.b.module_funcs.setdefault(self.mod.abspath, {})[
                    node.name] = key
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                value = node.value
                for tgt in targets:
                    if not isinstance(tgt, ast.Name):
                        continue
                    self.globals.add(tgt.id)
                    if isinstance(value, ast.Call):
                        tail = _dotted(value.func).rsplit(".", 1)[-1]
                        if LOCK_NAME_RE.search(tgt.id) and tail in (
                                "Lock", "RLock", "Condition", "Semaphore",
                                "BoundedSemaphore"):
                            lock_id = self._global_subject(tgt.id)
                            self.b.lock_kinds[lock_id] = tail
                        self.global_types[tgt.id] = tail

    def _scan_class(self, node: ast.ClassDef) -> None:
        info = ClassInfo(name=node.name, module=self.mod.abspath,
                         path=self.mod.path,
                         bases=[_dotted(b) for b in node.bases])
        # same-module name wins over a same-named class elsewhere
        self.b.classes.setdefault(node.name, info)
        self.b.module_classes.setdefault(self.mod.abspath, {})[
            node.name] = info
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = f"{self.mod.abspath}::{node.name}.{item.name}"
                info.methods[item.name] = key
                self.b.funcs[key] = FuncInfo(
                    key=key, qualname=f"{node.name}.{item.name}",
                    name=item.name, cls=node.name,
                    module=self.mod.abspath, path=self.mod.path,
                    line=item.lineno)
                self.b.method_index.setdefault(item.name, []).append(key)

    # phase 2: function bodies ----------------------------------------------

    def visit_bodies(self) -> None:
        tree = self.mod.tree
        assert tree is not None
        self._collect_test_nodes(tree)
        for node in tree.body:  # type: ignore[attr-defined]
            if isinstance(node, ast.ClassDef):
                cls = self.b.module_classes[self.mod.abspath][node.name]
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        key = cls.methods[item.name]
                        self._visit_function(item, self.b.funcs[key], cls)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = self.b.module_funcs[self.mod.abspath][node.name]
                self._visit_function(node, self.b.funcs[key], None)

    def _collect_test_nodes(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            tests: List[ast.AST] = []
            if isinstance(node, (ast.If, ast.While, ast.IfExp)):
                tests.append(node.test)
            elif isinstance(node, ast.Assert):
                tests.append(node.test)
            elif isinstance(node, ast.Compare):
                tests.append(node)
            for t in tests:
                for sub in ast.walk(t):
                    self.test_nodes.add(id(sub))

    # -- subjects and locks --

    def _global_subject(self, name: str) -> str:
        return f"{self.mod.abspath}::{name}"

    def _lock_id(self, expr: ast.AST, cls: Optional[ClassInfo]) -> Optional[str]:
        d = _dotted(expr)
        if not d:
            return None
        tail = d.rsplit(".", 1)[-1]
        if not LOCK_NAME_RE.search(tail):
            return None
        if d.startswith("self.") and cls is not None and d.count(".") == 1:
            return f"{cls.name}.{tail}"
        if "." not in d and d in self.globals:
            return self._global_subject(d)
        if "." not in d:
            return None  # a local lock guards nothing shared
        return d  # Cls._lock / mod._lock spelled explicitly

    def _self_attr(self, node: ast.AST) -> Optional[str]:
        """``self.X`` -> "X" (one level only)."""
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr
        return None

    # -- the recursive walk --

    def _visit_function(self, fn: ast.AST, info: FuncInfo,
                        cls: Optional[ClassInfo]) -> None:
        in_init = info.name in _INIT_METHODS
        local_defs: Dict[str, str] = {}
        # locals shadow module globals for the whole function body
        local_names: Set[str] = {
            a.arg for a in (fn.args.posonlyargs + fn.args.args
                            + fn.args.kwonlyargs)}
        if fn.args.vararg:
            local_names.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            local_names.add(fn.args.kwarg.arg)
        declared_global: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                declared_global.update(node.names)
            elif isinstance(node, ast.Name) and isinstance(
                    node.ctx, (ast.Store, ast.Del)):
                local_names.add(node.id)
        local_names -= declared_global

        def is_global(name: str) -> bool:
            return name in self.globals and (name not in local_names
                                             or name in declared_global)

        def record(subject: str, kind: str, node: ast.AST,
                   held: FrozenSet[str], **flags: bool) -> None:
            acc = Access(subject=subject, kind=kind, func=info.key,
                         path=self.mod.path, line=node.lineno,
                         col=node.col_offset, locks=held,
                         in_init=in_init, **flags)
            self.b.accesses.append(acc)
            for region in info.regions:
                if region.line <= node.lineno <= region.end_line:
                    if kind == "read":
                        region.read.add(subject)
                        if acc.in_test:
                            region.tested.add(subject)
                    else:
                        region.written.add(subject)
                        if acc.in_test:
                            # an atomic check-and-write (dict.setdefault)
                            # both re-validates and acts — the region
                            # counts as testing the premise
                            region.tested.add(subject)

        def subject_of(node: ast.AST) -> Optional[str]:
            attr = self._self_attr(node)
            if attr is not None and cls is not None:
                return f"{cls.name}.{attr}"
            if isinstance(node, ast.Name) and is_global(node.id):
                return self._global_subject(node.id)
            return None

        def record_write_target(tgt: ast.AST, held: FrozenSet[str]) -> None:
            # self.X = / global NAME = : a rebinding write
            attr = self._self_attr(tgt)
            if attr is not None and cls is not None:
                if isinstance(tgt, ast.Attribute):
                    record(f"{cls.name}.{attr}", "write", tgt, held)
                return
            if isinstance(tgt, ast.Name) and tgt.id in declared_global \
                    and tgt.id in self.globals:
                record(self._global_subject(tgt.id), "write", tgt, held)
                return
            # self.X[k] = / NAME[k] = / self.X.field = : in-place mutation
            if isinstance(tgt, ast.Subscript):
                sub = subject_of(tgt.value)
                if sub is not None:
                    record(sub, "mutate", tgt, held)
            elif isinstance(tgt, ast.Attribute):
                sub = subject_of(tgt.value)
                if sub is not None:
                    record(sub, "mutate", tgt, held)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                for elt in tgt.elts:
                    record_write_target(elt, held)

        def resolve_call(func_expr: ast.AST) -> Optional[str]:
            d = _dotted(func_expr)
            if not d:
                return None
            if d.startswith("self.") and cls is not None:
                rest = d[5:]
                if "." not in rest:
                    return self.b.resolve_method(cls, rest)
                attr, _, meth = rest.partition(".")
                if "." not in meth:
                    tname = cls.attr_types.get(attr)
                    target = self.b.classes.get(tname) if tname else None
                    if target is not None:
                        return self.b.resolve_method(target, meth)
                return None
            if "." not in d:
                if d in local_defs:
                    return local_defs[d]
                return self.b.module_funcs.get(self.mod.abspath, {}).get(d)
            head, _, meth = d.rpartition(".")
            if "." not in head:
                tname = self.global_types.get(head, head)
                target = (self.b.module_classes.get(self.mod.abspath, {})
                          .get(tname) or self.b.classes.get(tname))
                if target is not None:
                    return self.b.resolve_method(target, meth)
            return None

        def resolve_ref(expr: ast.AST) -> Optional[str]:
            """A function REFERENCE (thread target / callback arg)."""
            key = resolve_call(expr)
            if key is not None:
                return key
            # fall back to a unique method name anywhere in the project:
            # `Thread(target=replica.serve_loop)` where the receiver's
            # type is not inferrable but exactly one class defines it
            d = _dotted(expr)
            tail = d.rsplit(".", 1)[-1] if d else ""
            hits = self.b.method_index.get(tail, [])
            if len(hits) == 1:
                return hits[0]
            return None

        def mark_entry(expr: ast.AST, why: str) -> None:
            key = resolve_ref(expr)
            if key is not None and self.b.funcs[key].entry is None:
                self.b.funcs[key].entry = why

        def handle_call(node: ast.Call, held: FrozenSet[str]) -> None:
            d = _dotted(node.func)
            tail = d.rsplit(".", 1)[-1]
            if tail in _THREAD_TAILS:
                for kw in node.keywords:
                    if kw.arg == "target":
                        mark_entry(kw.value, "threading.Thread target")
            elif tail == "Timer":
                if len(node.args) > 1:
                    mark_entry(node.args[1], "threading.Timer callback")
                for kw in node.keywords:
                    if kw.arg == "function":
                        mark_entry(kw.value, "threading.Timer callback")
            elif tail in _CALLBACK_TAILS or tail.startswith("add_"):
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    mark_entry(arg, f"callback registered via {tail}()")
            callee = resolve_call(node.func)
            if callee is not None:
                info.calls.append((callee, held, node.lineno))
            else:
                # unresolved = not a project function: check the
                # blocking-call vocabulary (JT21); every candidate is
                # recorded — the rule adds the called-with-lock-held
                # inference before deciding
                category = _BLOCKING_EXACT.get(d)
                if category is None and "." in d:
                    category = _BLOCKING_TAILS.get(tail)
                if category is not None:
                    self.b.blocking_calls.append(BlockingCall(
                        name=d, category=category, func=info.key,
                        path=self.mod.path, line=node.lineno,
                        col=node.col_offset, locks=held))
            # mutating method call on a shared subject: self.X.append(...)
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS:
                sub = subject_of(node.func.value)
                if sub is not None:
                    record(sub, "mutate", node, held,
                           in_test=node.func.attr == "setdefault")

        def walk(node: ast.AST, held: FrozenSet[str]) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                cur = held
                for item in node.items:
                    walk(item.context_expr, cur)
                    if item.optional_vars is not None:
                        walk(item.optional_vars, cur)
                    lock = self._lock_id(item.context_expr, cls)
                    if lock is not None:
                        for src in sorted(cur):
                            self.b.lock_edges.append(LockEdge(
                                src=src, dst=lock, path=self.mod.path,
                                line=item.context_expr.lineno,
                                col=item.context_expr.col_offset, via=""))
                        if lock not in cur:
                            info.acquires.add(lock)
                            info.regions.append(Region(
                                lock=lock, line=node.lineno,
                                col=node.col_offset,
                                end_line=node.end_lineno or node.lineno))
                        cur = cur | {lock}
                for stmt in node.body:
                    walk(stmt, cur)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = f"{info.key}.<locals>.{node.name}"
                if key not in self.b.funcs:
                    nested = FuncInfo(
                        key=key, qualname=f"{info.qualname}.<locals>."
                        f"{node.name}", name=node.name, cls=info.cls,
                        module=self.mod.abspath, path=self.mod.path,
                        line=node.lineno)
                    self.b.funcs[key] = nested
                local_defs[node.name] = key
                # the nested body runs in its own frame with NO lock
                # inherited syntactically — call-site inference restores
                # any lock every caller provably holds
                self._visit_nested(node, self.b.funcs[key], cls)
                return
            if isinstance(node, ast.Lambda):
                return  # runs later, in an unknowable lock context
            if isinstance(node, ast.Call):
                handle_call(node, held)
                for child in ast.iter_child_nodes(node):
                    walk(child, held)
                return
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    record_write_target(tgt, held)
                    # self.X = ClassName(...) feeds attr-type inference
                    attr = self._self_attr(tgt)
                    value = node.value
                    if (attr is not None and cls is not None
                            and isinstance(value, ast.Call)):
                        tname = _dotted(value.func).rsplit(".", 1)[-1]
                        if tname in self.b.classes:
                            cls.attr_types.setdefault(attr, tname)
                        if LOCK_NAME_RE.search(attr) and tname in (
                                "Lock", "RLock", "Condition", "Semaphore",
                                "BoundedSemaphore"):
                            self.b.lock_kinds[f"{cls.name}.{attr}"] = tname
                if node.value is not None:
                    walk(node.value, held)
                return
            if isinstance(node, ast.Delete):
                for tgt in node.targets:
                    attr = self._self_attr(tgt)
                    if attr is not None and cls is not None:
                        record(f"{cls.name}.{attr}", "write", tgt, held)
                    elif isinstance(tgt, ast.Subscript):
                        sub = subject_of(tgt.value)
                        if sub is not None:
                            record(sub, "mutate", tgt, held)
                return
            if isinstance(node, ast.For):
                sub = subject_of(node.iter)
                if sub is not None:
                    record(sub, "read", node.iter, held, is_iter=True)
                for child in ast.iter_child_nodes(node):
                    walk(child, held)
                return
            if isinstance(node, ast.comprehension):
                sub = subject_of(node.iter)
                if sub is not None:
                    record(sub, "read", node.iter, held, is_iter=True)
                for child in ast.iter_child_nodes(node):
                    walk(child, held)
                return
            if isinstance(node, ast.Attribute) and isinstance(
                    node.ctx, ast.Load):
                sub = subject_of(node)
                if sub is not None:
                    record(sub, "read", node, held,
                           in_test=id(node) in self.test_nodes)
                    return
                if isinstance(node.value, (ast.Name, ast.Attribute)):
                    # a plain dotted chain: self.X.Y reads X once — do
                    # not descend
                    return
                # the base is itself an expression (a chained call like
                # Thread(...).start(), a subscript, ...): walk it, or
                # thread targets and accesses inside it go unseen
                walk(node.value, held)
                return
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                if is_global(node.id):
                    record(self._global_subject(node.id), "read", node,
                           held, in_test=id(node) in self.test_nodes)
                return
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        for stmt in fn.body:
            walk(stmt, frozenset())

    def _visit_nested(self, fn: ast.AST, info: FuncInfo,
                      cls: Optional[ClassInfo]) -> None:
        self._visit_function(fn, info, cls)


class _Builder:
    def __init__(self) -> None:
        self.funcs: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.module_classes: Dict[str, Dict[str, ClassInfo]] = {}
        self.module_funcs: Dict[str, Dict[str, str]] = {}
        self.method_index: Dict[str, List[str]] = {}
        self.accesses: List[Access] = []
        self.lock_edges: List[LockEdge] = []
        self.lock_kinds: Dict[str, str] = {}
        self.blocking_calls: List[BlockingCall] = []

    def resolve_method(self, cls: ClassInfo, name: str,
                       _depth: int = 0) -> Optional[str]:
        if name in cls.methods:
            return cls.methods[name]
        if _depth >= 4:
            return None
        for base in cls.bases:
            base_cls = self.classes.get(base.rsplit(".", 1)[-1])
            if base_cls is not None and base_cls is not cls:
                found = self.resolve_method(base_cls, name, _depth + 1)
                if found is not None:
                    return found
        return None


def build(modules: Sequence[ModuleInfo]) -> Project:
    """Build the whole-program model over the given module set."""
    b = _Builder()
    visitors: List[_ModuleVisitor] = []
    for mod in modules:
        if mod.tree is None:
            continue
        v = _ModuleVisitor(mod, b)
        v.scan_toplevel()
        visitors.append(v)
    for v in visitors:
        v.visit_bodies()

    # HTTP handlers: every do_* method runs on a per-connection thread
    for cls in b.classes.values():
        handlerish = "Handler" in cls.name or any(
            "Handler" in base for base in cls.bases)
        for name, key in cls.methods.items():
            if handlerish and name.startswith("do_"):
                fi = b.funcs[key]
                if fi.entry is None:
                    fi.entry = "HTTP handler (one thread per connection)"

    # thread reachability: BFS over resolved calls from every entry
    callers: Dict[str, List[Tuple[str, FrozenSet[str]]]] = {}
    for fi in b.funcs.values():
        for callee, held, _line in fi.calls:
            callers.setdefault(callee, []).append((fi.key, held))
    frontier = [k for k, fi in b.funcs.items() if fi.entry is not None]
    for key in frontier:
        b.funcs[key].thread_reachable = True
    while frontier:
        key = frontier.pop()
        for callee, _held, _line in b.funcs[key].calls:
            fi = b.funcs.get(callee)
            if fi is not None and not fi.thread_reachable:
                fi.thread_reachable = True
                frontier.append(callee)

    # called-with-lock-held inference, to fixpoint: a non-entry function
    # whose EVERY resolvable call site holds L executes under L
    inferred: Dict[str, FrozenSet[str]] = {
        k: frozenset() for k in b.funcs}
    for _ in range(10):
        changed = False
        for key, fi in b.funcs.items():
            sites = callers.get(key, [])
            if fi.entry is not None or not sites:
                target: FrozenSet[str] = frozenset()
            else:
                held_sets = [held | inferred[caller]
                             for caller, held in sites]
                target = frozenset.intersection(*held_sets)
            if target != inferred[key]:
                inferred[key] = target
                changed = True
        if not changed:
            break

    # transitive lock acquisition per function (for cross-method edges)
    acquired: Dict[str, Set[str]] = {
        k: set(fi.acquires) for k, fi in b.funcs.items()}
    for _ in range(20):
        changed = False
        for key, fi in b.funcs.items():
            for callee, _held, _line in fi.calls:
                extra = acquired.get(callee, set()) - acquired[key]
                if extra:
                    acquired[key].update(extra)
                    changed = True
        if not changed:
            break

    # cross-method lock edges: holding H while calling into a function
    # that (transitively) acquires more locks
    for fi in b.funcs.values():
        for callee, held, line in fi.calls:
            if callee not in b.funcs:
                continue
            full = held | inferred[fi.key]
            if not full:
                continue
            down = set(b.funcs[callee].acquires)
            for sub, _h, _l in b.funcs[callee].calls:
                down |= acquired.get(sub, set())
            for src in sorted(full):
                for dst in sorted(down):
                    b.lock_edges.append(LockEdge(
                        src=src, dst=dst, path=fi.path,
                        line=line, col=0, via=callee))

    # guard inference: majority of non-constructor writes under one lock
    by_subject: Dict[str, List[Access]] = {}
    for acc in b.accesses:
        by_subject.setdefault(acc.subject, []).append(acc)
    guards: Dict[str, GuardInfo] = {}
    for subject, accs in by_subject.items():
        tail = subject.rsplit(".", 1)[-1]
        if LOCK_NAME_RE.search(tail):
            continue  # the lock object itself is not a guarded subject
        writes = [a for a in accs if a.kind in ("write", "mutate")
                  and not a.in_init]
        if not writes:
            continue
        counts: Dict[str, int] = {}
        for a in writes:
            for lock in a.locks | inferred.get(a.func, frozenset()):
                counts[lock] = counts.get(lock, 0) + 1
        if not counts:
            continue
        best = max(sorted(counts), key=lambda k: counts[k])
        if counts[best] * 2 > len(writes):
            guards[subject] = GuardInfo(lock=best,
                                        locked_writes=counts[best],
                                        total_writes=len(writes))

    return Project(modules=list(modules), funcs=b.funcs, classes=b.classes,
                   accesses=b.accesses, guards=guards,
                   lock_edges=b.lock_edges, lock_kinds=b.lock_kinds,
                   inferred_held=inferred,
                   blocking_calls=b.blocking_calls)


# -- project rules -------------------------------------------------------------

class ProjectRule:
    """A whole-program analysis pass (cf. engine.Rule for per-file)."""

    id: str = ""
    name: str = ""
    rationale: str = ""

    def check(self, project: Project) -> Iterator[Finding]:
        raise NotImplementedError


#: rule id -> instance, in registration order.
PROJECT_RULES: Dict[str, ProjectRule] = {}


def register_project(cls: type) -> type:
    rule = cls()
    if not rule.id:
        raise ValueError(f"project rule {cls.__name__} has no id")
    if rule.id in PROJECT_RULES:
        raise ValueError(f"duplicate project rule id {rule.id}")
    PROJECT_RULES[rule.id] = rule
    return cls
