"""graftlint rules JT01-JT17 + JT22-JT23: hazards this codebase has hit.

Each rule encodes a failure class with a concrete precedent in this
tree's history (the bf16-Gramian divergence behind JT03 is recorded in
git: "Record bf16-Gramian rejection: Zipf groups break bf16
accumulation"). Rules are deliberately conservative AST passes — no
imports are executed, no type inference beyond local single-file
dataflow — so a finding is cheap to verify and a suppression comment
documents a reviewed exception.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from predictionio_tpu.tools.lint.engine import (
    FileContext,
    Finding,
    Rule,
    register,
)

# -- shared AST helpers --------------------------------------------------------

#: module spellings accepted for host numpy / device jax.numpy
_NP_MODULES = ("np", "numpy", "onp")
_JNP_MODULES = ("jnp", "jax.numpy")

#: attribute reads that are static under trace (shape metadata, not data)
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "weak_type", "aval"}

_LOW_PREC_NAMES = {"bfloat16", "float16", "bf16", "f16"}
_F32_NAMES = {"float32", "float64", "f32", "f64"}


def dotted(node: ast.AST) -> str:
    """``jax.numpy.sum`` for an Attribute/Name chain, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jit_callable(node: ast.AST) -> bool:
    d = dotted(node)
    return d in {"jit", "pjit"} or d.endswith(".jit") or d.endswith(".pjit")


def _const_strs(node: ast.AST) -> List[str]:
    """String constants in a literal or literal tuple/list."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[str] = []
        for elt in node.elts:
            out.extend(_const_strs(elt))
        return out
    return []


def _const_ints(node: ast.AST) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[int] = []
        for elt in node.elts:
            out.extend(_const_ints(elt))
        return out
    return []


def _jit_static_params(dec: ast.AST, fn: ast.FunctionDef) -> Optional[Set[str]]:
    """If ``dec`` marks ``fn`` as jit-compiled, the static param names.

    Recognizes ``@jax.jit`` / ``@jit`` / ``@pjit`` and the
    ``@(functools.)partial(jax.jit, static_arg...=...)`` idiom used
    throughout ops/ and models/. Returns None when not a jit decorator.
    """
    if _is_jit_callable(dec):
        return set()
    if not isinstance(dec, ast.Call):
        return None
    d = dotted(dec.func)
    inner = dec.args[0] if (
        d in {"partial", "functools.partial"} and dec.args
    ) else None
    if inner is None or not _is_jit_callable(inner):
        return None
    static: Set[str] = set()
    pos_params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for kw in dec.keywords:
        if kw.arg == "static_argnames":
            static.update(_const_strs(kw.value))
        elif kw.arg == "static_argnums":
            for i in _const_ints(kw.value):
                if 0 <= i < len(pos_params):
                    static.add(pos_params[i])
    return static


def iter_jit_functions(
    tree: ast.AST,
) -> Iterator[Tuple[ast.FunctionDef, Set[str], Set[str]]]:
    """Yield (function, traced-params, static-params) per jit'd def."""
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            static = _jit_static_params(dec, node)
            if static is None:
                continue
            params = {
                a.arg
                for a in (node.args.posonlyargs + node.args.args
                          + node.args.kwonlyargs)
            }
            yield node, params - static, static
            break


def _walk_body(fn: ast.FunctionDef) -> Iterator[ast.AST]:
    """Walk a function's body, skipping its decorators and signature."""
    for stmt in fn.body:
        yield from ast.walk(stmt)


def _parent_map(root: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(root):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _is_staticish(node: ast.AST, static_names: Set[str] = frozenset()) -> bool:
    """True when an expression reads only trace-time-static values
    (shapes, dims, len(), declared-static jit params) — safe to feed to
    float()/int() under jit."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Name):
        return node.id in static_names
    if isinstance(node, ast.Attribute):
        return node.attr in _STATIC_ATTRS
    if isinstance(node, ast.Subscript):
        return _is_staticish(node.value, static_names)
    if isinstance(node, ast.Call):
        return dotted(node.func) == "len"
    if isinstance(node, ast.BinOp):
        return (_is_staticish(node.left, static_names)
                and _is_staticish(node.right, static_names))
    if isinstance(node, ast.UnaryOp):
        return _is_staticish(node.operand, static_names)
    return False


def _is_low_prec_dtype(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value in _LOW_PREC_NAMES
    if isinstance(node, ast.Attribute):
        return node.attr in _LOW_PREC_NAMES
    return False


def _is_f32_dtype(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value in _F32_NAMES
    if isinstance(node, ast.Attribute):
        return node.attr in _F32_NAMES
    return False


def _is_low_prec_cast(node: ast.AST) -> bool:
    """``x.astype(jnp.bfloat16)``, ``jnp.asarray(x, dtype='bfloat16')``,
    ``jnp.bfloat16(x)`` — an expression that demotes data below f32."""
    if not isinstance(node, ast.Call):
        return False
    if isinstance(node.func, ast.Attribute) and node.func.attr == "astype":
        return bool(node.args) and _is_low_prec_dtype(node.args[0])
    d = dotted(node.func)
    tail = d.rsplit(".", 1)[-1]
    if tail in _LOW_PREC_NAMES:
        return True
    if tail in {"asarray", "array", "full", "zeros", "ones"}:
        return any(
            kw.arg == "dtype" and _is_low_prec_dtype(kw.value)
            for kw in node.keywords
        )
    return False


def _contains_low_prec(node: ast.AST, tainted: Set[str]) -> bool:
    for sub in ast.walk(node):
        if _is_low_prec_cast(sub):
            return True
        if (isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load)
                and sub.id in tainted):
            return True
    return False


# -- JT01 ----------------------------------------------------------------------

@register
class HostSyncInJit(Rule):
    id = "JT01"
    name = "host-sync-in-jit"
    rationale = (
        "float()/int()/bool()/.item()/np.asarray() on a traced value "
        "forces a device->host sync (or a ConcretizationTypeError) "
        "inside a jit trace; redundant asarray chains pay an extra host "
        "copy on the serving path."
    )

    _HOST_CASTS = {"float", "int", "bool", "complex"}
    _NP_PULLS = {f"{m}.{fn}" for m in _NP_MODULES for fn in ("asarray", "array")}
    _ASARRAYS = _NP_PULLS | {f"{m}.asarray" for m in _JNP_MODULES} | {
        f"{m}.array" for m in _JNP_MODULES
    }

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        in_jit: Set[ast.AST] = set()
        for fn, _traced, static in iter_jit_functions(ctx.tree):
            for node in _walk_body(fn):
                in_jit.add(node)
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func)
                if d in self._HOST_CASTS and node.args and not _is_staticish(
                    node.args[0], static
                ):
                    yield Finding(
                        self.id, ctx.path, node.lineno, node.col_offset,
                        f"{d}() on a (possibly traced) value inside a "
                        "jit-compiled function blocks the trace with a "
                        "host sync; compute in-graph or hoist out of jit",
                    )
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item"
                        and not _is_staticish(node.func.value, static)):
                    yield Finding(
                        self.id, ctx.path, node.lineno, node.col_offset,
                        ".item() inside a jit-compiled function forces a "
                        "device->host transfer per call; return the array "
                        "and pull the scalar outside jit",
                    )
                elif d in self._NP_PULLS and not (
                    node.args and _is_staticish(node.args[0], static)
                ):
                    yield Finding(
                        self.id, ctx.path, node.lineno, node.col_offset,
                        f"{d}() inside a jit-compiled function "
                        "materializes on host mid-trace; use jnp and keep "
                        "the value on device",
                    )
        # redundant double conversion anywhere (the serving-path cost):
        # asarray(asarray(x)) round-trips through a host buffer that a
        # single asarray(x, dtype=...) never allocates
        for node in ast.walk(ctx.tree):
            if node in in_jit or not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if d in self._ASARRAYS and node.args and isinstance(
                node.args[0], ast.Call
            ):
                inner = dotted(node.args[0].func)
                if inner in self._ASARRAYS:
                    yield Finding(
                        self.id, ctx.path, node.lineno, node.col_offset,
                        f"redundant double conversion {d}({inner}(...)): "
                        "collapse to one asarray(..., dtype=...) call and "
                        "skip the intermediate host copy",
                    )


# -- JT02 ----------------------------------------------------------------------

@register
class PythonBranchOnTracer(Rule):
    id = "JT02"
    name = "python-branch-on-tracer"
    rationale = (
        "Python if/while on a traced argument inside jit either raises "
        "ConcretizationTypeError or, via static_argnums misuse, triggers "
        "silent per-value recompilation; use lax.cond/select or declare "
        "the argument static."
    )

    _SAFE_CALLS = {"len", "isinstance", "hasattr", "getattr", "type"}

    def _exposed_name(self, test: ast.AST, traced: Set[str]) -> Optional[str]:
        parents = _parent_map(test)
        for node in ast.walk(test):
            if not (isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in traced):
                continue
            parent = parents.get(node)
            if isinstance(parent, ast.Attribute) and (
                parent.attr in _STATIC_ATTRS
            ):
                continue  # x.shape[0] > 2 — static under trace
            if isinstance(parent, ast.Call) and node in parent.args and (
                dotted(parent.func) in self._SAFE_CALLS
            ):
                continue  # len(x) — static under trace
            return node.id
        return None

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for fn, traced, _static in iter_jit_functions(ctx.tree):
            if not traced:
                continue
            for node in _walk_body(fn):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                name = self._exposed_name(node.test, traced)
                if name is not None:
                    kind = "if" if isinstance(node, ast.If) else "while"
                    yield Finding(
                        self.id, ctx.path, node.lineno, node.col_offset,
                        f"Python `{kind}` on traced argument `{name}` "
                        f"inside jit-compiled `{fn.name}`; use "
                        "jax.lax.cond/select/while_loop or mark the "
                        "argument static",
                    )


# -- JT03 ----------------------------------------------------------------------

@register
class LowPrecisionAccumulation(Rule):
    id = "JT03"
    name = "low-precision-accumulation"
    rationale = (
        "Reducing bf16/f16-cast operands without an f32 accumulator "
        "(preferred_element_type / dtype=float32) silently loses mass "
        "once partial sums exceed the mantissa — the bf16-Gramian "
        "divergence on Zipf-distributed groups recorded in git history."
    )

    _REDUCERS = {"sum", "mean", "matmul", "dot", "einsum", "tensordot",
                 "vdot", "inner", "segment_sum"}

    def _has_f32_accumulator(self, call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg == "preferred_element_type":
                return True
            if kw.arg == "dtype" and _is_f32_dtype(kw.value):
                return True
        return False

    def _operands(self, call: ast.Call) -> List[ast.AST]:
        ops = list(call.args)
        if isinstance(call.func, ast.Attribute):
            ops.append(call.func.value)  # x.astype(bf16).sum() method form
        return ops

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # file-local dataflow: names ever assigned from a low-precision
        # cast are tainted (no reassignment clearing — a linter
        # over-approximates; suppress with justification where reviewed)
        tainted: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and _is_low_prec_cast(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        tainted.add(tgt.id)
            elif isinstance(node, ast.AnnAssign) and node.value is not None \
                    and _is_low_prec_cast(node.value):
                if isinstance(node.target, ast.Name):
                    tainted.add(node.target.id)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                tail = dotted(node.func).rsplit(".", 1)[-1] or (
                    node.func.attr if isinstance(node.func, ast.Attribute)
                    else ""
                )
                if tail not in self._REDUCERS:
                    continue
                if self._has_f32_accumulator(node):
                    continue
                if any(_contains_low_prec(op, tainted)
                       for op in self._operands(node)):
                    yield Finding(
                        self.id, ctx.path, node.lineno, node.col_offset,
                        f"{tail}() over bf16/f16-cast operands without an "
                        "f32 accumulator; pass "
                        "preferred_element_type=jnp.float32 (matmul/dot/"
                        "einsum) or dtype=jnp.float32 (sum/segment_sum)",
                    )
            elif isinstance(node, ast.BinOp) and isinstance(
                node.op, ast.MatMult
            ):
                if _contains_low_prec(node.left, tainted) or (
                    _contains_low_prec(node.right, tainted)
                ):
                    yield Finding(
                        self.id, ctx.path, node.lineno, node.col_offset,
                        "`@` matmul over bf16/f16-cast operands "
                        "accumulates in low precision; use jnp.matmul(..., "
                        "preferred_element_type=jnp.float32)",
                    )


# -- JT04 ----------------------------------------------------------------------

@register
class SilentBroadExcept(Rule):
    id = "JT04"
    name = "silent-broad-except"
    rationale = (
        "`except Exception` that neither logs nor re-raises turns "
        "serving/storage/workflow failures into silent data loss; the "
        "operator's first symptom is wrong predictions, not an error."
    )

    _LOG_ATTRS = {"debug", "info", "warning", "warn", "error", "exception",
                  "critical", "log"}

    def applies_to(self, abspath: str) -> bool:
        return ("/serving/" in abspath or "/workflow/" in abspath
                or abspath.endswith("/data/storage.py"))

    def _is_broad(self, handler: ast.ExceptHandler) -> bool:
        if handler.type is None:
            return True
        types = (handler.type.elts if isinstance(handler.type, ast.Tuple)
                 else [handler.type])
        return any(
            dotted(t).rsplit(".", 1)[-1] in {"Exception", "BaseException"}
            for t in types
        )

    def _handles(self, handler: ast.ExceptHandler) -> bool:
        for node in ast.walk(handler):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ) and node.func.attr in self._LOG_ATTRS:
                return True
            # relaying counts: `except ... as e` whose body READS e
            # (p.error = e, self._send(500, str(e))) surfaces the error
            # to a caller/client instead of discarding it
            if handler.name and isinstance(node, ast.Name) and (
                node.id == handler.name
                and isinstance(node.ctx, ast.Load)
            ):
                return True
        return False

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if self._is_broad(handler) and not self._handles(handler):
                    yield Finding(
                        self.id, ctx.path, handler.lineno, handler.col_offset,
                        "broad except swallows the error without logging "
                        "or re-raising; log at warning level with context "
                        "or narrow the exception type",
                    )


# -- JT05 ----------------------------------------------------------------------

@register
class MeshAxisConsistency(Rule):
    id = "JT05"
    name = "mesh-axis-consistency"
    rationale = (
        "A PartitionSpec axis name that parallel/mesh.py never declares "
        "shards nothing: XLA replicates the array and the intended "
        "parallelism silently degrades to a full copy per device."
    )

    _FALLBACK_AXES = ("data", "model")

    def __init__(self) -> None:
        self._axes_cache: Dict[str, Tuple[str, ...]] = {}

    def applies_to(self, abspath: str) -> bool:
        return any(seg in abspath
                   for seg in ("/ops/", "/parallel/", "/templates/"))

    def _declared_axes(self, abspath: str) -> Tuple[str, ...]:
        """MESH_AXES from the nearest parallel/mesh.py up the tree."""
        d = os.path.dirname(abspath)
        seen: List[str] = []
        for _ in range(8):
            if d in self._axes_cache:
                axes = self._axes_cache[d]
                for s in seen:
                    self._axes_cache[s] = axes
                return axes
            seen.append(d)
            mesh_py = os.path.join(d, "parallel", "mesh.py")
            if os.path.isfile(mesh_py):
                axes = self._parse_axes(mesh_py)
                for s in seen:
                    self._axes_cache[s] = axes
                return axes
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
        for s in seen:
            self._axes_cache[s] = self._FALLBACK_AXES
        return self._FALLBACK_AXES

    def _parse_axes(self, mesh_py: str) -> Tuple[str, ...]:
        try:
            with open(mesh_py, "r", encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=mesh_py)
        except (OSError, SyntaxError):
            return self._FALLBACK_AXES
        for node in ast.walk(tree):
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            else:
                continue
            for tgt in targets:
                if isinstance(tgt, ast.Name) and tgt.id == "MESH_AXES":
                    axes = tuple(_const_strs(value))
                    if axes:
                        return axes
        return self._FALLBACK_AXES

    def _spec_aliases(self, tree: ast.AST) -> Set[str]:
        aliases: Set[str] = {"PartitionSpec"}
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                for a in node.names:
                    if a.name == "PartitionSpec":
                        aliases.add(a.asname or a.name)
        return aliases

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        axes = self._declared_axes(ctx.abspath)
        aliases = self._spec_aliases(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if not (d in aliases or d.endswith(".PartitionSpec")):
                continue
            for arg in node.args:
                for name in _const_strs(arg):
                    if name not in axes:
                        yield Finding(
                            self.id, ctx.path, node.lineno, node.col_offset,
                            f"PartitionSpec axis {name!r} is not declared "
                            f"by parallel/mesh.py (declared: "
                            f"{', '.join(axes)}); the array would be "
                            "silently replicated",
                        )


# -- JT06 ----------------------------------------------------------------------

@register
class BlockingTransferInHandler(Rule):
    id = "JT06"
    name = "blocking-transfer-in-handler"
    rationale = (
        "A per-request block_until_ready/device_get/np.asarray inside an "
        "HTTP handler serializes the device behind one connection; route "
        "device work through the micro-batcher (Deployment.query_batch) "
        "so concurrent requests share one dispatch."
    )

    _BLOCKING_ATTRS = {"block_until_ready", "device_get", "copy_to_host_async"}
    _BLOCKING_CALLS = {f"{m}.{fn}" for m in _NP_MODULES
                       for fn in ("asarray", "array")}

    def applies_to(self, abspath: str) -> bool:
        return "/serving/" in abspath and abspath.endswith("_server.py")

    def _handler_classes(self, tree: ast.AST) -> Iterator[ast.ClassDef]:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and (
                "Handler" in node.name
                or any("Handler" in dotted(b) for b in node.bases)
            ):
                yield node

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for cls in self._handler_classes(ctx.tree):
            for node in ast.walk(cls):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func)
                attr = (node.func.attr
                        if isinstance(node.func, ast.Attribute) else "")
                if attr in self._BLOCKING_ATTRS or d in self._BLOCKING_CALLS \
                        or d.endswith(".device_get"):
                    what = attr or d
                    yield Finding(
                        self.id, ctx.path, node.lineno, node.col_offset,
                        f"blocking transfer {what}() inside request "
                        f"handler {cls.name}; per-request host syncs "
                        "serialize the device — go through the "
                        "micro-batched query path",
                    )


# -- JT07 ----------------------------------------------------------------------

@register
class MissingBufferDonation(Rule):
    id = "JT07"
    name = "missing-buffer-donation"
    rationale = (
        "A jit'd step called as `params, ... = step(params, ...)` without "
        "donate_argnums/donate_argnames keeps the old AND new buffers "
        "live across the call — the rebound arrays' peak HBM doubles; "
        "donate the rebound arguments."
    )

    _DONATE_KWARGS = {"donate_argnums", "donate_argnames"}

    def _jit_call_donates(self, call: ast.Call) -> Optional[bool]:
        """For ``jax.jit(f, ...)`` / ``partial(jax.jit, ...)`` calls:
        whether donation is declared; None when not a jit call."""
        if not isinstance(call, ast.Call):
            return None
        if _is_jit_callable(call.func):
            return any(kw.arg in self._DONATE_KWARGS for kw in call.keywords)
        d = dotted(call.func)
        if d in {"partial", "functools.partial"} and call.args and (
            _is_jit_callable(call.args[0])
        ):
            return any(kw.arg in self._DONATE_KWARGS for kw in call.keywords)
        return None

    def _jit_targets(self, tree: ast.AST) -> Dict[str, bool]:
        """Dotted callee name -> donation declared, for every jit'd
        function visible file-locally: decorated defs and
        ``x = jax.jit(f, ...)`` bindings (incl. ``self._step = ...``)."""
        donates: Dict[str, bool] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _is_jit_callable(dec):
                        donates[node.name] = False      # bare @jax.jit
                    elif isinstance(dec, ast.Call):
                        # @partial(jax.jit, ...) / @jax.jit(...) forms
                        declared = self._jit_call_donates(dec)
                        if declared is not None:
                            donates[node.name] = declared
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                declared = self._jit_call_donates(node.value)
                if declared is None:
                    continue
                for tgt in node.targets:
                    name = dotted(tgt)
                    if name:
                        donates[name] = declared
        return donates

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        donates = self._jit_targets(ctx.tree)
        if not donates:
            return
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            callee = dotted(node.value.func)
            if donates.get(callee, True):
                continue  # not a known jit target, or donation declared
            passed = {dotted(a) for a in node.value.args} | {
                dotted(kw.value) for kw in node.value.keywords
            }
            passed.discard("")
            rebound: Set[str] = set()
            for tgt in node.targets:
                elts = tgt.elts if isinstance(tgt, (ast.Tuple, ast.List)) \
                    else [tgt]
                rebound.update(dotted(t) for t in elts)
            overlap = sorted(rebound & passed)
            if overlap:
                yield Finding(
                    self.id, ctx.path, node.lineno, node.col_offset,
                    f"jit'd `{callee}` rebinds its own argument(s) "
                    f"{', '.join(overlap)} without buffer donation — old "
                    "and new buffers coexist, doubling their peak HBM; "
                    "declare donate_argnums/donate_argnames for the "
                    "rebound arguments",
                )


# -- JT08 ----------------------------------------------------------------------

@register
class CompileCacheKeyInstability(Rule):
    id = "JT08"
    name = "compile-cache-key-instability"
    rationale = (
        "A jit-wrapped closure capturing unhashable or per-process Python "
        "state (dict/list/set displays, time/pid/uuid/random values) "
        "bakes that state into the traced program as constants, so "
        "byte-identical work fingerprints differently per process and "
        "the persistent compile cache (parallel/compile_cache.py) "
        "silently misses across trains/deploys/reloads."
    )

    #: calls whose value differs per process/invocation: traced in as a
    #: constant, each process compiles a different program
    _NONDET_CALLS = {
        "time.time", "time.time_ns", "time.monotonic", "time.perf_counter",
        "os.getpid", "os.urandom", "uuid.uuid1", "uuid.uuid4",
        "id", "hash",
    }
    #: stdlib/numpy RNG draws are per-process too; jax.random is NOT
    #: listed — its draws are pure functions of an explicit key
    _NONDET_PREFIXES = ("random.", "np.random.", "numpy.random.")

    _UNHASHABLE = (ast.Dict, ast.List, ast.Set,
                   ast.ListComp, ast.SetComp, ast.DictComp)

    def _is_nondet_call(self, node: ast.AST) -> Optional[str]:
        if not isinstance(node, ast.Call):
            return None
        d = dotted(node.func)
        if d in self._NONDET_CALLS or d.startswith(self._NONDET_PREFIXES):
            return d
        return None

    @staticmethod
    def _fn_params(fn) -> Set[str]:
        args = fn.args
        names = {a.arg for a in (args.posonlyargs + args.args
                                 + args.kwonlyargs)}
        if args.vararg:
            names.add(args.vararg.arg)
        if args.kwarg:
            names.add(args.kwarg.arg)
        return names

    def _free_names(self, fn) -> Set[str]:
        """Names a lambda/nested def reads but neither receives nor
        binds itself — the closure captures."""
        body = [fn.body] if isinstance(fn, ast.Lambda) else fn.body
        loads: Set[str] = set()
        stores: Set[str] = set(self._fn_params(fn))
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Name):
                    if isinstance(node.ctx, ast.Load):
                        loads.add(node.id)
                    else:
                        stores.add(node.id)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    stores.add(node.name)
        return loads - stores

    def _check_closure(self, ctx: FileContext, site: ast.AST, fn: ast.AST,
                       assigns: Dict[str, ast.AST]) -> Iterator[Finding]:
        for name in sorted(self._free_names(fn)):
            value = assigns.get(name)
            if value is None:
                continue
            if isinstance(value, self._UNHASHABLE):
                kind = type(value).__name__.lower().replace("comp",
                                                            " comprehension")
                yield Finding(
                    self.id, ctx.path, site.lineno, site.col_offset,
                    f"jit-wrapped closure captures `{name}`, a {kind} "
                    "built in the enclosing scope — its contents trace "
                    "in as constants, so per-process variation defeats "
                    "the persistent compile cache; pass it as a (static) "
                    "argument or hoist it to a module-level constant",
                )
                continue
            nondet = self._is_nondet_call(value)
            if nondet is not None:
                yield Finding(
                    self.id, ctx.path, site.lineno, site.col_offset,
                    f"jit-wrapped closure captures `{name}` = {nondet}() "
                    "— a per-process value traced in as a constant "
                    "guarantees a persistent compile-cache miss in every "
                    "new process; pass it as a traced argument instead",
                )

    @staticmethod
    def _scope_nodes(fn) -> Iterator[ast.AST]:
        """Walk a function's body WITHOUT descending into nested
        function/lambda bodies: a sibling helper's locals are not this
        scope's bindings, and attributing them here would flag
        cache-stable captures of same-named outer/module values."""
        stack: List[ast.AST] = list(fn.body)
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue  # separate scope — visited on its own turn
            stack.extend(ast.iter_child_nodes(node))

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # (1) per-process values consumed DIRECTLY inside any jit'd body
        for fn, _traced, _static in iter_jit_functions(ctx.tree):
            for node in _walk_body(fn):
                nondet = self._is_nondet_call(node)
                if nondet is not None:
                    yield Finding(
                        self.id, ctx.path, node.lineno, node.col_offset,
                        f"{nondet}() inside jit-compiled `{fn.name}` "
                        "traces to a per-process constant — every new "
                        "process compiles (and caches) a different "
                        "program; compute it outside and pass it in",
                    )
        # (2) jit-wrapped closures capturing unstable enclosing state;
        # each function is analyzed as ITS OWN scope (ast.walk visits
        # nested defs separately), so bindings never leak across scopes
        for outer in ast.walk(ctx.tree):
            if not isinstance(outer, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            local_defs: Dict[str, ast.AST] = {}
            assigns: Dict[str, ast.AST] = {}
            for node in self._scope_nodes(outer):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    local_defs[node.name] = node
                elif isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            assigns.setdefault(tgt.id, node.value)
                elif isinstance(node, ast.AnnAssign) and (
                        node.value is not None
                        and isinstance(node.target, ast.Name)):
                    assigns.setdefault(node.target.id, node.value)
            for node in self._scope_nodes(outer):
                fn_node: Optional[ast.AST] = None
                site: ast.AST = node
                if isinstance(node, ast.Call) and _is_jit_callable(node.func):
                    if not node.args:
                        continue
                    target = node.args[0]
                    if isinstance(target, ast.Lambda):
                        fn_node = target
                    elif isinstance(target, ast.Name):
                        fn_node = local_defs.get(target.id)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    # a jit-DECORATED def nested in a function is a
                    # closure too
                    if any(_jit_static_params(dec, node) is not None
                           for dec in node.decorator_list):
                        fn_node = node
                if fn_node is not None:
                    yield from self._check_closure(ctx, site, fn_node,
                                                   assigns)


# -- JT09 ----------------------------------------------------------------------

@register
class UnsupervisedDaemonThread(Rule):
    id = "JT09"
    name = "unsupervised-daemon-thread"
    rationale = (
        "A background threading.Thread whose service loop can raise "
        "without a broad except-that-logs dies silently: the pusher/"
        "watchdog/worker it implemented simply stops forever, and the "
        "operator's first symptom is the absence of the thing it "
        "produced. Every loop-running thread body needs a broad "
        "except-with-log inside (or logged around) its loop."
    )

    _THREAD_NAMES = {"Thread", "threading.Thread"}

    def _thread_targets(self, tree: ast.AST) -> Iterator[Tuple[ast.AST, str]]:
        """(call node, target's last name component) for every
        ``threading.Thread(target=...)`` whose target is resolvable
        file-locally (a bare name or attribute chain — external
        callables like ``server.serve_forever`` resolve to nothing)."""
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted(node.func) not in self._THREAD_NAMES:
                continue
            for kw in node.keywords:
                if kw.arg == "target":
                    name = dotted(kw.value).rsplit(".", 1)[-1]
                    if name:
                        yield node, name

    @staticmethod
    def _own_scope(fn: ast.AST) -> Iterator[ast.AST]:
        """Walk a function body without descending into nested defs or
        lambdas — their loops run in other call frames."""
        stack: List[ast.AST] = list(fn.body)
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    def _broad_logging_try(self, node: ast.AST) -> bool:
        """A Try with a broad (bare/Exception/BaseException) handler
        that logs — the supervision this rule requires."""
        if not isinstance(node, ast.Try):
            return False
        for handler in node.handlers:
            types = ([] if handler.type is None else
                     handler.type.elts if isinstance(handler.type, ast.Tuple)
                     else [handler.type])
            broad = handler.type is None or any(
                dotted(t).rsplit(".", 1)[-1] in {"Exception", "BaseException"}
                for t in types
            )
            if not broad:
                continue
            for sub in ast.walk(handler):
                if isinstance(sub, ast.Call) and isinstance(
                    sub.func, ast.Attribute
                ) and sub.func.attr in SilentBroadExcept._LOG_ATTRS:
                    return True
        return False

    def _loop_supervised(self, loop: ast.AST,
                         parents: Dict[ast.AST, ast.AST],
                         fn: ast.AST) -> bool:
        # supervised inside: any broad-logging try within the loop body
        for sub in ast.walk(loop):
            if sub is not loop and self._broad_logging_try(sub):
                return True
        # supervised outside: a broad-logging try wrapping the loop
        # (the thread then logs its own death instead of vanishing)
        node = parents.get(loop)
        while node is not None and node is not fn:
            if self._broad_logging_try(node):
                return True
            node = parents.get(node)
        return False

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        defs: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)
        seen: Set[ast.AST] = set()
        for _call, target in self._thread_targets(ctx.tree):
            for fn in defs.get(target, ()):
                if fn in seen:
                    continue
                seen.add(fn)
                parents = _parent_map(fn)
                # every unsupervised loop is ITS OWN finding: a
                # supervised main loop must not mask an unsupervised
                # sibling (drain/retry) loop in the same thread body.
                # Loops nested inside a flagged loop are skipped — one
                # unsupervised body, one report.
                flagged: List[ast.AST] = []
                loops = sorted(
                    (n for n in self._own_scope(fn)
                     if isinstance(n, (ast.While, ast.For))),
                    key=lambda n: (n.lineno, n.col_offset))
                for loop in loops:
                    if any(loop is not f and self._is_within(loop, f, parents)
                           for f in flagged):
                        continue
                    if self._loop_supervised(loop, parents, fn):
                        continue
                    flagged.append(loop)
                    yield Finding(
                        self.id, ctx.path, loop.lineno, loop.col_offset,
                        f"thread target `{fn.name}` runs a loop with no "
                        "broad except-with-log — if an iteration raises, "
                        "the background thread dies silently; wrap the "
                        "loop body in try/except Exception with a "
                        "log.exception call",
                    )

    @staticmethod
    def _is_within(node: ast.AST, ancestor: ast.AST,
                   parents: Dict[ast.AST, ast.AST]) -> bool:
        cur = parents.get(node)
        while cur is not None:
            if cur is ancestor:
                return True
            cur = parents.get(cur)
        return False


# -- JT10 ----------------------------------------------------------------------

@register
class OutboundCallWithoutTimeout(Rule):
    id = "JT10"
    name = "outbound-call-without-timeout"
    rationale = (
        "An outbound network call with no explicit timeout blocks its "
        "thread for as long as the peer cares to hold the socket: a "
        "hung storage server strands a serving handler, a dead "
        "metrics sink strands its daemon thread, and the watchdog "
        "fires on a stall a deadline would have bounded. Every "
        "urlopen/HTTPConnection/create_connection call must pass "
        "timeout= (ideally from a resilience Policy's deadline)."
    )

    #: callable's last name component -> index of the positional slot
    #: that carries the timeout (passing it positionally also counts)
    _TIMEOUT_SLOT = {
        "urlopen": 2,             # urlopen(url, data, timeout)
        "HTTPConnection": 2,      # HTTPConnection(host, port, timeout)
        "HTTPSConnection": 2,
        "create_connection": 1,   # create_connection(address, timeout)
    }

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func).rsplit(".", 1)[-1]
            slot = self._TIMEOUT_SLOT.get(name)
            if slot is None:
                continue
            if any(kw.arg == "timeout" for kw in node.keywords):
                continue
            if len(node.args) > slot:
                continue  # timeout passed positionally
            if any(isinstance(a, ast.Starred) for a in node.args) or any(
                kw.arg is None for kw in node.keywords
            ):
                continue  # *args/**kwargs may carry it; not decidable
            yield Finding(
                self.id, ctx.path, node.lineno, node.col_offset,
                f"`{name}` call without an explicit timeout — a hung "
                "peer strands this thread forever; pass timeout= "
                "(e.g. a resilience Policy's .deadline)",
            )


# -- JT11 ----------------------------------------------------------------------

@register
class UnboundedMetricLabelCardinality(Rule):
    id = "JT11"
    name = "unbounded-metric-label-cardinality"
    rationale = (
        "A metric label valued from per-request data (trace ids, "
        "user/entity/item ids, raw query strings) mints one time "
        "series per distinct value: the registry grows without bound, "
        "every /metrics scrape re-renders the whole cemetery, and the "
        "collector eventually OOMs. Label by bounded dimensions (route "
        "template, status, engine id) and carry per-request data as "
        "OpenMetrics exemplars, trace spans or flight-recorder fields "
        "instead."
    )

    #: identifier tails that are per-request by construction in this
    #: tree: trace/span/request/event/prediction ids, end-user and
    #: catalog-entity ids, raw query payloads
    _SUSPECT = re.compile(
        r"(?:^|_)(?:trace|span|request|req|event|pr)_?id$"
        r"|^(?:user|entity|item|session|uid|qid)(?:_id)?$"
        r"|^(?:query|raw_query|query_string)$"
    )

    #: value-preserving wrappers to look through: str(user_id) is as
    #: unbounded as user_id
    _WRAPPERS = {"str", "repr", "format"}

    def _suspect_name(self, node: ast.AST) -> Optional[str]:
        """The per-request identifier a label-value expression derives
        from, or None. Looks through Name/Attribute tails, str()/repr()
        wrappers, and f-string interpolations."""
        if isinstance(node, (ast.Name, ast.Attribute)):
            tail = dotted(node).rsplit(".", 1)[-1]
            if tail and self._SUSPECT.search(tail):
                return tail
            return None
        if isinstance(node, ast.Call):
            fn = dotted(node.func).rsplit(".", 1)[-1]
            if fn in self._WRAPPERS and node.args:
                return self._suspect_name(node.args[0])
            return None
        if isinstance(node, ast.JoinedStr):
            for part in node.values:
                if isinstance(part, ast.FormattedValue):
                    found = self._suspect_name(part.value)
                    if found:
                        return found
            return None
        if isinstance(node, ast.BinOp):  # "u-" + user_id concatenation
            return (self._suspect_name(node.left)
                    or self._suspect_name(node.right))
        return None

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if not (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "labels"):
                continue
            values = list(node.args) + [kw.value for kw in node.keywords
                                        if kw.arg is not None]
            for value in values:
                found = self._suspect_name(value)
                if found:
                    yield Finding(
                        self.id, ctx.path, value.lineno, value.col_offset,
                        f"metric label valued from per-request data "
                        f"(`{found}`) — every distinct value mints a new "
                        "time series and the registry grows without "
                        "bound; label by a bounded dimension and put "
                        "the id in an exemplar, span or flight record",
                    )


# -- JT12 ----------------------------------------------------------------------

@register
class JoinWaitWithoutTimeout(Rule):
    id = "JT12"
    name = "join-wait-without-timeout"
    rationale = (
        "A bare Thread.join() / Process.join() / Event.wait() / "
        "Popen.wait() blocks its caller for as long as the other side "
        "cares to stay stuck: a fleet supervisor joining a dead "
        "replica's thread, a main waiting on a wedged child process, "
        "or a shutdown path waiting on an event nobody will ever set "
        "hangs FOREVER — precisely during the crash it exists to "
        "clean up after. Pass timeout= (and handle the expiry) so a "
        "dead peer costs a bounded wait, never a hung supervisor. "
        "Receivers with NO timeout parameter (queue.Queue.join, "
        "multiprocessing Pool.join, os.wait) are exempted by receiver-"
        "name heuristic; anything the heuristic misses documents "
        "itself with a suppression comment."
    )

    #: receiver name fragments whose join()/wait() take no timeout at
    #: all — flagging them would demand an impossible fix
    _NO_TIMEOUT_RECEIVERS = ("queue", "pool")

    @staticmethod
    def _is_none(n: ast.AST) -> bool:
        return isinstance(n, ast.Constant) and n.value is None

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        _is_none = self._is_none
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            if func.attr not in ("join", "wait"):
                continue
            # any argument can carry the timeout: str.join(iterable),
            # thread.join(5), futures.wait(fs, 10) all pass — but a
            # literal None (join(None) / wait(timeout=None)) is the
            # bare unbounded wait spelled out, not a bound
            if (any(not _is_none(a) for a in node.args)
                    or any(kw.arg == "timeout" and not _is_none(kw.value)
                           for kw in node.keywords)):
                continue
            if any(kw.arg is None for kw in node.keywords):
                continue  # **kwargs may carry it; not decidable
            # receiver-is-a-call: Pallas DMA descriptors
            # (`make_async_copy(...).wait()`) and friends — a device-
            # side completion wait with no timeout concept, not a
            # thread join
            if isinstance(func.value, ast.Call):
                continue
            # receivers whose join/wait signature has no timeout:
            # os.wait(), queue.join(), pool.join() — "pass timeout="
            # would be a TypeError, so the rule must stay silent
            receiver = dotted(func.value).lower()
            tail = receiver.rsplit(".", 1)[-1]
            # the exempting noun must be the receiver's HEAD word (the
            # last underscore segment: work_queue, worker_pool) — a
            # substring test would also swallow queue_drained_evt.wait()
            # / pool_ready.wait(), which are exactly the hazard class
            if receiver == "os" or (tail.rsplit("_", 1)[-1]
                                    in self._NO_TIMEOUT_RECEIVERS):
                continue
            yield Finding(
                self.id, ctx.path, node.lineno, node.col_offset,
                f"bare `.{func.attr}()` with no timeout — a dead/"
                "wedged peer blocks this thread forever (a supervisor "
                "must never hang on a dead replica); pass timeout= "
                "and handle the expiry",
            )


# -- JT13 ----------------------------------------------------------------------

@register
class CopyInducingDeviceTransfer(Rule):
    id = "JT13"
    name = "copy-inducing-device-transfer"
    rationale = (
        "jax.device_put / jnp.array / jnp.asarray on a Python list, a "
        ".tolist() product, or a non-contiguous (stepped) slice forces "
        "a host-side serialize/copy before a single byte can cross to "
        "the device: the list round-trips element-by-element through "
        "the Python object layer, and the strided view is densified "
        "into a fresh host buffer first. On the data-path hot lanes "
        "(this repo's whole zero-copy design: native buffers -> numpy "
        "views -> device_put with no copies) that silently re-adds the "
        "copy the pipeline exists to remove. Build a contiguous "
        "ndarray first (np.asarray / np.ascontiguousarray) — or keep "
        "the native view and put IT."
    )

    #: the hazard lives where bulk arrays move; tiny constant lists in
    #: tests/CLI glue are not worth the noise
    def applies_to(self, abspath: str) -> bool:
        return ("/ops/" in abspath or "/data/" in abspath
                or "/models/" in abspath or "/templates/" in abspath
                or "/parallel/" in abspath)

    _TRANSFER_TAILS = {"device_put", "array", "asarray"}

    def _is_transfer(self, func: ast.AST) -> bool:
        d = dotted(func)
        if not d:
            return False
        head, _, tail = d.rpartition(".")
        if tail == "device_put":
            return head in ("jax", "") or head.endswith("jax")
        if tail in ("array", "asarray"):
            return head in _JNP_MODULES
        return False

    def _offender(self, arg: ast.AST) -> Optional[str]:
        if isinstance(arg, ast.List):
            return "a Python list literal"
        if isinstance(arg, (ast.ListComp, ast.GeneratorExp)):
            return "a Python list comprehension"
        if isinstance(arg, ast.Call):
            if (isinstance(arg.func, ast.Attribute)
                    and arg.func.attr == "tolist"):
                return "a .tolist() result"
            if dotted(arg.func) == "list":
                return "a list(...) result"
            return None
        if isinstance(arg, ast.Subscript):
            sl = arg.slice
            parts = sl.elts if isinstance(sl, ast.Tuple) else [sl]
            for part in parts:
                if not isinstance(part, ast.Slice) or part.step is None:
                    continue
                step = part.step
                if (isinstance(step, ast.Constant)
                        and step.value in (1, None)):
                    continue
                return "a stepped (non-contiguous) slice"
        return None

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if not self._is_transfer(node.func):
                continue
            why = self._offender(node.args[0])
            if why:
                yield Finding(
                    self.id, ctx.path, node.lineno, node.col_offset,
                    f"device transfer of {why} forces a host "
                    "serialize/copy on the data path; build a "
                    "contiguous ndarray (np.asarray/ascontiguousarray) "
                    "once and transfer that",
                )


# -- JT14 ----------------------------------------------------------------------

@register
class FullSortForTopK(Rule):
    id = "JT14"
    name = "full-sort-for-topk"
    rationale = (
        "argsort(...)[...:k] / sort(...)[...:k] pays a FULL O(n log n) "
        "sort (and materializes the whole order) to keep k elements. "
        "On serving and ops paths n is the catalog — np.argpartition "
        "selects in O(n), and on device jax.lax.top_k is the fused "
        "MXU-friendly form (the whole index subsystem's exact path is "
        "built on it). The truncating slice is the tell: a full sort "
        "whose result is immediately cut down never needed the total "
        "order."
    )

    #: the hazard lives where per-query ranking happens; CLI/tooling
    #: glue ranking a dozen rows is not worth the noise
    def applies_to(self, abspath: str) -> bool:
        return ("/ops/" in abspath or "/models/" in abspath
                or "/serving/" in abspath or "/templates/" in abspath
                or "/index/" in abspath)

    _SORT_TAILS = {"argsort", "sort"}

    def _is_full_sort(self, func: ast.AST) -> bool:
        d = dotted(func)
        if not d:
            return False
        head, _, tail = d.rpartition(".")
        return (tail in self._SORT_TAILS
                and head in _NP_MODULES + _JNP_MODULES)

    @staticmethod
    def _truncating_slice(sub: ast.Subscript) -> bool:
        """A slice that keeps only part of the sorted axis: any Slice
        element with a start or stop ([:k], [-k:], [1:], [:, :k]).
        Pure step slices ([::-1], [::2]) reorder/stride the FULL
        result — not the top-k pattern."""
        sl = sub.slice
        parts = sl.elts if isinstance(sl, ast.Tuple) else [sl]
        for part in parts:
            if isinstance(part, ast.Slice) and (
                    part.lower is not None or part.upper is not None):
                return True
        return False

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Subscript):
                continue
            if not (isinstance(node.value, ast.Call)
                    and self._is_full_sort(node.value.func)):
                continue
            if not self._truncating_slice(node):
                continue
            d = dotted(node.value.func)
            yield Finding(
                self.id, ctx.path, node.lineno, node.col_offset,
                f"{d}(...) immediately truncated by a slice — a full "
                "O(n log n) sort for a top-k answer; use "
                "np.argpartition (host) or jax.lax.top_k (device) and "
                "sort only the k survivors",
            )


# -- JT15 ----------------------------------------------------------------------

@register
class NonMonotonicDurationClock(Rule):
    id = "JT15"
    name = "nonmonotonic-duration-clock"
    rationale = (
        "A duration or deadline measured as a difference of time.time() "
        "readings jumps with every NTP step/slew: watchdog windows "
        "mis-fire, cadence checks freeze (a backwards step makes "
        "`now - last < interval` true forever), drain deadlines expire "
        "instantly or never. Durations and deadlines belong on "
        "time.monotonic()/time.perf_counter(); time.time() is for "
        "TIMESTAMPS that leave the process (records, filenames, "
        "series). The tell is a SUBTRACTION whose operands are BOTH "
        "wall-clock-derived; timestamp arithmetic against a plain "
        "number (`now - window`) stays silent."
    )

    _WALL_CALLS = {"time.time", "time.time_ns"}
    #: value-preserving wrappers to look through: round(time.time(), 3)
    #: is as wall as time.time()
    _WRAPPERS = {"round", "min", "max", "float", "int", "abs"}

    def _is_wall_call(self, node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and dotted(node.func) in self._WALL_CALLS)

    def _derives_from_wall(self, node: ast.AST, tainted: Set[str]) -> bool:
        """Whether an expression's VALUE is a wall-clock reading:
        deliberately shape-restricted (names, arithmetic, conditionals,
        value-preserving wrappers) — a dict/list that merely CONTAINS a
        timestamp does not make every read through it a wall value."""
        if self._is_wall_call(node):
            return True
        if isinstance(node, (ast.Name, ast.Attribute)):
            d = dotted(node)
            return bool(d) and d in tainted
        if isinstance(node, ast.IfExp):
            return (self._derives_from_wall(node.body, tainted)
                    or self._derives_from_wall(node.orelse, tainted))
        if isinstance(node, ast.BinOp):
            return (self._derives_from_wall(node.left, tainted)
                    or self._derives_from_wall(node.right, tainted))
        if isinstance(node, ast.UnaryOp):
            return self._derives_from_wall(node.operand, tainted)
        if isinstance(node, ast.BoolOp):
            return any(self._derives_from_wall(v, tainted)
                       for v in node.values)
        if isinstance(node, ast.Call):
            fn = dotted(node.func).rsplit(".", 1)[-1]
            if fn in self._WRAPPERS:
                return any(self._derives_from_wall(a, tainted)
                           for a in node.args)
        return False

    def _tainted_names(self, tree: ast.AST) -> Set[str]:
        """Names/attribute chains ever assigned a value containing a
        time.time() read — file-local dataflow like JT03's taint, with
        a second pass so one name-to-name hop propagates
        (``now = time.time(); self._last = now``). A linter
        over-approximates (no reassignment clearing); suppress with a
        justification where the wall clock is the reviewed intent."""
        tainted: Set[str] = set()
        for _ in range(2):
            for node in ast.walk(tree):
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) and (
                        node.value is not None):
                    targets, value = [node.target], node.value
                else:
                    continue
                if self._derives_from_wall(value, tainted):
                    for tgt in targets:
                        d = dotted(tgt)
                        if d:
                            tainted.add(d)
        return tainted

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        tainted = self._tainted_names(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)):
                continue
            if self._derives_from_wall(node.left, tainted) and (
                    self._derives_from_wall(node.right, tainted)):
                yield Finding(
                    self.id, ctx.path, node.lineno, node.col_offset,
                    "duration/deadline computed as a difference of "
                    "wall-clock (time.time()) readings — an NTP "
                    "step/slew skews or freezes it; measure durations "
                    "with time.monotonic()/time.perf_counter() and "
                    "keep time.time() for exported timestamps",
                )


# -- JT16 ----------------------------------------------------------------------

def _is_device_transfer_call(func: ast.AST) -> bool:
    """``jax.device_put`` / ``jnp.array`` / ``jnp.asarray`` — the calls
    that place bytes on device (shared tell of JT13 and JT16)."""
    d = dotted(func)
    if not d:
        return False
    head, _, tail = d.rpartition(".")
    if tail == "device_put":
        return head in ("jax", "") or head.endswith("jax")
    if tail in ("array", "asarray"):
        return head in _JNP_MODULES
    return False


@register
class UnledgeredDeviceResidency(Rule):
    id = "JT16"
    name = "unledgered-device-residency"
    rationale = (
        "A jax.device_put / jnp.array / jnp.asarray result stored on a "
        "self.* attribute is a LONG-LIVED device allocation: it serves "
        "queries and owns HBM until the object dies. Unledgered, it is "
        "invisible to the device-memory accounting plane "
        "(obs/memacct.MemLedger) — per-model gauges under-report, "
        "headroom over-reports, and the OOM preflight approves deploys "
        "that cannot fit: a serving process OOMs with every gauge "
        "reading healthy. Pair the assignment with a "
        "MemLedger.register / *_register_mem call in the same scope "
        "(re-pricing under the same owner is idempotent), or justify "
        "the suppression."
    )

    #: the hazard lives where serving objects hold device tables;
    #: ops-layer trainers price themselves at a coarser seam and
    #: short-lived compute temporaries would be all noise
    def applies_to(self, abspath: str) -> bool:
        return ("/models/" in abspath or "/index/" in abspath
                or "/serving/" in abspath)

    @staticmethod
    def _contains_transfer(node: ast.AST) -> bool:
        return any(isinstance(n, ast.Call)
                   and _is_device_transfer_call(n.func)
                   for n in ast.walk(node))

    @staticmethod
    def _body_walk(fn: ast.AST) -> Iterator[ast.AST]:
        """Walk a function's OWN body — nested defs are their own
        scope (their register call cannot vouch for the outer one and
        vice versa)."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            body = list(self._body_walk(fn))
            # the pairing tell: any register-shaped call in the same
            # scope (memacct.LEDGER.register, self._register_mem, a
            # release/re-register helper) vouches for the residency
            has_register = any(
                isinstance(n, ast.Call)
                and "register" in dotted(n.func).lower()
                for n in body)
            if has_register:
                continue
            # one-hop local taint: `padded = jnp.asarray(...);
            # self._cache = padded` is the same residency spelled in
            # two statements (AnnAssign included — an annotation does
            # not launder the transfer)
            tainted: Set[str] = set()
            for node in body:
                if isinstance(node, ast.Assign):
                    t_targets, t_value = node.targets, node.value
                elif (isinstance(node, ast.AnnAssign)
                      and node.value is not None):
                    t_targets, t_value = [node.target], node.value
                else:
                    continue
                if self._contains_transfer(t_value):
                    for tgt in t_targets:
                        if isinstance(tgt, ast.Name):
                            tainted.add(tgt.id)
            for node in body:
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif (isinstance(node, ast.AnnAssign)
                      and node.value is not None):
                    targets, value = [node.target], node.value
                else:
                    continue
                # flatten tuple/list targets: `self._u, self._i = ...`
                # is two residency stores, not an exempt Tuple node
                flat = []
                for t in targets:
                    flat.extend(t.elts if isinstance(
                        t, (ast.Tuple, ast.List)) else [t])
                stores_on_self = any(
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                    for t in flat)
                if not stores_on_self:
                    continue
                resident = self._contains_transfer(value) or (
                    isinstance(value, ast.Name) and value.id in tainted)
                if resident:
                    yield Finding(
                        self.id, ctx.path, node.lineno, node.col_offset,
                        "device-transfer result stored on self.* with "
                        "no MemLedger.register in the same scope — a "
                        "long-lived allocation the memory ledger (and "
                        "the OOM preflight) cannot see; register a "
                        "Footprint (obs/memacct) beside it or justify "
                        "a suppression",
                    )


# -- JT17 ----------------------------------------------------------------------

@register
class UntracedIntraFleetCall(Rule):
    id = "JT17"
    name = "untraced-intra-fleet-call"
    rationale = (
        "An outbound HTTP request between fleet members that does not "
        "attach the trace headers (trace.TRACE_HEADER + "
        "X-PIO-Parent-Span, i.e. trace.traced_headers()) breaks the "
        "cross-process trace exactly at the hop an operator is trying "
        "to follow: the federation collector (obs/collect.py) stitches "
        "per-process span rings by propagated ids, and one untraced "
        "lane turns a stitched tree back into disconnected fragments. "
        "Every intra-fleet urlopen/Request/HTTPConnection site must "
        "attach the context (traced_headers is a no-op without an "
        "active trace, so probes and daemons stay cheap) or carry a "
        "justified suppression naming why the peer is not a fleet "
        "member."
    )

    #: request-construction call tails audited (the places headers go)
    _CONN_CTORS = {"HTTPConnection", "HTTPSConnection"}
    #: helper calls that attach the context for the site
    _MARKER_CALLS = {"traced_headers", "inject_headers"}
    #: manual-attach evidence: the header constants referenced directly
    _MARKER_NAMES = {"TRACE_HEADER", "PARENT_HEADER"}

    def applies_to(self, abspath: str) -> bool:
        # the layers that call other fleet members; tools/ (interactive
        # one-shot CLI) and tests are out of scope by design
        return any(frag in abspath for frag in (
            "/serving/", "/workflow/", "/obs/", "/resilience/",
            "/data/backends/"))

    @staticmethod
    def _enclosing_function(node: ast.AST, parents) -> Optional[ast.AST]:
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = parents.get(cur)
        return None

    def _has_marker(self, scope: ast.AST) -> bool:
        for sub in ast.walk(scope):
            if isinstance(sub, (ast.Name, ast.Attribute)):
                if dotted(sub).rsplit(".", 1)[-1] in self._MARKER_NAMES:
                    return True
            if isinstance(sub, ast.Call) and (
                    dotted(sub.func).rsplit(".", 1)[-1]
                    in self._MARKER_CALLS):
                return True
        return False

    @staticmethod
    def _call_assigned_names(scope: ast.AST) -> Set[str]:
        """Names assigned from a CALL result in ``scope`` — the
        ``req = Request(...)`` / ``req = self._build(...)`` shapes
        whose urlopen use defers to the construction site."""
        out: Set[str] = set()
        for sub in ast.walk(scope):
            value = None
            targets: List[ast.AST] = []
            if isinstance(sub, ast.Assign):
                value, targets = sub.value, sub.targets
            elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
                value, targets = sub.value, [sub.target]
            if not isinstance(value, ast.Call):
                continue
            for t in targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        return out

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        parents = _parent_map(ctx.tree)
        marker_cache: Dict[ast.AST, bool] = {}
        assigned_cache: Dict[ast.AST, Set[str]] = {}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            tail = dotted(node.func).rsplit(".", 1)[-1]
            if tail in self._CONN_CTORS or tail == "Request":
                pass
            elif tail == "urlopen":
                # urlopen(req) on a PREBUILT request object defers to
                # the construction site (where this rule already
                # looks): a bare attribute read, or a name assigned
                # from a call in the enclosing scope chain (closures
                # read outer names — the retrying-inner-attempt shape).
                # A URL STRING parked in a variable (`url = f"..."`)
                # is NOT prebuilt — flagging it is the point.
                arg0 = node.args[0] if node.args else None
                if isinstance(arg0, ast.Attribute):
                    continue
                if isinstance(arg0, ast.Name):
                    assigned = False
                    cur: Optional[ast.AST] = node
                    while cur is not None and not assigned:
                        cur = self._enclosing_function(cur, parents)
                        scope0 = cur if cur is not None else ctx.tree
                        if scope0 not in assigned_cache:
                            assigned_cache[scope0] = (
                                self._call_assigned_names(scope0))
                        assigned = arg0.id in assigned_cache[scope0]
                        if cur is None:
                            break
                    if assigned:
                        continue
            else:
                continue
            scope = self._enclosing_function(node, parents) or ctx.tree
            if scope not in marker_cache:
                marker_cache[scope] = self._has_marker(scope)
            if marker_cache[scope]:
                continue
            if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
                params = (scope.args.posonlyargs + scope.args.args
                          + scope.args.kwonlyargs)
                if any(a.arg == "headers" for a in params):
                    # the caller hands the headers in: propagation is
                    # the caller's duty (the router's pooled client)
                    continue
            yield Finding(
                self.id, ctx.path, node.lineno, node.col_offset,
                f"`{tail}` builds an intra-fleet request without the "
                "trace headers — wrap the headers in "
                "trace.traced_headers() (no-op without an active "
                "trace) so obs/collect.py can stitch the hop, or "
                "suppress with a justification naming why the peer is "
                "not a fleet member",
            )


# -- JT22 ----------------------------------------------------------------------

@register
class UnjournaledStateTransition(Rule):
    id = "JT22"
    name = "unjournaled-state-transition"
    rationale = (
        "A write to a breaker/canary/replica state attribute (the "
        "`state`/`_state` name-tail convention) IS an operational "
        "transition: a replica left rotation, a circuit opened, a "
        "canary verdict landed. Unjournaled, the transition exists "
        "only in process memory — `pio journal` cannot answer 'what "
        "changed before the regression', the anomaly sentinel "
        "(obs/anomaly.py) has nothing to attribute the change-point "
        "to, and the durable record (PIO_JOURNAL_PATH) misses the one "
        "event a post-mortem needs. Pair the write with a journal "
        "emit (obs/journal.emit or Journal.emit) in the same scope, "
        "or justify the suppression (e.g. a test-only reset that is "
        "not an operational transition)."
    )

    #: the hazard lives where operational state machines flip:
    #: the resilience layer (breakers, admission), the fleet
    #: supervisor and the streaming updater — elsewhere a `state`
    #: attribute is ordinary data, not an ops transition
    def applies_to(self, abspath: str) -> bool:
        norm = abspath.replace("\\", "/")
        return ("/resilience/" in norm
                or norm.endswith("/serving/fleet.py")
                or norm.endswith("/workflow/stream.py"))

    @staticmethod
    def _is_state_attr(node: ast.AST) -> bool:
        return (isinstance(node, ast.Attribute)
                and (node.attr == "state"
                     or node.attr.endswith("_state")))

    @staticmethod
    def _body_walk(fn: ast.AST) -> Iterator[ast.AST]:
        """Walk a function's OWN body — nested defs are their own
        scope (their journal call cannot vouch for the outer one and
        vice versa)."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name == "__init__":
                # construction is initialization, not a transition —
                # there is nothing to journal about an object being
                # born in its resting state (same stance as JT18)
                continue
            body = list(self._body_walk(fn))
            # the pairing tell: any journal-shaped call in the same
            # scope (journal.emit, JOURNAL.emit, self._journal.emit, a
            # note_* helper on the journal module) vouches for every
            # transition the scope performs — the emit carries the
            # scope's context, per-write pairing would be noise
            has_journal = any(
                isinstance(n, ast.Call)
                and "journal" in dotted(n.func).lower()
                for n in body)
            if has_journal:
                continue
            # one-hop local taint (JT16 discipline): a state attribute
            # read into a local and written back transformed
            # (`s = self._state; ...; self._state = next_of(s)`) is
            # still ONE transition — and a helper call that RECEIVES
            # the journal module/object as an argument vouches the
            # same way a direct emit does
            vouched_names: Set[str] = set()
            for node in body:
                if isinstance(node, ast.Assign):
                    if ("journal" in dotted(node.value).lower()
                            if isinstance(node.value, (ast.Attribute,
                                                       ast.Name))
                            else False):
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                vouched_names.add(tgt.id)
            if vouched_names and any(
                    isinstance(n, ast.Call)
                    and any(isinstance(a, ast.Name)
                            and a.id in vouched_names
                            for a in n.args)
                    for n in body):
                continue
            for node in body:
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                    targets = [node.target]
                else:
                    continue
                flat = []
                for t in targets:
                    flat.extend(t.elts if isinstance(
                        t, (ast.Tuple, ast.List)) else [t])
                if any(self._is_state_attr(t) for t in flat):
                    yield Finding(
                        self.id, ctx.path, node.lineno, node.col_offset,
                        "state-attribute write with no journal emit in "
                        "the same scope — an operational transition "
                        "the ops journal (obs/journal.py) cannot see; "
                        "emit a journal event beside it or justify a "
                        "suppression",
                    )


# -- JT23 ----------------------------------------------------------------------

@register
class UnboundedPerKeyDictGrowth(Rule):
    id = "JT23"
    name = "unbounded-per-key-dict-growth"
    rationale = (
        "A dict on `self` indexed by a request- or event-derived key "
        "(user/entity/item ids, trace ids — the JT11 taint "
        "vocabulary) grows one entry per distinct value: on a serving "
        "or observability path that is a slow memory leak sized by "
        "the traffic's key cardinality, and the process OOMs on "
        "exactly the workloads worth serving (a million-user Zipf "
        "stream). Track per-key state with a bounded sketch "
        "(obs/dataobs.py: count-min, space-saving, HLL, fixed-budget "
        "quantiles) or cap the table with explicit eviction and an "
        "`(other)` overflow row (the contprof endpoint-cap "
        "discipline); evidence of either in the same scope vouches "
        "the write."
    )

    #: the hazard lives where per-request/per-event keys flow:
    #: serving/ handles the traffic, obs/ accounts for it — elsewhere
    #: a keyed dict is ordinary data plumbing, not a traffic-sized
    #: table
    def applies_to(self, abspath: str) -> bool:
        norm = abspath.replace("\\", "/")
        return "/serving/" in norm or "/obs/" in norm

    #: JT11's taint vocabulary: identifier tails that are per-request
    #: by construction in this tree
    _TAINT = UnboundedMetricLabelCardinality()

    def _tainted(self, node: ast.AST) -> Optional[str]:
        """The request-derived identifier a dict KEY expression
        derives from, or None. A tuple key is tainted if any component
        is (``(app_id, entity_id)`` grows like entity_id does)."""
        if isinstance(node, ast.Tuple):
            for elt in node.elts:
                found = self._tainted(elt)
                if found:
                    return found
            return None
        return self._TAINT._suspect_name(node)

    @staticmethod
    def _is_self_dict(node: ast.AST) -> bool:
        """``self.<attr>[...]`` — the subscripted object is an
        attribute on self (a local alias is out of scope for a
        per-file rule; the attribute form is the idiom that leaks)."""
        return (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self")

    @staticmethod
    def _scope_has_bound(body: List[ast.AST]) -> bool:
        """Eviction/bound evidence that vouches every keyed write in
        the scope: a len() comparison (cap check), a .pop/.popitem/
        .clear/.popleft call, a del statement, an explicit `(other)`
        overflow row, or a call into an evict/compact/trim/prune
        helper."""
        for node in body:
            if isinstance(node, ast.Delete):
                return True
            if isinstance(node, ast.Compare):
                sides = [node.left] + list(node.comparators)
                if any(isinstance(s, ast.Call)
                       and dotted(s.func) == "len" for s in sides):
                    return True
            if isinstance(node, ast.Call):
                tail = dotted(node.func).rsplit(".", 1)[-1].lower()
                if tail in ("pop", "popitem", "clear", "popleft"):
                    return True
                if any(word in tail for word in
                       ("evict", "compact", "trim", "prune")):
                    return True
            if isinstance(node, ast.Constant) and node.value == "(other)":
                return True
        return False

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            body = list(UnjournaledStateTransition._body_walk(fn))
            writes: List[Tuple[ast.AST, str]] = []
            for node in body:
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AugAssign):
                    targets = [node.target]
                else:
                    if (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and node.func.attr == "setdefault"
                            and self._is_self_dict(node.func.value)
                            and node.args):
                        found = self._tainted(node.args[0])
                        if found:
                            writes.append((node, found))
                    continue
                for t in targets:
                    if (isinstance(t, ast.Subscript)
                            and self._is_self_dict(t.value)):
                        found = self._tainted(t.slice)
                        if found:
                            writes.append((t, found))
            if not writes:
                continue
            if self._scope_has_bound(body):
                continue
            for node, found in writes:
                yield Finding(
                    self.id, ctx.path, node.lineno, node.col_offset,
                    f"per-key dict write on self keyed by "
                    f"request-derived `{found}` with no bound or "
                    "eviction in scope — one entry per distinct key is "
                    "a traffic-sized leak; use a bounded sketch "
                    "(obs/dataobs.py) or cap the table with eviction "
                    "and an `(other)` overflow row",
                )
