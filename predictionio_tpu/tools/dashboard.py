"""Evaluation dashboard server.

Behavior contract from the reference (tools/.../dashboard/
Dashboard.scala:37-141): an HTML index of completed evaluation
instances (newest first) with per-instance result routes

  GET /                                                -> HTML listing
  GET /engine_instances/<id>/evaluator_results.txt     -> one-liner
  GET /engine_instances/<id>/evaluator_results.html    -> HTML report
  GET /engine_instances/<id>/evaluator_results.json    -> JSON report

plus CORS headers (ref: CorsSupport.scala), and — beyond the
reference — operator views of this process's diagnostics:

  GET /flight[?slow=1]  -> HTML table of the last recorded requests
                           (stage timings, trace ids; ?slow=1 keeps
                           only slow/errored ones). The JSON dump is
                           at /admin/flight like on every PIO server.
  GET /slo              -> HTML panel of the SLO burn-rate evaluation
                           (obs/slo.py) — per SLO, the burn in every
                           window and whether the fast/slow page is
                           firing. JSON at /admin/slo.
  GET /resilience       -> HTML panel of the resilience subsystem:
                           circuit breaker states, shed counters and
                           the active chaos rules of THIS process.
                           JSON at /admin/resilience.
  GET /timeline         -> HTML panel of the metric timelines
                           (obs/timeline.py): per-series sparklines of
                           MFU, model staleness, serving p50/p99 and
                           request rate, plus the data-path ledger's
                           per-run stage table. JSON at
                           /admin/timeline.
  GET /quality          -> HTML panel of the model-quality plane
                           (obs/quality.py): drift-vs-shadow-retrain
                           sparklines off the ``quality.*`` timeline
                           series, the latest replay comparison
                           report, and the canary verdict. JSON at
                           /admin/quality.
  GET /data             -> HTML panel of the data & ingest plane
                           (obs/dataobs.py): ingest rates, entity
                           heavy hitters + Zipf skew, cardinality,
                           quantile sketches, schema drift and the
                           unknown-entity coverage ratio. JSON at
                           /admin/data.
  GET /memory           -> HTML panel of the device-memory
                           accounting plane (obs/memacct.py):
                           headroom + basis, the per-model HBM
                           ledger, train peaks and the last OOM
                           preflight decision. JSON at /admin/memory.
  GET /trace[?id=...]   -> HTML view of the cross-process trace
                           stitcher (obs/collect.py): a lookup form +
                           this process's recently seen traces, and —
                           given an id — the stitched tree assembled
                           from the federation members, rendered by
                           the same ASCII renderer ``pio trace`` uses.
  GET /prof             -> HTML view of the continuous host profiler
                           (obs/contprof.py): the process flame tree
                           + hot frames via the same renderer
                           ``pio prof`` uses; ?slow=1 and ?endpoint=
                           slices. JSON at /admin/prof.
  GET /fleet            -> HTML panel of the serving fleet(s)
                           supervised IN THIS PROCESS
                           (serving/fleet.py ACTIVE registry —
                           `pio deploy --replicas` / threaded tests;
                           a remote fleet's JSON lives on its router
                           at /admin/fleet): per-replica state,
                           version, restarts, outstanding load, and
                           rolling-swap progress.
"""

from __future__ import annotations

import html
import json as _json
import logging
from typing import Optional
from urllib.parse import parse_qs, urlparse

from predictionio_tpu.data.storage import Storage, get_storage
from predictionio_tpu.obs import flight
from predictionio_tpu.obs import logging as obs_logging
from predictionio_tpu.serving.http import HTTPServerBase, JSONRequestHandler

log = logging.getLogger(__name__)

DEFAULT_PORT = 9000


class _DashboardRequestHandler(JSONRequestHandler):
    server_version = "PIODashboard/0.1"

    def _send_cors(self, status, body, content_type):
        # CORS on result routes (ref: CorsSupport.scala)
        self._send(status, body, content_type,
                   extra_headers={"Access-Control-Allow-Origin": "*"})

    def do_GET(self):
        url = urlparse(self.path)
        path = url.path
        storage: Storage = self.server_ref.storage
        if path == "/":
            self._send_cors(200, self.server_ref.index_html(),
                            "text/html; charset=UTF-8")
            return
        if path == "/flight":
            slow_only = (parse_qs(url.query).get("slow")
                         or ["0"])[0].lower() in ("1", "true")
            self._send_cors(200, self.server_ref.flight_html(slow_only),
                            "text/html; charset=UTF-8")
            return
        if path == "/slo":
            self._send_cors(200, self.server_ref.slo_html(),
                            "text/html; charset=UTF-8")
            return
        if path == "/resilience":
            self._send_cors(200, self.server_ref.resilience_html(),
                            "text/html; charset=UTF-8")
            return
        if path == "/timeline":
            self._send_cors(200, self.server_ref.timeline_html(),
                            "text/html; charset=UTF-8")
            return
        if path == "/fleet":
            self._send_cors(200, self.server_ref.fleet_html(),
                            "text/html; charset=UTF-8")
            return
        if path == "/quality":
            self._send_cors(200, self.server_ref.quality_html(),
                            "text/html; charset=UTF-8")
            return
        if path == "/data":
            self._send_cors(200, self.server_ref.data_html(),
                            "text/html; charset=UTF-8")
            return
        if path == "/trace":
            trace_id = (parse_qs(url.query).get("id") or [None])[0]
            self._send_cors(200, self.server_ref.trace_html(trace_id),
                            "text/html; charset=UTF-8")
            return
        if path == "/memory":
            self._send_cors(200, self.server_ref.memory_html(),
                            "text/html; charset=UTF-8")
            return
        if path == "/anomaly":
            self._send_cors(200, self.server_ref.anomaly_html(),
                            "text/html; charset=UTF-8")
            return
        if path == "/prof":
            params = parse_qs(url.query)
            slow = (params.get("slow") or ["0"])[0].lower() in ("1",
                                                                "true")
            endpoint = (params.get("endpoint") or [None])[0]
            self._send_cors(200,
                            self.server_ref.prof_html(endpoint, slow),
                            "text/html; charset=UTF-8")
            return
        parts = [p for p in path.split("/") if p]
        # path form: /engine_instances/<id>/evaluator_results.<fmt>
        if len(parts) == 3 and parts[0] == "engine_instances":
            instance = storage.evaluation_instances().get(parts[1])
            if instance is None:
                self._send(404, {"message": "Not Found"})
                return
            mapping = {
                "evaluator_results.txt": (instance.evaluator_results,
                                          "text/plain; charset=UTF-8"),
                "evaluator_results.html": (instance.evaluator_results_html,
                                           "text/html; charset=UTF-8"),
                "evaluator_results.json": (instance.evaluator_results_json,
                                           "application/json; charset=UTF-8"),
            }
            if parts[2] in mapping:
                body, ctype = mapping[parts[2]]
                self._send_cors(200, body, ctype)
                return
        self._send(404, {"message": "Not Found"})


class DashboardServer(HTTPServerBase):
    """ref: Dashboard.createDashboard (Dashboard.scala:58)."""

    def __init__(
        self,
        storage: Optional[Storage] = None,
        host: str = "0.0.0.0",
        port: int = DEFAULT_PORT,
    ):
        self.storage = storage or get_storage()
        super().__init__(host, port, _DashboardRequestHandler)

    def index_html(self) -> str:
        """Completed evaluations, newest first (ref: Dashboard.scala:76)."""
        instances = sorted(
            (
                i
                for i in self.storage.evaluation_instances().get_completed()
            ),
            key=lambda i: i.start_time,
            reverse=True,
        )
        rows = "\n".join(
            "<tr><td>{id}</td><td>{start}</td><td>{cls}</td><td>{batch}</td>"
            '<td><a href="/engine_instances/{id}/evaluator_results.html">HTML</a> '
            '<a href="/engine_instances/{id}/evaluator_results.json">JSON</a> '
            '<a href="/engine_instances/{id}/evaluator_results.txt">TXT</a></td></tr>'.format(
                id=html.escape(i.id),
                start=html.escape(i.start_time.isoformat()),
                cls=html.escape(i.evaluation_class),
                batch=html.escape(i.batch),
            )
            for i in instances
        )
        return (
            "<!DOCTYPE html><html><head><title>PredictionIO-TPU Dashboard"
            "</title></head><body><h1>Evaluation Instances</h1>"
            "<table border='1'><tr><th>ID</th><th>Started</th>"
            "<th>Evaluation</th><th>Batch</th><th>Results</th></tr>"
            f"{rows}</table>"
            '<p><a href="/flight">Flight recorder</a> · '
            '<a href="/flight?slow=1">slow/errored requests</a> · '
            '<a href="/admin/flight">JSON dump</a> · '
            '<a href="/slo">SLO burn rates</a> · '
            '<a href="/resilience">resilience</a> · '
            '<a href="/timeline">timelines</a> · '
            '<a href="/anomaly">anomaly sentinel</a> · '
            '<a href="/quality">model quality</a> · '
            '<a href="/data">data &amp; ingest</a> · '
            '<a href="/memory">device memory</a> · '
            '<a href="/trace">trace stitcher</a> · '
            '<a href="/prof">profiler flame</a> · '
            '<a href="/prof?slow=1">slow-cohort flame</a> · '
            '<a href="/fleet">fleet</a> · '
            '<a href="/metrics">metrics</a> · '
            '<a href="/readyz">readiness</a></p>'
            "</body></html>"
        )

    def flight_html(self, slow_only: bool = False) -> str:
        """The flight recorder as an operator table: one row per
        recorded request (newest first), stage breakdown inline — the
        slow-query view when ``slow_only``."""
        records = flight.RECORDER.records(slow_only=slow_only)
        rows = "\n".join(
            "<tr><td>{trace}</td><td>{server}</td><td>{method} {route}</td>"
            "<td>{status}</td><td>{dur:.1f}</td><td><code>{stages}</code>"
            "</td><td>{flags}</td></tr>".format(
                trace=html.escape(str(r.get("trace", ""))[:16]),
                server=html.escape(str(r.get("server", ""))),
                method=html.escape(str(r.get("method", ""))),
                route=html.escape(str(r.get("route", ""))),
                status=html.escape(str(r.get("status"))),
                dur=r.get("duration_ms", 0.0),
                stages=html.escape(_json.dumps(r.get("stages", {}))),
                flags=html.escape(
                    ("SLOW " if r.get("slow") else "")
                    + (f"ERROR: {r.get('error')}" if r.get("error") else "")),
            )
            for r in reversed(records)
        )
        title = "Slow / errored requests" if slow_only else "Flight recorder"
        return (
            "<!DOCTYPE html><html><head><title>{t}</title></head><body>"
            "<h1>{t}</h1><p>{n} record(s); slow threshold "
            "{ms:.0f} ms (PIO_SLOW_MS). <a href='/flight'>all</a> · "
            "<a href='/flight?slow=1'>slow only</a> · "
            "<a href='/admin/flight'>JSON</a></p>"
            "<table border='1'><tr><th>Trace</th><th>Server</th>"
            "<th>Request</th><th>Status</th><th>ms</th><th>Stages (ms)"
            "</th><th>Flags</th></tr>{rows}</table></body></html>"
        ).format(t=title, n=len(records), ms=flight.slow_threshold_ms(),
                 rows=rows)

    def slo_html(self) -> str:
        """The SLO evaluation as an operator panel: one row per SLO
        with its burn rate in every window, colored by alert state."""
        from predictionio_tpu.obs import slo as _slo

        report = _slo.MONITOR.report()
        window_labels: list = []
        for entry in report["slos"]:
            for label in entry["burn_rates"]:
                if label not in window_labels:
                    window_labels.append(label)
        header = "".join(f"<th>burn {html.escape(w)}</th>"
                         for w in window_labels)
        rows = []
        for entry in report["slos"]:
            color = {"firing": "#c0392b", "ok": "#27ae60"}.get(
                entry["state"], "#888")
            cells = "".join(
                "<td>{}</td>".format(
                    "–" if entry["burn_rates"].get(w) is None
                    else f"{entry['burn_rates'][w]:.2f}")
                for w in window_labels)
            objective = entry["objective"]
            target = f"{objective:.3%}"
            if entry.get("threshold_ms") is not None:
                target += f" &le; {entry['threshold_ms']:.0f} ms"
            rows.append(
                "<tr><td>{name}</td><td>{kind}</td><td>{target}</td>{cells}"
                '<td style="color:{color};font-weight:bold">{state}'
                "</td></tr>".format(
                    name=html.escape(entry["name"]),
                    kind=html.escape(entry["kind"]),
                    target=target, cells=cells, color=color,
                    state=html.escape(entry["state"])))
        return (
            "<!DOCTYPE html><html><head><title>SLO burn rates</title>"
            "</head><body><h1>SLO burn rates</h1>"
            "<p>Multi-window burn-rate alerting: the fast page needs "
            "burn &ge; 14.4 over both 5m and 1h; the slow page needs "
            "&ge; 6 over both 30m and 6h. "
            '<a href="/admin/slo">JSON</a> · <a href="/">index</a></p>'
            "<table border='1'><tr><th>SLO</th><th>Kind</th>"
            f"<th>Objective</th>{header}<th>State</th></tr>"
            f"{''.join(rows)}</table></body></html>"
        )


    def timeline_html(self) -> str:
        """The metric timelines as an operator panel: one row per
        tracked series with a unicode sparkline (the same renderer
        `pio top` uses) and the latest/min/max values, followed by the
        data-path ledger's per-run stage table and the staleness
        clock."""
        from predictionio_tpu.obs import perfacct
        from predictionio_tpu.obs.timeline import TIMELINE, sparkline

        TIMELINE.sample()  # watching the panel builds its history
        payload = TIMELINE.series()
        rows = []
        for name in sorted(payload["series"]):
            points = payload["series"][name]
            if not points:
                continue
            values = [p[1] for p in points]
            rows.append(
                "<tr><td>{name}</td><td><code>{spark}</code></td>"
                "<td>{last:.4g}</td><td>{lo:.4g}</td><td>{hi:.4g}</td>"
                "<td>{n}</td></tr>".format(
                    name=html.escape(name),
                    spark=html.escape(sparkline(values, 48)),
                    last=values[-1], lo=min(values), hi=max(values),
                    n=len(values)))
        series_rows = "".join(rows) or (
            "<tr><td colspan='6'>no samples yet — traffic or a train "
            "run feeds the timeline</td></tr>")
        datapath = perfacct.LEDGER.snapshot()
        run_rows = "".join(
            "<tr><td>{run}</td><td><code>{stages}</code></td></tr>".format(
                run=html.escape(str(r["run"])[:16]),
                stages=html.escape(" ".join(
                    f"{k}={v:.2f}s" for k, v in sorted(r["stages"].items()))
                    or "(no stages)"))
            for r in reversed(datapath["runs"])
        ) or "<tr><td colspan='2'>no training runs recorded</td></tr>"
        return (
            "<!DOCTYPE html><html><head><title>Metric timelines</title>"
            "</head><body><h1>Metric timelines</h1>"
            "<p>Cadence {interval:g}s, {cap} samples/series "
            "(PIO_TIMELINE_INTERVAL_SEC / PIO_TIMELINE_CAPACITY). "
            '<a href="/admin/timeline">JSON</a> · '
            '<a href="/admin/tail">tail attribution</a> · '
            '<a href="/">index</a></p>'
            "<table border='1'><tr><th>Series</th><th>Sparkline</th>"
            "<th>Last</th><th>Min</th><th>Max</th><th>Samples</th></tr>"
            "{series_rows}</table>"
            "<h2>Data-path ledger</h2>"
            "<p>Model staleness: {stale:.1f}s</p>"
            "<table border='1'><tr><th>Run</th><th>Stage seconds</th>"
            "</tr>{run_rows}</table>"
            "</body></html>"
        ).format(interval=payload["interval_sec"], cap=payload["capacity"],
                 series_rows=series_rows,
                 stale=datapath["staleness_seconds"], run_rows=run_rows)

    def anomaly_html(self) -> str:
        """The regression sentinel as an operator panel: active
        change-points with their causal journal attribution, each
        series' sparkline with the anomaly onset (^) and nearby
        journal events (|) marked under it, plus the journal tail."""
        from predictionio_tpu.obs import anomaly, journal
        from predictionio_tpu.obs.timeline import TIMELINE, sparkline

        report = anomaly.SENTINEL.scan()  # watching the panel scans
        payload = TIMELINE.series()
        events = journal.JOURNAL.recent(30)

        def marker_line(points, width, onset_ts, window) -> str:
            """A second code line under a sparkline: ``^`` at the
            anomaly onset sample, ``|`` at journal events that fall
            inside the attribution window around it."""
            if not points or len(points) < 2:
                return ""
            t0, t1 = points[0][0], points[-1][0]
            span = max(t1 - t0, 1e-9)

            def col(ts) -> int:
                return min(width - 1,
                           max(0, int((ts - t0) / span * (width - 1))))

            line = [" "] * width
            for event in events:
                ets = event.get("ts")
                if (isinstance(ets, (int, float)) and t0 <= ets <= t1
                        and event.get("kind") not in ("anomaly",
                                                      "anomaly_resolved")
                        and onset_ts is not None
                        and abs(ets - onset_ts) <= window):
                    line[col(ets)] = "|"
            if onset_ts is not None and t0 <= onset_ts <= t1:
                line[col(onset_ts)] = "^"
            return "".join(line).rstrip()

        window = report.get("window_sec", 30.0)
        active_rows = []
        for name, entry in sorted((report.get("active") or {}).items()):
            points = payload["series"].get(name) or []
            values = [p[1] for p in points]
            spark = sparkline(values, 48) if values else ""
            marks = marker_line(points, 48, entry.get("onset_ts"),
                                window)
            cause = entry.get("cause") or {}
            cause_text = (
                "{kind} ({gap:+.1f}s)".format(
                    kind=cause.get("kind", "?"),
                    gap=cause.get("gap_sec", 0.0))
                if cause else "(no journal event in window)")
            active_rows.append(
                "<tr><td>{name}</td><td>{mode}/{direction}</td>"
                "<td>{z:.1f}</td><td>{baseline:.4g} → {value:.4g}</td>"
                "<td>{cause}</td>"
                "<td><code>{spark}<br>{marks}</code></td></tr>".format(
                    name=html.escape(name),
                    mode=html.escape(str(entry.get("mode", "?"))),
                    direction=html.escape(str(entry.get("direction",
                                                        "?"))),
                    z=entry.get("z", 0.0),
                    baseline=entry.get("baseline", 0.0),
                    value=entry.get("recent", 0.0),
                    cause=html.escape(cause_text),
                    spark=html.escape(spark),
                    marks=html.escape(marks).replace(" ", "&nbsp;")))
        active_table = "".join(active_rows) or (
            "<tr><td colspan='6'>no active anomalies — the sentinel "
            "scans every timeline sample</td></tr>")
        resolved_rows = "".join(
            "<tr><td>{name}</td><td>{dur:.0f}s</td><td>{cause}</td>"
            "</tr>".format(
                name=html.escape(str(entry.get("series", "?"))),
                dur=entry.get("duration_sec", 0.0),
                cause=html.escape(str((entry.get("cause") or {}).get(
                    "kind", "-"))))
            for entry in reversed(report.get("recent_resolved") or [])
        ) or "<tr><td colspan='3'>none</td></tr>"
        journal_rows = "".join(
            "<tr><td>{ts:.1f}</td><td>{kind}</td><td><code>{rest}"
            "</code></td></tr>".format(
                ts=event.get("ts", 0.0),
                kind=html.escape(str(event.get("kind", "?"))),
                rest=html.escape(" ".join(
                    f"{k}={v}" for k, v in event.items()
                    if k not in ("ts", "mono", "kind"))))
            for event in reversed(events)
        ) or "<tr><td colspan='3'>journal is empty</td></tr>"
        return (
            "<!DOCTYPE html><html><head><title>Regression sentinel"
            "</title></head><body><h1>Regression sentinel</h1>"
            "<p>Change-point scan over the metric timelines on the "
            "snapshot cadence; onsets join the ops journal within "
            "{window:g}s (PIO_ANOMALY_WINDOW_SEC). Last scan "
            "{scan_ms:.2f}ms. "
            '<a href="/admin/anomaly">JSON</a> · '
            '<a href="/admin/journal">journal JSON</a> · '
            '<a href="/timeline">timelines</a> · '
            '<a href="/">index</a></p>'
            "<h2>Active</h2>"
            "<table border='1'><tr><th>Series</th><th>Mode</th>"
            "<th>z</th><th>Baseline → now</th><th>Attributed cause</th>"
            "<th>Sparkline (^ onset, | journal)</th></tr>"
            "{active_table}</table>"
            "<h2>Recently resolved</h2>"
            "<table border='1'><tr><th>Series</th><th>Duration</th>"
            "<th>Cause</th></tr>{resolved_rows}</table>"
            "<h2>Journal tail</h2>"
            "<table border='1'><tr><th>ts</th><th>Kind</th>"
            "<th>Fields</th></tr>{journal_rows}</table>"
            "</body></html>"
        ).format(window=window, scan_ms=report.get("scan_ms") or 0.0,
                 active_table=active_table, resolved_rows=resolved_rows,
                 journal_rows=journal_rows)

    def quality_html(self) -> str:
        """The model-quality plane as an operator panel: drift values
        + their timeline sparklines (the ``quality.*`` series the
        timeline samples off the gauges), the latest replay comparison
        report, and the canary verdict — every number read from
        obs/quality.py's one STATE, so this panel, ``pio canary`` and
        the gauges can never disagree."""
        from predictionio_tpu.obs import quality
        from predictionio_tpu.obs.timeline import TIMELINE, sparkline

        report = quality.STATE.report()
        TIMELINE.sample()
        series = TIMELINE.series()["series"]
        spark_rows = "".join(
            "<tr><td>{name}</td><td><code>{spark}</code></td>"
            "<td>{last:.4g}</td></tr>".format(
                name=html.escape(name),
                spark=html.escape(
                    sparkline([p[1] for p in series[name]], 48)),
                last=series[name][-1][1])
            for name in sorted(series)
            if name.startswith("quality.") and series[name])
        drift = report.get("drift")
        if drift:
            breached = drift.get("breached") or []
            verdict = ("<b style='color:#c0392b'>BREACHED: "
                       + html.escape(", ".join(breached)) + "</b>"
                       if breached else
                       "<b style='color:#27ae60'>inside band</b>")
            drift_html = (
                f"<p>shadow instance <code>"
                f"{html.escape(str(drift.get('shadow_instance'))[:16])}"
                f"</code>, band {report['band']:g} — {verdict}</p>"
                "<table border='1'><tr><th>recall_vs_retrain</th>"
                "<th>rmse_drift</th><th>factor_drift</th>"
                "<th>sampled users</th></tr>"
                f"<tr><td>{drift.get('recall_vs_retrain')}</td>"
                f"<td>{drift.get('rmse_drift')}</td>"
                f"<td>{drift.get('factor_drift')}</td>"
                f"<td>{drift.get('sampled_users')}</td></tr></table>")
        else:
            drift_html = ("<p>no drift probe yet — <code>pio stream"
                          "</code> against a trained instance feeds the "
                          "gauges.</p>")
        rep = report.get("replay")
        if rep:
            replay_html = (
                "<table border='1'><tr><th>queries</th><th>diffed</th>"
                "<th>mean overlap</th><th>worst overlap</th>"
                "<th>mean |score Δ|</th><th>errors</th></tr>"
                f"<tr><td>{rep.get('n')}</td><td>{rep.get('diffed')}</td>"
                f"<td>{rep.get('mean_overlap')}</td>"
                f"<td>{rep.get('worst_overlap')}</td>"
                f"<td>{rep.get('mean_score_delta')}</td>"
                f"<td>{html.escape(_json.dumps(rep.get('errors')))}</td>"
                "</tr></table>")
        else:
            replay_html = ("<p>no replay report yet — <code>pio replay"
                           "</code> registers one here.</p>")
        canary = report.get("canary")
        if canary:
            verdict = canary.get("verdict") or {}
            state = ("ACTIVE" if canary.get("active")
                     else canary.get("outcome") or "inactive")
            paired = canary.get("paired") or {}
            reasons = "".join(f"<li>{html.escape(r)}</li>"
                              for r in verdict.get("reasons") or [])
            canary_html = (
                f"<p>[{html.escape(state)}] replica <code>"
                f"{html.escape(str(canary.get('replica')))}</code>: "
                f"candidate <code>"
                f"{html.escape(str(canary.get('candidate_version'))[:16])}"
                "</code> vs baseline <code>"
                f"{html.escape(str(canary.get('baseline_version'))[:16])}"
                f"</code> — verdict <b>"
                f"{html.escape(str(verdict.get('verdict', '–')).upper())}"
                f"</b></p><p>paired samples: {paired.get('n')} "
                f"(errors {paired.get('errors')}), mean overlap "
                f"{paired.get('mean_overlap')}</p><ul>{reasons}</ul>")
        else:
            canary_html = ("<p>no canary — <code>pio canary --start"
                           "</code> (or <code>pio deploy --canary"
                           "</code> mode) runs one.</p>")
        return (
            "<!DOCTYPE html><html><head><title>Model quality</title>"
            "</head><body><h1>Model quality</h1>"
            "<h2>Drift vs shadow retrain</h2>"
            f"{drift_html}"
            "<table border='1'><tr><th>Series</th><th>Sparkline</th>"
            f"<th>Last</th></tr>{spark_rows}</table>"
            "<h2>Replay comparison</h2>"
            f"{replay_html}"
            "<h2>Canary</h2>"
            f"{canary_html}"
            '<p><a href="/admin/quality">JSON</a> · '
            '<a href="/">index</a></p></body></html>'
        )

    def data_html(self) -> str:
        """The data & ingest plane as an operator panel
        (obs/dataobs.py): ingest rates per (app, event), entity heavy
        hitters with the fitted Zipf skew, HLL cardinalities, the
        payload/value/inter-arrival quantiles, the live-vs-frozen
        schema diff and the unknown-entity coverage ratio — plus the
        ``data.*`` timeline sparklines."""
        from predictionio_tpu.obs import dataobs
        from predictionio_tpu.obs.timeline import TIMELINE, sparkline

        report = dataobs.DATAOBS.report()
        TIMELINE.sample()
        series = TIMELINE.series()["series"]
        spark_rows = "".join(
            "<tr><td>{name}</td><td><code>{spark}</code></td>"
            "<td>{last:.4g}</td></tr>".format(
                name=html.escape(name),
                spark=html.escape(
                    sparkline([p[1] for p in series[name]], 48)),
                last=series[name][-1][1])
            for name in sorted(series)
            if name.startswith("data.") and series[name])
        entities = report.get("entities") or {}
        breaches = [k for k, v in
                    (report.get("breach_active") or {}).items() if v]
        breach_html = (
            "<p><b style='color:#c0392b'>ACTIVE BREACH: "
            + html.escape(", ".join(sorted(breaches))) + "</b></p>"
            if breaches else "")
        rate_rows = "".join(
            f"<tr><td>{html.escape(str(r.get('app')))}</td>"
            f"<td>{html.escape(str(r.get('event')))}</td>"
            f"<td>{r.get('count')}</td></tr>"
            for r in (report.get("rates") or [])[:20])
        hot_rows = "".join(
            f"<tr><td><code>{html.escape(str(r.get('id')))}</code></td>"
            f"<td>{r.get('count')}</td><td>±{r.get('err')}</td></tr>"
            for r in entities.get("top") or [])
        card = entities.get("cardinality") or {}
        quant = report.get("quantiles") or {}
        quant_rows = "".join(
            f"<tr><td>{html.escape(name)}</td><td>{s.get('p50')}</td>"
            f"<td>{s.get('p90')}</td><td>{s.get('p99')}</td>"
            f"<td>{s.get('n')}</td></tr>"
            for name, s in sorted(quant.items()) if s and s.get("n"))
        schema = report.get("schema") or {}
        change_rows = "".join(
            f"<tr><td>{html.escape(str(c.get('event')))}</td>"
            f"<td>{html.escape(str(c.get('field')))}</td>"
            f"<td>{html.escape(str(c.get('change')))}</td>"
            f"<td>{html.escape(str(c.get('old_type') or '–'))}</td>"
            f"<td>{html.escape(str(c.get('new_type') or '–'))}</td></tr>"
            for c in (schema.get("changes") or [])[-20:])
        frozen = (f"frozen at instance <code>"
                  f"{html.escape(str(schema.get('frozen_instance'))[:16])}"
                  "</code>" if schema.get("frozen_instance")
                  else "no frozen profile yet (a COMPLETED train "
                       "freezes one)")
        return (
            "<!DOCTYPE html><html><head><title>Data plane</title>"
            "</head><body><h1>Data &amp; ingest</h1>"
            f"{breach_html}"
            f"<p>events {report.get('events_total')} "
            f"({report.get('eps')}/s), tail "
            f"{report.get('tail_events_total')}, bytes "
            f"{report.get('bytes_total')} — entity skew "
            f"<b>{entities.get('skew')}</b>, unknown-entity ratio "
            f"<b>{report.get('unknown_ratio')}</b> over "
            f"{report.get('queries_seen')} query refs; cardinality "
            + " ".join(f"{html.escape(k)}={v}"
                       for k, v in sorted(card.items()))
            + "</p>"
            "<table border='1'><tr><th>Series</th><th>Sparkline</th>"
            f"<th>Last</th></tr>{spark_rows}</table>"
            "<h2>Rates</h2><table border='1'><tr><th>app</th>"
            f"<th>event</th><th>count</th></tr>{rate_rows}</table>"
            "<h2>Hot entities</h2><table border='1'><tr><th>id</th>"
            f"<th>count</th><th>err</th></tr>{hot_rows}</table>"
            "<h2>Quantiles</h2><table border='1'><tr><th>sketch</th>"
            "<th>p50</th><th>p90</th><th>p99</th><th>n</th></tr>"
            f"{quant_rows}</table>"
            f"<h2>Schema drift</h2><p>{frozen}</p>"
            "<table border='1'><tr><th>event</th><th>field</th>"
            "<th>change</th><th>old</th><th>new</th></tr>"
            f"{change_rows}</table>"
            '<p><a href="/admin/data">JSON</a> · '
            '<a href="/">index</a></p></body></html>'
        )

    def trace_html(self, trace_id: Optional[str] = None) -> str:
        """The cross-process trace view (obs/collect.py): without an
        id, a lookup form plus the traces recently seen by THIS
        process's ring; with ``?id=``, the stitched tree fan-out over
        the federation members (this process, ACTIVE fleets,
        PIO_OBS_MEMBERS) rendered through the SAME ASCII renderer
        ``pio trace`` uses — one renderer, no drift."""
        from predictionio_tpu.obs import collect, trace as _trace

        form = (
            '<form method="get" action="/trace">'
            '<input name="id" size="40" placeholder="trace id '
            '(X-PIO-Trace-Id)" value="{}"/> '
            "<button>stitch</button></form>"
        ).format(html.escape(trace_id or ""))
        if trace_id and _trace.valid_trace_id(trace_id):
            doc = collect.stitch_trace(trace_id,
                                       collect.default_members())
            body = ("<pre>"
                    + html.escape(collect.format_trace_tree(doc))
                    + "</pre>")
        elif trace_id:
            body = "<p>that is not an id-shaped trace id.</p>"
        else:
            recent: dict = {}
            for record in _trace.recent_spans():
                entry = recent.setdefault(
                    record["trace"], {"spans": 0, "names": set()})
                entry["spans"] += 1
                entry["names"].add(record["name"])
            rows = "".join(
                '<tr><td><a href="/trace?id={t}"><code>{t}</code></a>'
                "</td><td>{n}</td><td><code>{names}</code></td></tr>"
                .format(t=html.escape(t), n=entry["spans"],
                        names=html.escape(", ".join(
                            sorted(entry["names"])[:6])))
                for t, entry in list(recent.items())[-20:][::-1]
            ) or ("<tr><td colspan='3'>no spans in this process's "
                  "ring yet</td></tr>")
            body = ("<table border='1'><tr><th>Trace</th><th>Spans "
                    "here</th><th>Span names</th></tr>" + rows
                    + "</table>")
        return (
            "<!DOCTYPE html><html><head><title>Trace</title></head>"
            "<body><h1>Cross-process trace</h1>"
            f"{form}{body}"
            '<p><a href="/admin/trace">JSON (?id=)</a> · '
            '<a href="/">index</a></p></body></html>'
        )

    def memory_html(self) -> str:
        """The device-memory accounting plane (obs/memacct.py) as an
        operator panel: capacity/headroom with their basis, a
        ``mem.headroom`` timeline sparkline, the per-model component
        ledger, train peaks and the last OOM-preflight decision —
        every number read from memacct's one report, so this panel,
        ``pio mem`` and ``GET /admin/memory`` can never disagree."""
        import html as _html

        from predictionio_tpu.obs import memacct
        from predictionio_tpu.obs.timeline import TIMELINE, sparkline

        report = memacct.report()

        def esc(v) -> str:
            return _html.escape(str(v))

        model_rows = []
        for model in sorted(report.get("models") or {}):
            block = report["models"][model]
            components = ", ".join(
                f"{name}: {nbytes:,} B" for name, nbytes in
                sorted(block["components"].items()))
            model_rows.append(
                f"<tr><td>{esc(model)}</td>"
                f"<td>{block['total_bytes']:,} B</td>"
                f"<td>{esc(components)}</td></tr>")
        peak_rows = [
            f"<tr><td>{esc(model)}</td><td>{peak['bytes']:,} B</td>"
            f"<td>{esc(peak['source'])}</td></tr>"
            for model, peak in sorted(
                (report.get("train_peaks") or {}).items())]
        series = (TIMELINE.series().get("series") or {}).get(
            "mem.headroom") or []
        spark = sparkline([p[1] for p in series], 40)
        pre = report.get("preflight") or {}
        last = pre.get("last")

        def bytes_or_dash(v) -> str:
            # an unknown_size decision stores estimated_bytes=None —
            # render '-' like `pio mem`, never the Python None repr
            return "-" if v is None else f"{int(v):,} B"

        last_line = ("no preflight decision yet" if not last else
                     f"last: {esc(last.get('result'))} for instance "
                     f"{esc(last.get('instance'))} (estimated "
                     f"{bytes_or_dash(last.get('estimated_bytes'))} vs "
                     f"headroom "
                     f"{bytes_or_dash(last.get('headroom_bytes'))})")
        return (
            "<!DOCTYPE html><html><head><title>Device memory</title>"
            "</head><body><h1>Device memory</h1>"
            f"<p>Basis <b>{esc(report['basis'])}</b>: "
            f"{report['in_use_bytes']:,} B in use of "
            f"{report['capacity_bytes']:,} B — headroom "
            f"<b>{report['headroom_bytes']:,} B</b> (floor "
            f"{report['headroom_floor_fraction']:.0%} of capacity; "
            "PIO_PEAK_HBM_BYTES / PIO_MEM_HEADROOM_FLOOR).</p>"
            f"<p>headroom <code>{esc(spark) or '(no samples yet)'}"
            "</code></p>"
            "<h2>Per-model ledger</h2>"
            "<table border='1'><tr><th>Model</th><th>Total</th>"
            "<th>Components</th></tr>"
            f"{''.join(model_rows) or '<tr><td colspan=3>(empty)</td></tr>'}"
            "</table>"
            "<h2>Train peaks</h2>"
            "<table border='1'><tr><th>Model</th><th>Peak bytes</th>"
            "<th>Basis</th></tr>"
            f"{''.join(peak_rows) or '<tr><td colspan=3>(none)</td></tr>'}"
            "</table>"
            "<h2>OOM preflight</h2>"
            f"<p>{'enabled' if pre.get('enabled') else 'DISABLED'} "
            f"(estimate scale x{pre.get('estimate_scale')}); "
            f"{last_line}</p>"
            '<p><a href="/admin/memory">JSON</a> · '
            '<a href="/">index</a></p></body></html>'
        )

    def prof_html(self, endpoint: Optional[str] = None,
                  slow: bool = False) -> str:
        """The continuous profiler's flame (obs/contprof.py) rendered
        through the SAME ASCII renderer ``pio prof`` uses — one
        renderer, every surface. ``?slow=1`` shows the above-PIO_SLOW_MS
        tail cohort, ``?endpoint=`` one route's slice."""
        from urllib.parse import quote

        from predictionio_tpu.obs import contprof

        payload = contprof.snapshot(endpoint=endpoint, slow=slow)
        flame = contprof.format_flame(payload)
        slices = [
            '<a href="/prof">all</a>',
            '<a href="/prof?slow=1">slow cohort</a>',
        ]
        for ep in payload.get("endpoints") or []:
            slices.append(
                '<a href="/prof?endpoint={}"><code>{}</code></a>'.format(
                    quote(ep, safe=""), html.escape(ep)))
        return (
            "<!DOCTYPE html><html><head><title>Continuous profile"
            "</title></head><body><h1>Continuous profile"
            f" [{html.escape(str(payload.get('slice')))}]</h1>"
            f"<p>slices: {' · '.join(slices)}</p>"
            f"<pre>{html.escape(flame)}</pre>"
            '<p><a href="/admin/prof">JSON</a> · '
            '<a href="/admin/prof?format=collapsed">collapsed</a> · '
            '<a href="/">index</a></p></body></html>'
        )

    def fleet_html(self) -> str:
        """The serving fleet(s) supervised in THIS process as an
        operator panel: one table per supervisor — replica state
        (colored), version, restarts, outstanding router requests —
        plus the last rolling-swap verdict. A fleet running in another
        process is one `pio fleet --url <router>` away."""
        from predictionio_tpu.serving import fleet as _fleet

        color = {"ready": "#27ae60", "starting": "#e67e22",
                 "evicted": "#e67e22", "draining": "#2980b9",
                 "dead": "#c0392b", "stopped": "#888"}
        sections = []
        for i, supervisor in enumerate(list(_fleet.ACTIVE)):
            snap = supervisor.snapshot()
            rows = "".join(
                '<tr><td>{name}</td><td style="color:{c};'
                'font-weight:bold">{state}</td><td>{port}</td>'
                "<td><code>{version}</code></td><td>{restarts}</td>"
                "<td>{outstanding}</td></tr>".format(
                    name=html.escape(r["name"]),
                    c=color.get(r["state"], "#888"),
                    state=html.escape(r["state"]),
                    port=r["port"] or "–",
                    version=html.escape(str(r["version"] or "–")[:16]),
                    restarts=r["restarts"],
                    outstanding=r["outstanding"])
                for r in snap["replicas"])
            swap_line = _fleet.format_swap(snap["swap"])
            sections.append(
                f"<h2>Fleet {i}: {snap['ready']}/{snap['size']} ready, "
                f"version <code>"
                f"{html.escape(str(snap['version'] or 'mixed/none'))}"
                "</code></h2>"
                "<table border='1'><tr><th>Replica</th><th>State</th>"
                "<th>Port</th><th>Version</th><th>Restarts</th>"
                f"<th>Outstanding</th></tr>{rows}</table>"
                f"<p>{html.escape(swap_line)}</p>")
        body = "".join(sections) or (
            "<p>No fleet supervised in this process — "
            "<code>pio deploy --replicas N</code> runs one, and a "
            "remote fleet answers <code>pio fleet --url "
            "&lt;router&gt;</code>.</p>")
        return (
            "<!DOCTYPE html><html><head><title>Serving fleet</title>"
            "</head><body><h1>Serving fleet</h1>"
            f"{body}"
            '<p><a href="/admin/fleet">JSON (on the router)</a> · '
            '<a href="/">index</a></p></body></html>'
        )

    def resilience_html(self) -> str:
        """Breaker states, shed counters and chaos rules of THIS
        process (each serving process owns its breakers — fleet views
        scrape ``pio_circuit_state`` instead)."""
        from predictionio_tpu.obs import metrics as _metrics
        from predictionio_tpu.resilience import chaos as _chaos
        from predictionio_tpu.resilience import policy as _policy

        color = {"closed": "#27ae60", "half_open": "#e67e22",
                 "open": "#c0392b"}
        circuit_rows = "".join(
            '<tr><td>{t}</td><td style="color:{c};font-weight:bold">{s}'
            "</td><td>{f}/{th}</td><td>{r:.0f}s</td></tr>".format(
                t=html.escape(b["target"]),
                c=color.get(b["state"], "#888"),
                s=html.escape(b["state"]),
                f=b["consecutive_failures"], th=b["failure_threshold"],
                r=b["reset_timeout_sec"])
            for b in _policy.breakers_snapshot()
        ) or "<tr><td colspan='4'>no circuits yet</td></tr>"
        shed_family = _metrics.REGISTRY.get("pio_shed_total")
        shed_rows = ""
        if shed_family is not None:
            shed_rows = "".join(
                f"<tr><td>{html.escape('/'.join(values))}</td>"
                f"<td>{int(child.value)}</td></tr>"
                for values, child in shed_family.children())
        shed_rows = shed_rows or ("<tr><td colspan='2'>nothing shed"
                                  "</td></tr>")
        state = _chaos.describe()
        chaos_line = (html.escape(state["spec"]) if state["enabled"]
                      else "inactive")
        return (
            "<!DOCTYPE html><html><head><title>Resilience</title></head>"
            "<body><h1>Resilience</h1>"
            "<h2>Circuit breakers</h2>"
            "<table border='1'><tr><th>Target</th><th>State</th>"
            "<th>Failures</th><th>Reset</th></tr>"
            f"{circuit_rows}</table>"
            "<h2>Admission control (shed counters)</h2>"
            "<table border='1'><tr><th>server/reason</th><th>shed</th>"
            f"</tr>{shed_rows}</table>"
            f"<h2>Chaos</h2><p><code>{chaos_line}</code> — toggle via "
            "<code>pio chaos --url ... --set SPEC</code> or "
            "<code>POST /admin/chaos</code>.</p>"
            '<p><a href="/admin/resilience">JSON</a> · '
            '<a href="/">index</a></p>'
            "</body></html>"
        )


def main(argv=None) -> None:
    import argparse

    parser = argparse.ArgumentParser(description="PIO-TPU dashboard")
    parser.add_argument("--ip", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=DEFAULT_PORT)
    args = parser.parse_args(argv)
    obs_logging.setup(level=logging.INFO)
    server = DashboardServer(host=args.ip, port=args.port)
    log.info("dashboard running on %s:%s", args.ip, server.port)
    server.serve_forever()


if __name__ == "__main__":
    main()
