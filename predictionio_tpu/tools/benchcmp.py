"""`pio bench-compare`: per-metric deltas across the bench trajectory.

The driver leaves one ``BENCH_r<NN>.json`` per round (the bench.py
headline record under ``parsed``: a named metric + a ``detail`` object
of numeric evidence). Regressions hide in that trajectory — a step-time
number drifting 15% over three rounds never trips any single run's
gate. This tool makes the drift visible at review time: it loads every
round, extracts the numeric metrics, and compares the newest round
against a baseline (the previous round by default), printing per-metric
deltas and exiting non-zero when any metric regressed beyond the
tolerance band.

Direction is inferred from the metric name: latency/time-shaped metrics
(``*_ms``, ``*_sec``, ``*latency*``) regress by going UP, everything
else (throughput, QPS, rates, MFU) regresses by going DOWN. Deltas
within the tolerance band (default 10%) are noise, beyond it they are
verdicts: REGRESSION (exit 1) or IMPROVED (exit 0, still printed —
an unexplained improvement is worth a look too).
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import re
from typing import Dict, List, Optional, Tuple

#: rate-shaped fragments where HIGHER is better — checked first so
#: ``*_per_sec_per_chip`` is not misread as a duration,
#: ``retrieval_qps_recall95`` is not misread via nothing at all, and
#: ``quality_recall_vs_retrain`` / replay ``overlap`` read as quality
#: floors (a drop IS the regression)
_HIGHER_BETTER = re.compile(r"(per_sec|_qps|qps$|throughput|mfu|"
                            r"_per_chip|hit|recall|overlap)")
#: metric-name fragments where a LOWER value is better —
#: ``canary_verdict_ms`` rides the ``_ms$`` tail, drift gauges the
#: ``drift`` fragment, and the device-memory plane's
#: ``model_hbm_bytes`` / ``train_peak_bytes`` the anchored ``_bytes$``
#: tail (resident bytes growing IS the regression the memacct keys
#: gate; the anchor stays — a bare ``bytes`` fragment would flip
#: direction on any future metric merely containing the word). The
#: ``overhead`` fragment gates the continuous profiler's cost
#: (``prof_overhead_pct``): the sampler rides every serving process,
#: so its growth taxes every request. The ``_us`` tails gate the
#: sentinel stage's ``journal_append_us`` — the journal emit rides
#: every breaker flip and canary verdict on the serving path, so
#: microsecond creep there is a real regression
_LOWER_BETTER = re.compile(r"(_ms$|_ms_|_us$|_us_|_sec$|_sec_|_seconds|"
                           r"latency|_bytes$|p50|p99|debt|rmse|drift|"
                           r"overhead)")

#: detail keys that are run configuration, not performance — a change
#: is reported as CONFIG-CHANGED (never a regression verdict: comparing
#: perf across different configs is the reader's call)
_CONFIG_KEYS = re.compile(r"^(n_|rank$|iterations$|epochs$|seed$|"
                          r"max_|batch)")


@dataclasses.dataclass
class Delta:
    metric: str
    base: float
    new: float
    pct: Optional[float]          # None when base == 0
    verdict: str                  # ok | regression | improved | config-changed

    def line(self) -> str:
        pct = "n/a" if self.pct is None else f"{self.pct:+.1f}%"
        return (f"{self.metric}: {self.base:g} -> {self.new:g} ({pct}) "
                f"{self.verdict.upper()}")


def load_metrics(path: str) -> Dict[str, float]:
    """The numeric metrics of one bench round: the headline
    ``{metric, value}`` pair plus every numeric scalar under
    ``parsed.detail`` (as ``detail.<key>``) and ``parsed.key`` (as
    ``key.<name>`` — the compact headline block real rounds carry, so
    ``twotower_mfu``, the serve percentiles and the data-path seconds
    all sit in the direction-aware gate set)."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    parsed = doc.get("parsed") or {}
    out: Dict[str, float] = {}
    name = parsed.get("metric")
    value = parsed.get("value")
    if name and isinstance(value, (int, float)) and not isinstance(
            value, bool):
        out[str(name)] = float(value)
    for block, prefix in ((parsed.get("detail"), "detail"),
                          (parsed.get("key"), "key")):
        if isinstance(block, dict):
            for key, v in block.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    out[f"{prefix}.{key}"] = float(v)
    return out


def lower_is_better(metric: str) -> bool:
    if _HIGHER_BETTER.search(metric):
        return False
    return bool(_LOWER_BETTER.search(metric))


def is_config_key(metric: str) -> bool:
    leaf = metric.rsplit(".", 1)[-1]
    return bool(_CONFIG_KEYS.match(leaf))


def compare(base: Dict[str, float], new: Dict[str, float],
            tolerance_pct: float) -> List[Delta]:
    """Deltas for every metric present in BOTH rounds, worst first."""
    deltas: List[Delta] = []
    for metric in sorted(set(base) & set(new)):
        b, n = base[metric], new[metric]
        pct = None if b == 0 else (n - b) / abs(b) * 100.0
        if is_config_key(metric):
            verdict = "ok" if b == n else "config-changed"
        elif pct is None:
            verdict = "ok" if n == 0 else "config-changed"
        elif abs(pct) <= tolerance_pct:
            verdict = "ok"
        else:
            worse = pct > 0 if lower_is_better(metric) else pct < 0
            verdict = "regression" if worse else "improved"
        deltas.append(Delta(metric, b, n, pct, verdict))
    rank = {"regression": 0, "config-changed": 1, "improved": 2, "ok": 3}
    deltas.sort(key=lambda d: (rank[d.verdict],
                               -(abs(d.pct) if d.pct is not None else 0.0)))
    return deltas


def default_files(directory: str = ".") -> List[str]:
    return sorted(glob.glob(os.path.join(directory, "BENCH_r*.json")))


def run(files: List[str], tolerance_pct: float = 10.0,
        against: str = "prev", out=None) -> int:
    """Compare the newest round against the baseline; print the deltas;
    exit 1 on any REGRESSION beyond tolerance."""
    import sys

    out = out if out is not None else sys.stdout
    files = [f for f in files if os.path.isfile(f)]
    # a round whose headline failed to parse (empty ``parsed``) holds
    # no metrics — skip it when picking newest/baseline instead of
    # reporting a useless "no common metrics" against it
    rounds = [(f, load_metrics(f)) for f in files]
    skipped = [f for f, m in rounds if not m]
    for f in skipped:
        print(f"bench-compare: {os.path.basename(f)} has no extractable "
              "metrics; skipping", file=out)
    rounds = [(f, m) for f, m in rounds if m]
    if len(rounds) < 2:
        print("bench-compare: need at least two bench files with "
              f"extractable metrics (got {len(rounds)})", file=out)
        return 2
    newest, new_metrics = rounds[-1]
    baseline, base_metrics = (rounds[0] if against == "first"
                              else rounds[-2])
    common = set(base_metrics) & set(new_metrics)
    if not common:
        print(f"bench-compare: no common metrics between "
              f"{os.path.basename(baseline)} and "
              f"{os.path.basename(newest)}", file=out)
        return 2
    print(f"bench-compare: {os.path.basename(newest)} vs "
          f"{os.path.basename(baseline)} "
          f"(tolerance ±{tolerance_pct:g}%)", file=out)
    deltas = compare(base_metrics, new_metrics, tolerance_pct)
    regressions = 0
    for d in deltas:
        if d.verdict != "ok":
            print("  " + d.line(), file=out)
            regressions += d.verdict == "regression"
    within = sum(1 for d in deltas if d.verdict == "ok")
    print(f"  ({within} metric(s) within tolerance)", file=out)
    if regressions:
        print(f"bench-compare: {regressions} regression(s) beyond "
              f"{tolerance_pct:g}%", file=out)
        return 1
    print("bench-compare: no regressions beyond tolerance", file=out)
    return 0
